"""Dispatcher: the service's item scheduler and liveness tracker.

Runs as a single thread that owns the ROUTER socket (ZMQ sockets are not
thread-safe; every socket operation happens here). Other threads interact
through three thread-safe surfaces only: :meth:`submit` (the ventilator
hands in work items), the ``deliver`` callback (results flow out to the
:class:`~petastorm_tpu.service.service_pool.ServicePool`'s bounded queue),
and :meth:`stats` (gauges).

Scheduling is credit-based: each live, READY worker server holds at most
``max_inflight_per_worker`` assigned items, so a slow worker never hoards
the queue and back-pressure composes with the ventilator's own in-flight
bound.

Fault tolerance — the exactly-once core:

* Every ventilated item gets a monotonically increasing id; ownership
  (``item id -> worker identity``) is recorded at assignment.
* A worker whose heartbeat lapses past ``liveness_timeout_s`` is
  deregistered and its in-flight items go back to the FRONT of the pending
  queue (**re-ventilation**) for reassignment.
* Completions are deduplicated by item id: a lapsed-but-actually-alive
  worker (GC pause, network stall) racing its replacement can produce two
  DONEs for one item — the first wins and is delivered, the second is
  dropped. Worker servers buffer an item's results and send them in a
  single DONE, so a worker killed mid-item has delivered nothing for it
  and the re-run is not a duplicate. Together: every item's row set reaches
  the consumer exactly once.

Failure-domain hardening (docs/service.md, "Failure semantics"):

* **Retry budgets**: every failed attempt of an item — a worker ERROR or
  a heartbeat-lapse re-ventilation — counts against the item's budget
  (``PETASTORM_TPU_SERVICE_MAX_RETRIES`` total attempts). Failed items
  re-enter the queue after an exponential, deterministically-jittered
  backoff instead of immediately, so a deterministic crasher cannot
  hot-loop the fleet.
* **Suspect isolation**: an item with a failed attempt behind it is only
  ever assigned ALONE to an idle worker. A poisoned row-group therefore
  burns exactly one worker per attempt and never drags co-assigned
  innocent items' budgets down with it.
* **Poison quarantine**: an item that exhausts its budget is quarantined
  — skipped with a ``('poisoned', info)`` delivery (the pool applies the
  reader's ``poison_policy``), recorded on :meth:`health` (the /health
  endpoint), counted, and announced as a ``row_group_poisoned`` anomaly
  event — instead of crash-looping the fleet forever.
* **Incarnation token**: SPEC replies and heartbeat ACKs carry this
  dispatcher's random token; a worker that suddenly sees a different
  token knows its dispatcher was replaced (client restart on the same
  endpoint) and re-registers instead of decoding for a job spec the new
  dispatcher never sent it.
"""

import collections
import heapq
import logging
import threading
import time
import uuid

from petastorm_tpu import faults
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.telemetry import (
    count_swallowed, get_registry, knobs, merge_worker_delta,
    metrics_disabled, note_producer_wait, tracing,
)
from petastorm_tpu.telemetry.timeseries import record_anomaly

logger = logging.getLogger(__name__)

_POLL_INTERVAL_MS = 50
_STOP_BROADCASTS = 3

#: quarantined-item descriptors retained for /health (count is unbounded,
#: the descriptor list is not — an operator needs the recent offenders,
#: not an ever-growing ledger in a long-lived daemon)
_POISONED_KEEP = 100

# Fleet-health metric names (docs/telemetry.md): the dispatcher runs in
# the CONSUMER process, so these land straight in its process-wide
# registry and surface through pipeline_report()'s `service` section —
# re-ventilation/dedupe activity visible without reading dispatcher logs.
SERVICE_REVENTILATED = 'petastorm_tpu_service_reventilated_total'
SERVICE_DUPLICATE_DONE = 'petastorm_tpu_service_duplicate_done_total'
SERVICE_WORKERS_ALIVE = 'petastorm_tpu_service_workers_alive'
SERVICE_WORKERS_REGISTERED = 'petastorm_tpu_service_workers_registered'
SERVICE_ITEMS_PENDING = 'petastorm_tpu_service_items_pending'
SERVICE_ITEMS_ASSIGNED = 'petastorm_tpu_service_items_assigned'
SERVICE_RETRIES = 'petastorm_tpu_service_retries_total'
SERVICE_POISONED = 'petastorm_tpu_service_items_poisoned_total'


class _WorkerState:
    __slots__ = ('identity', 'last_heartbeat', 'ready', 'inflight')

    def __init__(self, identity, now):
        self.identity = identity
        self.last_heartbeat = now
        self.ready = False
        self.inflight = set()


class _TraceEntry:
    """Lifecycle of one traced item at the dispatcher: how many times it
    was dispatched, and — once delivered while still dedup-risky — when,
    so the sweep can age the retained entry out."""

    __slots__ = ('ctx', 'attempts', 'completed_at')

    def __init__(self, ctx):
        self.ctx = ctx
        self.attempts = 0
        self.completed_at = None


class Dispatcher:
    """Single-threaded scheduler loop behind a :class:`ServicePool`.

    :param endpoint: ``tcp://host:port`` to bind; port ``0`` binds a random
        free port (the resolved endpoint appears as :attr:`endpoint` once
        :meth:`wait_bound` returns).
    :param job_spec_payload: :func:`protocol.dump_job_spec` bytes replied to
        every REGISTER.
    :param deliver: NON-BLOCKING callable ``(kind, payload) -> bool``
        pushing ``('result', bytes)`` / ``('error', exc)`` /
        ``('marker', None)`` entries to the consumer; returns False when
        the consumer queue is momentarily full (the entry is then kept in
        an internal backlog and retried) and True when accepted or the
        pool is stopping. It must never block: this thread also acks
        worker heartbeats, and a consumer pause (recompile, checkpoint
        save) must quiesce the fleet, not starve its liveness protocol.
    :param stop_event: shared :class:`threading.Event`; setting it makes
        :meth:`run` broadcast STOP to all workers and exit.
    """

    def __init__(self, endpoint, job_spec_payload, deliver, stop_event,
                 heartbeat_interval_s=1.0, liveness_timeout_s=4.0,
                 max_inflight_per_worker=2, no_workers_timeout_s=30.0,
                 max_retries=None, retry_backoff_s=None):
        self._requested_endpoint = endpoint
        self._job_spec_payload = job_spec_payload
        self._deliver = deliver
        self._stop_event = stop_event
        self._heartbeat_interval_s = heartbeat_interval_s
        self._liveness_timeout_s = liveness_timeout_s
        self._max_inflight_per_worker = max_inflight_per_worker
        self._no_workers_timeout_s = no_workers_timeout_s
        # per-item retry budget (total attempts) + backoff base; knob
        # defaults so a standing fleet is governed without code changes
        self._max_retries = (max_retries if max_retries is not None
                             else knobs.get_int(
                                 'PETASTORM_TPU_SERVICE_MAX_RETRIES', 3,
                                 floor=1))
        self._retry_backoff_s = (retry_backoff_s
                                 if retry_backoff_s is not None
                                 else knobs.get_float(
                                     'PETASTORM_TPU_SERVICE_RETRY'
                                     '_BACKOFF_S', 0.05, floor=0.0))
        #: this dispatcher incarnation's identity, riding every SPEC and
        #: HEARTBEAT_ACK: a worker that sees the token change knows its
        #: dispatcher was replaced and must re-register for the new job
        self.token = uuid.uuid4().hex[:16].encode()

        self.endpoint = None
        self._bound = threading.Event()
        self._lock = threading.Lock()
        self._pending = collections.deque()   # (item_id, payload)
        self._pending_ids = set()
        self._next_item_id = 0
        self._workers = {}                    # identity -> _WorkerState
        self._inflight = {}                   # item_id -> (identity, payload)
        # Completion dedup applies ONLY to items that were ever
        # re-ventilated: a single-assignment item produces exactly one DONE
        # (one WORK message -> one completion), so keeping every finished id
        # would leak memory across an infinite-epoch stream for nothing.
        # _risky_ids marks re-ventilated items; _done records their
        # completions. Both stay bounded by failure churn, not stream length.
        self._risky_ids = set()
        self._done = set()
        # failure-domain state: failed-attempt counts (an item present
        # here is a SUSPECT and is assigned in isolation), the last
        # worker exception per suspect (delivered on quarantine so
        # poison_policy='raise' surfaces the real error), the backoff
        # heap of (ready_at, seq, item_id, payload), and the quarantine
        # ledger. All bounded by failure churn, never by stream length.
        self._attempts = {}
        self._last_error = {}
        self._retry = []
        self._retry_seq = 0
        self._poisoned = collections.OrderedDict()
        self._poisoned_count = 0
        self._retried_count = 0
        # Results awaiting consumer-queue space. Bounded in steady state:
        # while it is non-empty no new items are assigned, so it can never
        # exceed the completions already in flight when the consumer
        # stalled (≈ max_inflight_per_worker × workers).
        self._out_backlog = collections.deque()
        self._completed_count = 0
        self._reventilated_count = 0
        self._duplicate_done_count = 0
        self._workers_seen = 0
        self._metrics_deltas_merged = 0
        # identity -> latest heartbeat-piggybacked observability summary
        # (JSON dict); the per-worker breakdown of the fleet view. Kept
        # alongside _workers and pruned on deregister, so it is bounded
        # by fleet size.
        self._worker_obs = {}
        self._fatal_error = None
        self._no_workers_since = None
        # item_id -> _TraceEntry for traced items: the
        # work payload is opaque dill here, so the ServicePool registers
        # the context at submit time and the dispatcher stamps lifecycle
        # instants (dispatch/reventilate/done/duplicate_done) — which is
        # exactly what makes the exactly-once machinery OBSERVABLE: a
        # re-ventilated item's timeline shows every dispatch attempt and
        # its single deduped completion. Entries drop at completion; risky
        # ones are retained briefly for dedup marking and aged out by the
        # sweep, so the map stays bounded by in-flight work, never by
        # stream length or failure churn.
        self._trace_ctx = {}

    # -- thread-safe surface (called from pool / ventilator threads) ---------

    def submit(self, payload, trace_ctx=None):
        """Enqueue one dill-framed work item; returns its item id.
        ``trace_ctx`` (when the item is traced) keys the dispatcher's
        lifecycle instants to the trace minted at ventilation."""
        with self._lock:
            item_id = self._next_item_id
            self._next_item_id += 1
            self._pending.append((item_id, payload))
            self._pending_ids.add(item_id)
            if trace_ctx is not None:
                self._trace_ctx[item_id] = _TraceEntry(trace_ctx)
            return item_id

    def wait_bound(self, timeout):
        """Block until the ROUTER socket is bound (or binding failed)."""
        if not self._bound.wait(timeout):
            raise RuntimeError('Dispatcher did not bind %r within %.1fs'
                               % (self._requested_endpoint, timeout))
        if self._fatal_error is not None:
            raise self._fatal_error

    @property
    def fatal_error(self):
        return self._fatal_error

    def registered_workers(self):
        return len(self._workers)

    def stats(self):
        with self._lock:
            pending = len(self._pending)
        # list() snapshots the dict at C level (atomic under the GIL):
        # the dispatcher thread may register/deregister workers while a
        # consumer thread polls diagnostics.
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if time.monotonic() - w.last_heartbeat
                   <= self._liveness_timeout_s)
        return {
            'workers_alive': live,
            'workers_registered': len(self._workers),
            'workers_seen': self._workers_seen,
            'items_assigned': len(self._inflight),
            'items_pending': pending + len(self._retry),
            'items_reventilated': self._reventilated_count,
            'items_duplicate_done': self._duplicate_done_count,
            'items_retried': self._retried_count,
            'items_poisoned': self._poisoned_count,
            'metrics_deltas_merged': self._metrics_deltas_merged,
        }

    def health(self):
        """The dispatcher's /health contribution: fleet liveness plus
        the back-pressure state an operator needs first — ``quiesced``
        means completions are backlogged behind a full consumer queue,
        so the fleet is idling by design, not broken — plus the
        quarantine ledger: every recently-poisoned item with its attempt
        count and last failure, so "which row-group is killing my
        workers" is a /health read, not a log dig."""
        stats = self.stats()
        stats['quiesced'] = bool(self._out_backlog)
        stats['out_backlog'] = len(self._out_backlog)
        stats['endpoint'] = self.endpoint
        stats['items_completed'] = self._completed_count
        stats['max_retries'] = self._max_retries
        stats['poisoned'] = list(self._poisoned.values())
        return stats

    def fleet_view(self):
        """The merged fleet view the dispatcher's /report serves:
        per-worker breakdown (liveness, in-flight load, and the latest
        heartbeat-piggybacked observability summary — rates, pid, the
        worker's own obs endpoint port) plus the scheduler totals. The
        *aggregate* metrics (fleet-wide stage seconds, anomaly counters)
        already live in this process's registry via the DONE-frame delta
        merges, so `pipeline_report()` alongside this IS the merged
        view."""
        now = time.monotonic()
        workers = {}
        for identity, worker in list(self._workers.items()):
            name = identity.decode('utf-8', 'replace')
            entry = {
                'alive': now - worker.last_heartbeat
                <= self._liveness_timeout_s,
                'ready': worker.ready,
                'inflight': len(worker.inflight),
                'heartbeat_age_s': round(now - worker.last_heartbeat, 3),
            }
            summary = self._worker_obs.get(identity)
            if summary is not None:
                entry['summary'] = summary
            workers[name] = entry
        view = {'workers': workers}
        view.update(self.stats())
        return view

    def _update_fleet_gauges(self):
        """Mirror fleet health into the process-wide registry so
        pipeline_report()'s `service` section (and the Prometheus/JSONL
        exporters) see it without holding a pool reference."""
        if metrics_disabled():
            return
        now = time.monotonic()
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if now - w.last_heartbeat <= self._liveness_timeout_s)
        registry = get_registry()
        registry.gauge(SERVICE_WORKERS_ALIVE).set(live)
        registry.gauge(SERVICE_WORKERS_REGISTERED).set(len(workers))
        with self._lock:
            pending = len(self._pending)
        # backoff-delayed retries are pending work too — stats()/health()
        # already count them, and the gauge must agree
        registry.gauge(SERVICE_ITEMS_PENDING).set(pending
                                                  + len(self._retry))
        registry.gauge(SERVICE_ITEMS_ASSIGNED).set(len(self._inflight))

    # -- dispatcher thread ---------------------------------------------------

    def run(self):
        import zmq

        context = zmq.Context()
        sock = context.socket(zmq.ROUTER)
        try:
            if self._requested_endpoint.endswith(':0'):
                base = self._requested_endpoint.rsplit(':', 1)[0]
                port = sock.bind_to_random_port(base)
                self.endpoint = '%s:%d' % (base, port)
            else:
                sock.bind(self._requested_endpoint)
                self.endpoint = self._requested_endpoint
        except Exception as e:  # noqa: BLE001 - surfaced to start()
            self._fatal_error = RuntimeError(
                'Dispatcher failed to bind %r: %s'
                % (self._requested_endpoint, e))
            self._bound.set()
            sock.close(linger=0)
            context.term()
            return
        self._bound.set()

        last_sweep = time.monotonic()
        last_tick = last_sweep
        backlog_prev = False
        try:
            while not self._stop_event.is_set():
                self._flush_backlog()
                # Time spent with completions backlogged behind a full
                # consumer queue is the service-side back-pressure clock:
                # the fleet is quiesced because the CONSUMER is slow —
                # producer wait, consumer-bound evidence (the remote
                # workers never block locally; their out channel is the
                # dispatcher, so this is measured here). An interval
                # counts only when the backlog existed at BOTH of its
                # ends: charging the interval in which a backlog first
                # appeared would bill message-handling time that preceded
                # it as a stall.
                tick = time.monotonic()
                backlogged = bool(self._out_backlog)
                if backlogged and backlog_prev:
                    note_producer_wait(tick - last_tick)
                backlog_prev = backlogged
                last_tick = tick
                # While completions are backlogged the consumer's next free
                # queue slot is the event that matters, and ZMQ cannot wake
                # us for it — poll short so drained slots refill within
                # ~5ms instead of a full poll interval (otherwise every
                # marker behind a full queue costs the consumer a phantom
                # ~50ms starvation wait).
                poll_ms = 5 if self._out_backlog else _POLL_INTERVAL_MS
                if sock.poll(poll_ms):
                    # Drain everything queued before scheduling: completions
                    # free credit that the assignment pass below can use.
                    while True:
                        try:
                            frames = sock.recv_multipart(zmq.NOBLOCK)
                        except zmq.Again:
                            break
                        if faults.ARMED and faults.fault_hit(
                                'zmq.recv',
                                key=frames[1] if len(frames) > 1
                                else b'') == 'drop':
                            continue  # injected: message lost in flight
                        self._handle(sock, frames)
                self._assign(sock)
                now = time.monotonic()
                if now - last_sweep >= self._heartbeat_interval_s:
                    last_sweep = now
                    self._sweep(now)
                    self._update_fleet_gauges()
        except Exception as e:  # noqa: BLE001 - fatal for the whole pool
            logger.exception('Dispatcher loop died')
            self._fatal_error = e
        finally:
            for _ in range(_STOP_BROADCASTS):
                for identity in list(self._workers):
                    if faults.ARMED and faults.fault_hit(
                            'zmq.stop', key=identity) == 'drop':
                        continue  # injected: died without goodbye
                    try:
                        sock.send_multipart([identity, proto.MSG_STOP],
                                            flags=zmq.NOBLOCK)
                    except Exception:  # noqa: BLE001 - peer may be gone
                        count_swallowed('dispatcher-stop-broadcast')
                time.sleep(_POLL_INTERVAL_MS / 1000.0)
            sock.close(linger=500)
            context.term()

    # -- message handling ----------------------------------------------------

    def _handle(self, sock, frames):
        identity, msg = frames[0], frames[1]
        now = time.monotonic()
        if msg == proto.MSG_REGISTER:
            if identity not in self._workers:
                self._workers[identity] = _WorkerState(identity, now)
                self._workers_seen += 1
                logger.info('Worker %s registered (%d registered)',
                            identity, len(self._workers))
            else:
                self._workers[identity].last_heartbeat = now
            sock.send_multipart([identity, proto.MSG_SPEC,
                                 self._job_spec_payload, self.token])
            self._update_fleet_gauges()
        elif msg == proto.MSG_READY:
            worker = self._workers.get(identity)
            if worker is not None:
                worker.ready = True
                worker.last_heartbeat = now
        elif msg == proto.MSG_HEARTBEAT:
            summary = None
            if len(frames) > 2:
                # optional trailing frames: the worker's per-heartbeat
                # observability summary (docs/telemetry.md fleet view;
                # b'' when its advisory path degraded) and — its own
                # frame, never inside the summary, because correctness
                # must not ride an advisory channel — the worker's job
                # token. Absent from older builds; a bad summary frame
                # degrades to None and liveness never depends on either.
                summary = proto.load_obs_summary(frames[2])
            # a worker still serving ANOTHER dispatcher incarnation's
            # job (this one replaced it on the endpoint) advertises that
            # incarnation's token: keep its liveness, never assign it
            # work — our ACK's token will send it back to registration
            foreign = len(frames) > 3 and frames[3] != self.token
            worker = self._workers.get(identity)
            if worker is None:
                # A lapsed worker resurfacing (its items were already
                # re-ventilated): re-admit it with a clean slate — it
                # already holds the spec and a live decode worker.
                worker = _WorkerState(identity, now)
                worker.ready = not foreign
                self._workers[identity] = worker
                logger.info('Worker %s re-admitted after lapse%s',
                            identity,
                            ' (foreign incarnation; not assignable)'
                            if foreign else '')
            else:
                worker.last_heartbeat = now
                if foreign:
                    worker.ready = False
            if summary is not None:
                self._worker_obs[identity] = summary
            sock.send_multipart([identity, proto.MSG_HEARTBEAT_ACK,
                                 self.token])
        elif msg == proto.MSG_DONE:
            item_id = proto.unpack_item_id(frames[2])
            # frames: [identity, DONE, item_id, metrics, result*]. The
            # wire has no version marker, and externally-started worker
            # servers may run a pre-telemetry build whose DONE is
            # [identity, DONE, item_id, result*] — so the slot is claimed
            # as metrics ONLY when it is empty (b'': "nothing changed")
            # or passes load_metrics_delta's strict delta-shape check;
            # otherwise it is treated as the first result frame. Dropping
            # a result would be silent row loss; misreading one as a
            # delta is made implausible by the strict shape.
            payload = frames[3:]
            if payload and (payload[0] == b''
                            or self._merge_metrics(payload[0])):
                payload = payload[1:]
            self._complete(identity, item_id, ('result', payload), now)
        elif msg == proto.MSG_ERROR:
            item_id = proto.unpack_item_id(frames[2])
            exc = proto.load_exception(frames[3])
            if len(frames) > 4:
                self._merge_metrics(frames[4])
            self._fail(identity, item_id, exc, now)
        elif msg == proto.MSG_BYE:
            self._deregister(identity, 'said goodbye')
        else:
            logger.warning('Unknown service message type %r from %s',
                           msg, identity)

    def _merge_metrics(self, frame):
        """Fold one worker server's piggybacked telemetry delta into this
        (client) process's registry — the dispatcher is where per-worker
        deltas become the fleet-wide aggregate. Returns whether the frame
        WAS a delta (the DONE path uses this to tell the metrics slot from
        a result frame sent by a pre-telemetry worker build). Duplicate
        completions double-merge in the worst case (telemetry is advisory;
        item delivery, not metrics, carries the exactly-once guarantee)."""
        delta = proto.load_metrics_delta(frame)
        if delta is None:
            return False
        self._metrics_deltas_merged += 1
        merge_worker_delta(delta)
        return True

    def _complete(self, identity, item_id, outcome, now):
        worker = self._workers.get(identity)
        if worker is not None:
            worker.last_heartbeat = now
            worker.inflight.discard(item_id)
        if item_id in self._done:
            # Duplicate completion from a lapsed-then-reassigned race; the
            # first DONE already delivered this item's rows.
            logger.debug('Dropping duplicate completion of item %d from %s',
                         item_id, identity)
            self._duplicate_done_count += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_DUPLICATE_DONE).inc()
            # both completions have now been seen: the trace entry has
            # served its purpose (the dedup drop is marked on the timeline)
            dup_entry = self._trace_ctx.pop(item_id, None)
            if dup_entry is not None:
                tracing.record_instant(
                    'duplicate_done', dup_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'))
            return
        assignment = self._inflight.pop(item_id, None)
        if assignment is None:
            # Ghost completion: the item lapsed back onto the pending queue
            # (or the retry backoff heap) but its original owner finished
            # after all. Accept the result and withdraw the waiting copy
            # so it is not run twice.
            if not self._withdraw_waiting(item_id):
                logger.warning('Completion of unknown item %d from %s '
                               'dropped', item_id, identity)
                return
        else:
            owner = self._workers.get(assignment[0])
            if owner is not None:
                owner.inflight.discard(item_id)
        # a delivered completion clears the item's suspect record: its
        # budget was for THIS traversal, and innocent items that shared a
        # dying worker must not carry the black mark forever
        self._attempts.pop(item_id, None)
        self._last_error.pop(item_id, None)
        if item_id in self._risky_ids:
            self._done.add(item_id)
            # a risky item keeps its trace entry so a RACED second DONE
            # can be marked as deduped — but a SIGKILLed first owner never
            # sends one, so stamp the completion time and let the sweep
            # age the entry out (the ghost race window is a few liveness
            # timeouts at most); without this the map would grow with
            # failure churn for the life of the process
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None and trace_entry.completed_at is None:
                trace_entry.completed_at = now
        else:
            trace_entry = self._trace_ctx.pop(item_id, None)
        if trace_entry is not None:
            # the item's ONE delivered completion
            tracing.record_instant(
                'done', trace_entry.ctx, 'dispatcher',
                worker=identity.decode('utf-8', 'replace'),
                attempts=trace_entry.attempts, outcome=outcome[0])
        self._completed_count += 1
        kind, payload = outcome
        if kind == 'result':
            for result_frame in payload:
                self._emit(('result', result_frame))
        else:
            self._emit(('error', payload))
        self._emit(('marker', item_id))

    def _emit(self, entry):
        """Hand one entry toward the consumer, preserving order: direct
        only while the backlog is empty AND the queue has room."""
        if self._out_backlog or not self._deliver(entry):
            self._out_backlog.append(entry)

    def _flush_backlog(self):
        while self._out_backlog:
            if not self._deliver(self._out_backlog[0]):
                return
            self._out_backlog.popleft()

    # -- failure handling: retry budget, backoff, quarantine -----------------

    def _withdraw_waiting(self, item_id):
        """Remove a waiting (pending or backoff-heap) copy of ``item_id``
        after a ghost completion delivered it; False when no copy was
        waiting (a genuinely unknown completion)."""
        with self._lock:
            if item_id not in self._pending_ids:
                return False
            self._pending_ids.discard(item_id)
            self._pending = collections.deque(
                (i, p) for i, p in self._pending if i != item_id)
        if any(entry[2] == item_id for entry in self._retry):
            self._retry = [entry for entry in self._retry
                           if entry[2] != item_id]
            heapq.heapify(self._retry)
        return True

    def _fail(self, identity, item_id, exc, now):
        """One failed worker attempt (an ERROR frame): charge the item's
        retry budget and reschedule with backoff — or quarantine."""
        worker = self._workers.get(identity)
        if worker is not None:
            worker.last_heartbeat = now
            worker.inflight.discard(item_id)
        if item_id in self._done:
            # raced failure of an item whose ghost already delivered —
            # same dedup shape as a duplicate DONE
            self._duplicate_done_count += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_DUPLICATE_DONE).inc()
            return
        assignment = self._inflight.get(item_id)
        if assignment is None:
            # ghost failure from a lapsed owner; the re-ventilated copy
            # is already waiting (or assigned) and will speak for itself
            return
        if assignment[0] != identity:
            # ghost ERROR from a PRIOR owner racing its replacement: the
            # live assignment stands — cancelling it here would charge a
            # phantom attempt and let the item run twice concurrently
            return
        self._inflight.pop(item_id)
        self._record_failure(item_id, assignment[1],
                             'worker error: %s: %s'
                             % (type(exc).__name__, exc), exc, now)

    @staticmethod
    def _jitter(item_id, attempt):
        """Deterministic backoff jitter factor in [0.5, 1.5): seeded by
        the item identity so replayed chaos runs reschedule identically
        (no ``random`` module state involved)."""
        return 0.5 + ((item_id * 2654435761 + attempt * 40503)
                      % 4093) / 4093.0

    def _record_failure(self, item_id, payload, reason, exc, now):
        """Charge one failed attempt. Under budget: backoff-requeue.
        Budget exhausted: quarantine."""
        attempt = self._attempts.get(item_id, 0) + 1
        self._attempts[item_id] = attempt
        if exc is not None:
            self._last_error[item_id] = exc
        if attempt >= self._max_retries:
            self._quarantine(item_id, reason, now)
            return
        delay = (self._retry_backoff_s * (2 ** (attempt - 1))
                 * self._jitter(item_id, attempt))
        heapq.heappush(self._retry,
                       (now + delay, self._retry_seq, item_id, payload))
        self._retry_seq += 1
        with self._lock:
            self._pending_ids.add(item_id)
        self._retried_count += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_RETRIES).inc()
        entry = self._trace_ctx.get(item_id)
        if entry is not None:
            tracing.record_instant('retry', entry.ctx, 'dispatcher',
                                   attempt=attempt, reason=reason,
                                   backoff_s=round(delay, 4))
        logger.warning('Item %d failed attempt %d/%d (%s); retrying in '
                       '%.3fs', item_id, attempt, self._max_retries,
                       reason, delay)

    def _quarantine(self, item_id, reason, now):
        """Retry budget exhausted: skip the item, record it, surface it.
        The consumer receives a ``('poisoned', info)`` entry (policy
        applied pool-side) plus the accounting marker, so the epoch
        completes with the loss REPORTED instead of the fleet
        crash-looping or the read wedging."""
        attempts = self._attempts.pop(item_id, 0)
        exc = self._last_error.pop(item_id, None)
        # late ghost completions of a quarantined item must dedup away:
        # its rows were declared lost, and delivering them afterwards
        # would turn "reported loss" into silent duplication
        self._done.add(item_id)
        info = {'item_id': item_id, 'attempts': attempts,
                'reason': reason, 'error': exc,
                'max_retries': self._max_retries}
        descriptor = {'item_id': item_id, 'attempts': attempts,
                      'reason': reason,
                      'error': repr(exc) if exc is not None else None,
                      'quarantined_at': time.time()}
        self._poisoned[item_id] = descriptor
        while len(self._poisoned) > _POISONED_KEEP:
            self._poisoned.popitem(last=False)
        self._poisoned_count += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_POISONED).inc()
        record_anomaly('row_group_poisoned',
                       detail={k: v for k, v in descriptor.items()
                               if k != 'quarantined_at'})
        trace_entry = self._trace_ctx.pop(item_id, None)
        if trace_entry is not None:
            tracing.record_instant('poisoned', trace_entry.ctx,
                                   'dispatcher', attempts=attempts,
                                   reason=reason)
        self._emit(('poisoned', info))
        self._emit(('marker', item_id))

    def _promote_due_retries(self, now):
        """Move backoff-expired retries to the FRONT of the pending queue
        (oldest first): lapsed work is the oldest and gates epoch
        completion through the ventilator's in-flight bound."""
        due = []
        while self._retry and self._retry[0][0] <= now:
            _, _, item_id, payload = heapq.heappop(self._retry)
            due.append((item_id, payload))
        if due:
            with self._lock:
                for item_id, payload in reversed(due):
                    if item_id in self._pending_ids:
                        self._pending.appendleft((item_id, payload))

    def _pop_assignable(self, allow_suspect):
        """Pop the leftmost assignable pending item. Suspects (items with
        a failed attempt) are skipped unless ``allow_suspect`` — they are
        only ever assigned alone to an idle worker."""
        with self._lock:
            for idx in range(len(self._pending)):
                item_id, payload = self._pending[idx]
                if not allow_suspect and item_id in self._attempts:
                    continue
                del self._pending[idx]
                self._pending_ids.discard(item_id)
                return item_id, payload
        return None

    # -- scheduling ----------------------------------------------------------

    def _assign(self, sock):
        if self._out_backlog:
            # The consumer is stalled; assigning more work would just grow
            # the backlog unboundedly. Workers idle (heartbeating, acked)
            # until the consumer drains — quiescence, not decay.
            return
        self._promote_due_retries(time.monotonic())
        # Least-loaded first, so a fresh (or re-admitted) worker fills up
        # before busy ones receive more.
        workers = sorted((w for w in self._workers.values() if w.ready),
                         key=lambda w: len(w.inflight))
        for worker in workers:
            if any(i in self._attempts for i in worker.inflight):
                # suspect isolation: a worker running a retried item gets
                # NOTHING else — if the item kills it, it dies alone and
                # no innocent item's budget is charged for the crash
                continue
            while len(worker.inflight) < self._max_inflight_per_worker:
                popped = self._pop_assignable(
                    allow_suspect=not worker.inflight)
                if popped is None:
                    break
                item_id, payload = popped
                if item_id in self._done:
                    continue
                if faults.ARMED and faults.fault_hit(
                        'zmq.work', key=item_id) == 'drop':
                    pass  # injected: WORK frame lost; accounting intact
                else:
                    sock.send_multipart([worker.identity, proto.MSG_WORK,
                                         proto.pack_item_id(item_id),
                                         payload])
                self._inflight[item_id] = (worker.identity, payload)
                worker.inflight.add(item_id)
                entry = self._trace_ctx.get(item_id)
                if entry is not None:
                    entry.attempts += 1
                    tracing.record_instant(
                        'dispatch', entry.ctx, 'dispatcher',
                        worker=worker.identity.decode('utf-8', 'replace'),
                        attempt=entry.attempts)
                if item_id in self._attempts:
                    break  # nothing rides along with a suspect

    def _sweep(self, now):
        for identity, worker in list(self._workers.items()):
            if now - worker.last_heartbeat > self._liveness_timeout_s:
                self._deregister(
                    identity, 'heartbeat lapsed (%.1fs > %.1fs)'
                    % (now - worker.last_heartbeat, self._liveness_timeout_s))
        # age out trace entries retained past completion for dedup marking
        # (see _complete): a ghost DONE races within ZMQ buffering of one
        # lapse, so several liveness timeouts is a generous window
        retention_s = 10.0 * self._liveness_timeout_s
        stale = [item_id for item_id, entry in list(self._trace_ctx.items())
                 if entry.completed_at is not None
                 and now - entry.completed_at > retention_s]
        for item_id in stale:
            self._trace_ctx.pop(item_id, None)
        with self._lock:
            outstanding = bool(self._pending) or bool(self._inflight) \
                or bool(self._retry)
        if outstanding and not self._workers:
            if self._no_workers_since is None:
                self._no_workers_since = now
            elif now - self._no_workers_since > self._no_workers_timeout_s:
                raise RuntimeError(
                    'No live worker servers for %.1fs with work outstanding; '
                    'is the dispatcher endpoint (%s) reachable from the '
                    'workers?' % (self._no_workers_timeout_s, self.endpoint))
        else:
            self._no_workers_since = None

    def _deregister(self, identity, reason):
        worker = self._workers.pop(identity, None)
        self._worker_obs.pop(identity, None)
        if worker is None:
            return
        now = time.monotonic()
        reventilated = 0
        for item_id in worker.inflight:
            entry = self._inflight.pop(item_id, None)
            if entry is None or item_id in self._done:
                continue
            # From here the item can complete twice (ghost + reassigned
            # copy); only such items need completion dedup.
            self._risky_ids.add(item_id)
            reventilated += 1
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None:
                tracing.record_instant(
                    'reventilate', trace_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'),
                    reason=reason)
            # every re-ventilation charges the item's retry budget: a
            # row-group that deterministically kills its worker runs out
            # of budget and quarantines instead of crash-looping the
            # whole fleet forever (docs/service.md, failure semantics)
            self._record_failure(
                item_id, entry[1],
                'worker %s %s' % (identity.decode('utf-8', 'replace'),
                                  reason),
                None, now)
        self._reventilated_count += reventilated
        if reventilated and not metrics_disabled():
            get_registry().counter(SERVICE_REVENTILATED).inc(reventilated)
        self._update_fleet_gauges()
        logger.warning('Worker %s deregistered (%s); re-ventilated %d '
                       'in-flight item(s)', identity, reason, reventilated)
