"""Dispatcher: the service's item scheduler, job registry and liveness
tracker.

Runs as a single thread that owns the ROUTER socket (ZMQ sockets are not
thread-safe; every socket operation happens here). Other threads interact
through three thread-safe surfaces only: :meth:`submit` (the ventilator
hands in work items), the per-job ``deliver`` callback (results flow out
to the :class:`~petastorm_tpu.service.service_pool.ServicePool`'s bounded
queue), and :meth:`stats` (gauges).

Since the standing-service refactor (docs/service.md, "Standing
service") the dispatcher is **multi-job**: a *job registry* maps job ids
to their spec payload, their result destination, and their slice of the
worker fleet. Two kinds of job share one scheduler:

* the **local job** (id 0) — the embedded :class:`ServicePool` case:
  spec fixed at construction, results delivered through a callback into
  the consumer's bounded queue. At most one exists, so the embedded
  topology behaves exactly as before the registry existed.
* **client jobs** — registered over the wire (REGISTER_JOB) by remote
  :class:`~petastorm_tpu.service.daemon.DaemonClientPool` consumers.
  Results travel back as RESULT frames, items are keyed by the client's
  own item ids on the wire (the dispatcher's global item counter keeps
  the *internal* id space collision-free across jobs), delivery is
  gated by a per-job **credit** (the client's bounded-queue capacity),
  and a **lease** reclaims everything — pending, in-flight, workers —
  when a client dies without a goodbye.

Worker servers are **partitioned** across jobs: each worker is bound to
one job at registration (the job spec IS the worker build, so a worker
can only ever decode for one job at a time), the binding is chosen
least-loaded-first, and the sweep rebalances by STOPping one idle worker
of an over-served job per interval — the worker re-registers with a
fresh identity and lands on the starved job. Per-worker credit
(``max_inflight_per_worker``) is unchanged, so fair sharing composes
out of fair partitioning × per-worker credit.

Scheduling is credit-based: each live, READY worker server holds at most
``max_inflight_per_worker`` assigned items, so a slow worker never hoards
the queue and back-pressure composes with the ventilator's own in-flight
bound.

Fault tolerance — the exactly-once core:

* Every ventilated item gets a monotonically increasing id; ownership
  (``item id -> worker identity``) is recorded at assignment.
* A worker whose heartbeat lapses past ``liveness_timeout_s`` is
  deregistered and its in-flight items go back to the FRONT of its job's
  pending queue (**re-ventilation**) for reassignment.
* Completions are deduplicated by item id: a lapsed-but-actually-alive
  worker (GC pause, network stall) racing its replacement can produce two
  DONEs for one item — the first wins and is delivered, the second is
  dropped. Worker servers buffer an item's results and send them in a
  single DONE, so a worker killed mid-item has delivered nothing for it
  and the re-run is not a duplicate. Together: every item's row set reaches
  the consumer exactly once.

Failure-domain hardening (docs/service.md, "Failure semantics"):

* **Retry budgets**: every failed attempt of an item — a worker ERROR or
  a heartbeat-lapse re-ventilation — counts against the item's budget
  (``PETASTORM_TPU_SERVICE_MAX_RETRIES`` total attempts). Failed items
  re-enter the queue after an exponential, deterministically-jittered
  backoff instead of immediately, so a deterministic crasher cannot
  hot-loop the fleet.
* **Suspect isolation**: an item with a failed attempt behind it is only
  ever assigned ALONE to an idle worker. A poisoned row-group therefore
  burns exactly one worker per attempt and never drags co-assigned
  innocent items' budgets down with it.
* **Poison quarantine**: an item that exhausts its budget is quarantined
  — skipped with a ``('poisoned', info)`` delivery (the pool applies the
  reader's ``poison_policy``), recorded on :meth:`health` (the /health
  endpoint), counted, and announced as a ``row_group_poisoned`` anomaly
  event — instead of crash-looping the fleet forever.
* **Incarnation token**: SPEC replies and heartbeat ACKs carry this
  dispatcher's random token; a worker that suddenly sees a different
  token knows its dispatcher was replaced (client restart on the same
  endpoint) and re-registers instead of decoding for a job spec the new
  dispatcher never sent it.
* **Job leases**: a client job whose SUBMIT/CLIENT_HB traffic goes
  silent past its lease is garbage-collected — in-flight work reclaimed
  (late completions dedup away), pending purged, its workers STOPped
  back into the registration pool — announced as a ``job_lease_expired``
  anomaly event, with zero effect on co-tenant jobs.
* **Drain**: :meth:`begin_drain` (the daemon's SIGTERM path) makes every
  new REGISTER_JOB answer a retryable BUSY while registered jobs finish;
  admission control answers the same BUSY when the registry is full
  (``PETASTORM_TPU_SERVICE_MAX_JOBS``) — clients back off and retry
  instead of erroring.
"""

import collections
import heapq
import logging
import threading
import time
import uuid

from petastorm_tpu import faults
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.service.peer_cache import (
    PEER_CACHE_EVICT_HINTS, FleetCacheDirectory,
)
from petastorm_tpu.telemetry import (
    count_swallowed, get_registry, knobs, merge_worker_delta,
    metrics_disabled, note_producer_wait, tracing,
)
from petastorm_tpu.telemetry.timeseries import record_anomaly

logger = logging.getLogger(__name__)

_POLL_INTERVAL_MS = 50
_STOP_BROADCASTS = 3

#: how often the sweep recomputes fleet-global eviction hints from the
#: peer-cache directory (hint queues drain on heartbeat ACKs between
#: recomputes; coarser than the cold threshold is all that's needed)
_PEER_HINT_INTERVAL_S = 5.0

#: digests answered per DIRGET request (the asker re-asks for the rest;
#: in practice it asks for one digest per fetch)
_DIRGET_CAP = 64

#: liveness floor for workers WAITING for a job (job_id None): their
#: only liveness signal is the REGISTER re-send, whose worker-side
#: backoff caps at 2s — a tight heartbeat-tuned window would lapse and
#: re-admit every healthy idle worker in a pointless churn loop. They
#: hold no in-flight work, so the slow detection costs nothing.
_UNBOUND_LIVENESS_FLOOR_S = 5.0

#: quarantined-item descriptors retained for /health (count is unbounded,
#: the descriptor list is not — an operator needs the recent offenders,
#: not an ever-growing ledger in a long-lived daemon)
_POISONED_KEEP = 100

#: the embedded (callback-delivery) job's fixed id; client jobs count up
#: from 1
LOCAL_JOB_ID = 0

# Fleet-health metric names (docs/telemetry.md): the dispatcher runs in
# the CONSUMER process (or the standing daemon), so these land straight
# in its process-wide registry and surface through pipeline_report()'s
# `service` section — re-ventilation/dedupe activity visible without
# reading dispatcher logs.
SERVICE_REVENTILATED = 'petastorm_tpu_service_reventilated_total'
SERVICE_DUPLICATE_DONE = 'petastorm_tpu_service_duplicate_done_total'
SERVICE_WORKERS_ALIVE = 'petastorm_tpu_service_workers_alive'
SERVICE_WORKERS_REGISTERED = 'petastorm_tpu_service_workers_registered'
SERVICE_ITEMS_PENDING = 'petastorm_tpu_service_items_pending'
SERVICE_ITEMS_ASSIGNED = 'petastorm_tpu_service_items_assigned'
SERVICE_RETRIES = 'petastorm_tpu_service_retries_total'
SERVICE_POISONED = 'petastorm_tpu_service_items_poisoned_total'
SERVICE_JOBS = 'petastorm_tpu_service_jobs_active'
# cache-aware placement + QoS (docs/service.md, "High availability"):
# a placement hit is a job bound to a worker already advertising the
# job's decode fingerprint (its host holds the warm cache); a
# preemption is a worker cordoned away from a lower-priority job for a
# higher-priority one at row-group granularity
SERVICE_PLACEMENT_HITS = 'petastorm_tpu_service_placement_hits_total'
SERVICE_PLACEMENT_MISSES = 'petastorm_tpu_service_placement_misses_total'
SERVICE_PREEMPTIONS = 'petastorm_tpu_service_preemptions_total'


class _WorkerState:
    __slots__ = ('identity', 'last_heartbeat', 'ready', 'inflight',
                 'job_id', 'cordoned', 'pid', 'cache_fps', 'preempted_to',
                 'peer_dir_seen')

    def __init__(self, identity, now):
        self.identity = identity
        self.last_heartbeat = now
        self.ready = False
        self.inflight = set()
        #: the job this worker was built for (its SPEC); None while the
        #: worker awaits a job to exist
        self.job_id = None
        #: True once the supervisor marked this worker for release: no
        #: new assignments, terminated once idle
        self.cordoned = False
        #: learned from the REGISTER pid frame (new-build workers) or
        #: the heartbeat summaries; None on old builds until the first
        #: summary arrives
        self.pid = None
        #: decode fingerprints the worker's host advertises (REGISTER
        #: advert frame / heartbeat summaries) — the dispatcher's slice
        #: of the fleet cache directory (docs/service.md)
        self.cache_fps = set()
        #: job id a pending preemption is cordoning this worker toward:
        #: no new assignments, STOPped once its in-flight drains (never
        #: mid-item), then re-bound by priority. Distinct from
        #: ``cordoned``, which is the supervisor's TERMINATE path.
        self.preempted_to = None
        #: peer-cache directory version last piggybacked to this worker
        #: on a WORK frame (fleet cache tier, docs/service.md)
        self.peer_dir_seen = 0


class _Job:
    """One registry entry: where a job's items come from and where its
    results go. ``deliver`` set = the local (embedded-pool) job;
    ``client`` set = a remote client job speaking RESULT frames."""

    __slots__ = ('job_id', 'name', 'spec_payload', 'deliver', 'client',
                 'client_key', 'lease_s', 'last_client_seen', 'credit',
                 'markers_sent', 'markers_acked', 'pending', 'pending_ids',
                 'client_item_ids', 'live_cids', 'out', 'workers',
                 'submitted', 'completed', 'created_at', 'weight',
                 'priority', 'fingerprint')

    def __init__(self, job_id, spec_payload, deliver=None, client=None,
                 client_key=None, lease_s=None, credit=None, name=None,
                 weight=None, priority=None, fingerprint=None):
        self.job_id = job_id
        self.name = name or 'job-%d' % job_id
        self.spec_payload = spec_payload
        self.deliver = deliver
        self.client = client
        self.client_key = client_key
        self.lease_s = lease_s
        self.last_client_seen = time.monotonic()
        self.credit = credit
        # QoS (docs/service.md, "High availability"): weight scales the
        # job's fair share of the worker fleet (weight 3 ≈ 3× the
        # workers of a weight-1 co-tenant); priority is strict admission
        # — a higher tier with pending work takes workers from lower
        # tiers (preemption), never the reverse. Defaults reproduce the
        # pre-QoS equal-share scheduler exactly.
        self.weight = max(float(weight), 1e-6) if weight else 1.0
        self.priority = int(priority) if priority else 0
        #: decode fingerprint for cache-aware placement; None opts out
        self.fingerprint = fingerprint or None
        # delivery-credit clock for client jobs: markers sent vs markers
        # the client reports consumed; the gap bounds everything buffered
        # between the two processes, so a stalled consumer quiesces ITS
        # job's slice of the fleet without touching co-tenants
        self.markers_sent = 0
        self.markers_acked = 0
        self.pending = collections.deque()    # (item_id, payload)
        self.pending_ids = set()
        self.client_item_ids = {}             # item_id -> client item id
        #: live client item ids — dedups a reconnected client's
        #: re-submission of items this job still holds (its marker was
        #: in flight when the client's socket reset)
        self.live_cids = set()
        # undelivered outbound entries: local = delivery tuples awaiting
        # queue space; client = RESULT frame lists awaiting socket space
        self.out = collections.deque()
        self.workers = set()                  # bound worker identities
        self.submitted = 0
        self.completed = 0
        self.created_at = time.time()

    @property
    def is_local(self):
        return self.deliver is not None

    def gated(self):
        """True when assigning more of this job's items would only grow
        an unbounded buffer: the local consumer's queue is full
        (backlog), or a client job's delivery credit is spent."""
        if self.out:
            return True
        return (self.credit is not None
                and self.markers_sent - self.markers_acked >= self.credit)

    def descriptor(self):
        return {
            'job_id': self.job_id,
            'name': self.name,
            'local': self.is_local,
            'pending': len(self.pending),
            'workers': len(self.workers),
            'submitted': self.submitted,
            'completed': self.completed,
            'unacked': self.markers_sent - self.markers_acked,
            'credit': self.credit,
            'lease_s': self.lease_s,
            'out_backlog': len(self.out),
            'weight': self.weight,
            'priority': self.priority,
            'fingerprint': self.fingerprint,
        }


class _TraceEntry:
    """Lifecycle of one traced item at the dispatcher: how many times it
    was dispatched, and — once delivered while still dedup-risky — when,
    so the sweep can age the retained entry out."""

    __slots__ = ('ctx', 'attempts', 'completed_at')

    def __init__(self, ctx):
        self.ctx = ctx
        self.attempts = 0
        self.completed_at = None


class Dispatcher:
    """Single-threaded scheduler loop behind a :class:`ServicePool` or a
    standing :class:`~petastorm_tpu.service.daemon.ServiceDaemon`.

    :param endpoint: ``tcp://host:port`` to bind; port ``0`` binds a random
        free port (the resolved endpoint appears as :attr:`endpoint` once
        :meth:`wait_bound` returns).
    :param job_spec_payload: :func:`protocol.dump_job_spec` bytes replied to
        every REGISTER for the embedded local job; ``None`` for a standing
        daemon (jobs arrive over the wire instead).
    :param deliver: NON-BLOCKING callable ``(kind, payload) -> bool``
        pushing ``('result', bytes)`` / ``('error', exc)`` /
        ``('marker', None)`` entries to the consumer; returns False when
        the consumer queue is momentarily full (the entry is then kept in
        an internal backlog and retried) and True when accepted or the
        pool is stopping. It must never block: this thread also acks
        worker heartbeats, and a consumer pause (recompile, checkpoint
        save) must quiesce the fleet, not starve its liveness protocol.
        ``None`` for a standing daemon.
    :param stop_event: shared :class:`threading.Event`; setting it makes
        :meth:`run` broadcast STOP to all workers and exit.
    :param standing: True for a daemonized dispatcher: zero live workers
        with work outstanding is a supervisor condition to repair, not a
        fatal error, and client REGISTER_JOB frames are expected traffic.
    """

    def __init__(self, endpoint, job_spec_payload, deliver, stop_event,
                 heartbeat_interval_s=1.0, liveness_timeout_s=4.0,
                 max_inflight_per_worker=2, no_workers_timeout_s=30.0,
                 max_retries=None, retry_backoff_s=None, standing=False,
                 max_jobs=None, default_lease_s=None, seed_state=None):
        self._requested_endpoint = endpoint
        self._deliver = deliver
        self._stop_event = stop_event
        self._heartbeat_interval_s = heartbeat_interval_s
        self._liveness_timeout_s = liveness_timeout_s
        self._max_inflight_per_worker = max_inflight_per_worker
        self._no_workers_timeout_s = no_workers_timeout_s
        self._standing = standing
        # per-item retry budget (total attempts) + backoff base; knob
        # defaults so a standing fleet is governed without code changes
        self._max_retries = (max_retries if max_retries is not None
                             else knobs.get_int(
                                 'PETASTORM_TPU_SERVICE_MAX_RETRIES', 3,
                                 floor=1))
        self._retry_backoff_s = (retry_backoff_s
                                 if retry_backoff_s is not None
                                 else knobs.get_float(
                                     'PETASTORM_TPU_SERVICE_RETRY'
                                     '_BACKOFF_S', 0.05, floor=0.0))
        # job-registry governance (standing service): admission ceiling
        # and the default lease clients inherit when they name none
        self._max_jobs = (max_jobs if max_jobs is not None
                          else knobs.get_int(
                              'PETASTORM_TPU_SERVICE_MAX_JOBS', 16,
                              floor=1))
        self._default_lease_s = (default_lease_s
                                 if default_lease_s is not None
                                 else knobs.get_float(
                                     'PETASTORM_TPU_SERVICE_LEASE_S',
                                     30.0, floor=1.0))
        # cache-aware placement toggle: on by default, a kill switch for
        # fleets where fingerprint adverts misbehave
        self._placement_enabled = not knobs.is_disabled(
            'PETASTORM_TPU_SERVICE_PLACEMENT')
        # fleet cache tier (docs/service.md, "Fleet cache tier"): the
        # digest -> holders directory folded from worker adverts, plus
        # the advisory global-eviction machinery. On by default;
        # PETASTORM_TPU_PEER_CACHE=0 is the host-local oracle.
        self._peer_enabled = not knobs.is_disabled(
            'PETASTORM_TPU_PEER_CACHE')
        self._peer_dir = FleetCacheDirectory()
        self._peer_hint_at = 0.0
        self._peer_evict_hints_sent = 0
        #: this dispatcher incarnation's identity, riding every SPEC and
        #: HEARTBEAT_ACK: a worker that sees the token change knows its
        #: dispatcher was replaced and must re-register for the new job
        self.token = uuid.uuid4().hex[:16].encode()

        self.endpoint = None
        self._bound = threading.Event()
        self._lock = threading.Lock()
        # the job registry: LOCAL_JOB_ID (embedded callback delivery) +
        # wire-registered client jobs; _item_job maps every live item's
        # GLOBAL id to its job, which is what keeps N jobs' item spaces
        # collision-free over one worker wire protocol
        self._jobs = collections.OrderedDict()
        self._job_seq = LOCAL_JOB_ID
        self._item_job = {}
        self._draining = False
        if job_spec_payload is not None:
            self._jobs[LOCAL_JOB_ID] = _Job(LOCAL_JOB_ID, job_spec_payload,
                                            deliver=deliver, name='local')
        self._next_item_id = 0
        self._workers = {}                    # identity -> _WorkerState
        self._inflight = {}                   # item_id -> (identity, payload)
        # Completion dedup applies ONLY to items that were ever
        # re-ventilated (or reclaimed by a job GC): a single-assignment
        # item produces exactly one DONE (one WORK message -> one
        # completion), so keeping every finished id would leak memory
        # across an infinite-epoch stream for nothing. _risky_ids marks
        # re-ventilated items; _done records their completions. Both stay
        # bounded by failure churn, not stream length.
        self._risky_ids = set()
        self._done = set()
        # failure-domain state: failed-attempt counts (an item present
        # here is a SUSPECT and is assigned in isolation), the last
        # worker exception per suspect (delivered on quarantine so
        # poison_policy='raise' surfaces the real error), the backoff
        # heap of (ready_at, seq, item_id, payload), and the quarantine
        # ledger. All bounded by failure churn, never by stream length.
        self._attempts = {}
        self._last_error = {}
        # item_id -> identities EVER assigned that item by THIS
        # dispatcher incarnation. Completion acceptance is gated on it:
        # a ghost DONE from a lapsed prior owner is legitimate (the
        # exactly-once dedup handles it), but a STALE DONE from another
        # incarnation's worker — its socket flushing on reconnect after
        # a daemon restart, carrying an item id that COLLIDES with this
        # incarnation's id space — must be dropped, or it completes the
        # wrong item with the wrong rows (duplicate + loss). Entries
        # drop with their item (completion/quarantine/job GC).
        self._item_owners = {}
        self._retry = []
        self._retry_seq = 0
        self._poisoned = collections.OrderedDict()
        self._poisoned_count = 0
        self._retried_count = 0
        self._completed_count = 0
        self._reventilated_count = 0
        self._duplicate_done_count = 0
        self._workers_seen = 0
        self._jobs_seen = 1 if job_spec_payload is not None else 0
        self._jobs_expired = 0
        self._metrics_deltas_merged = 0
        # QoS / placement / HA accounting (docs/service.md, "High
        # availability"): binding placement hits/misses, preemptions,
        # and the replication pulls served to a warm standby
        self._placement_hits = 0
        self._placement_misses = 0
        self._preemptions = 0
        self._standby_syncs_served = 0
        self._last_standby_sync = None
        # identity -> latest heartbeat-piggybacked observability summary
        # (JSON dict); the per-worker breakdown of the fleet view. Kept
        # alongside _workers and pruned on deregister, so it is bounded
        # by fleet size.
        self._worker_obs = {}
        # identity -> job_id at deregistration time: a lapsed worker
        # resurfacing via heartbeat is still RUNNING the spec of the job
        # it lapsed from — re-binding it anywhere else would hand job
        # B's items to job A's decode worker. Bounded: lapse churn only.
        self._lapsed_bindings = collections.OrderedDict()
        self._fatal_error = None
        self._no_workers_since = None
        # the ROUTER socket, owned by the dispatcher thread; deep
        # delivery paths (quarantine inside a sweep) reach it here
        # instead of threading it through six call layers. Only the
        # dispatcher thread may touch it.
        self._sock = None
        # item_id -> _TraceEntry for traced items: the
        # work payload is opaque dill here, so the ServicePool registers
        # the context at submit time and the dispatcher stamps lifecycle
        # instants (dispatch/reventilate/done/duplicate_done) — which is
        # exactly what makes the exactly-once machinery OBSERVABLE: a
        # re-ventilated item's timeline shows every dispatch attempt and
        # its single deduped completion. Entries drop at completion; risky
        # ones are retained briefly for dedup marking and aged out by the
        # sweep, so the map stays bounded by in-flight work, never by
        # stream length or failure churn.
        self._trace_ctx = {}
        if seed_state:
            self._adopt_seed_state(seed_state)

    def _adopt_seed_state(self, state):
        """Adopt a promoted standby's replicated registry snapshot
        (:meth:`standby_snapshot` of the dead primary). Jobs come back
        with their identity (job id, client key), lease, credit and QoS
        params but with ``client=None`` and a zeroed credit window: the
        clients' re-registration (triggered by this incarnation's fresh
        token) re-binds them by key and re-submits every un-markered
        item, which is also why no in-flight items replicate — they
        re-ventilate from the client side. ``next_item_id`` seeds ABOVE
        the dead primary's watermark so late cross-incarnation frames
        can never collide with this incarnation's id space (they are
        dropped by the ``_item_owners`` gate regardless)."""
        try:
            for desc in state.get('jobs', ()):
                job = _Job(int(desc['job_id']), desc['spec_payload'],
                           client=None, client_key=desc.get('client_key'),
                           lease_s=desc.get('lease_s'),
                           credit=desc.get('credit'),
                           name=desc.get('name'),
                           weight=desc.get('weight'),
                           priority=desc.get('priority'),
                           fingerprint=desc.get('fingerprint'))
                self._jobs[job.job_id] = job
                self._jobs_seen += 1
            self._job_seq = max([self._job_seq,
                                 int(state.get('job_seq', 0))]
                                + [j.job_id for j in self._jobs.values()])
            self._next_item_id = max(self._next_item_id,
                                     int(state.get('next_item_id', 0)))
            # the replicated peer-cache directory: seeded under synthetic
            # per-endpoint identities so DIRGET stays warm through the
            # failover window (workers' re-REGISTER full adverts replace
            # the seeds; unclaimed seeds age out in the sweep)
            peer_snapshot = state.get('peer_directory')
            if peer_snapshot and self._peer_enabled:
                self._peer_dir.seed(peer_snapshot, time.monotonic())
        except Exception:  # noqa: BLE001 - degrade to a cold promote
            count_swallowed('dispatcher-seed-state')
            logger.warning('Unusable standby seed state; promoting cold '
                           '(clients re-register)', exc_info=True)

    def standby_snapshot(self):
        """The replication snapshot a warm standby pulls (SSYNC): client
        job identities and QoS/lease/credit params plus the id
        watermarks. Deliberately NOT replicated: in-flight items and
        delivery buffers (they re-ventilate via client re-submission),
        the worker roster beyond its cache adverts (workers re-register
        with the new incarnation within a heartbeat), and the local
        embedded job (it dies with its process)."""
        with self._lock:
            jobs = [{
                'job_id': job.job_id,
                'name': job.name,
                'spec_payload': job.spec_payload,
                'client_key': job.client_key,
                'lease_s': job.lease_s,
                'credit': job.credit,
                'weight': job.weight,
                'priority': job.priority,
                'fingerprint': job.fingerprint,
            } for job in self._jobs.values() if not job.is_local]
            next_item_id = self._next_item_id
        fleet_fps = set()
        for worker in list(self._workers.values()):
            fleet_fps.update(worker.cache_fps)
        return {'next_item_id': next_item_id,
                'job_seq': self._job_seq,
                'jobs': jobs,
                'fleet_cache_fps': sorted(fleet_fps),
                'peer_directory': self._peer_dir.snapshot()}

    # -- thread-safe surface (called from pool / ventilator threads) ---------

    def submit(self, payload, trace_ctx=None, job_id=LOCAL_JOB_ID,
               client_item_id=None):
        """Enqueue one dill-framed work item for ``job_id``; returns its
        GLOBAL item id (unique across every job this dispatcher ever
        scheduled). ``trace_ctx`` (when the item is traced) keys the
        dispatcher's lifecycle instants to the trace minted at
        ventilation; ``client_item_id`` is the wire id RESULT frames echo
        back to a client job."""
        with self._lock:
            job = self._jobs[job_id]
            item_id = self._next_item_id
            self._next_item_id += 1
            job.pending.append((item_id, payload))
            job.pending_ids.add(item_id)
            job.submitted += 1
            self._item_job[item_id] = job_id
            if client_item_id is not None:
                job.client_item_ids[item_id] = client_item_id
                job.live_cids.add(client_item_id)
            if trace_ctx is not None:
                self._trace_ctx[item_id] = _TraceEntry(trace_ctx)
            return item_id

    def wait_bound(self, timeout):
        """Block until the ROUTER socket is bound (or binding failed)."""
        if not self._bound.wait(timeout):
            raise RuntimeError('Dispatcher did not bind %r within %.1fs'
                               % (self._requested_endpoint, timeout))
        if self._fatal_error is not None:
            raise self._fatal_error

    @property
    def fatal_error(self):
        return self._fatal_error

    @property
    def draining(self):
        return self._draining

    def begin_drain(self):
        """Enter drain mode (the daemon's SIGTERM path): every later
        REGISTER_JOB answers a retryable BUSY; already-registered jobs
        keep running until they finish or their lease lapses."""
        self._draining = True
        logger.warning('Dispatcher draining: new jobs refused (BUSY), '
                       '%d job(s) finishing', len(self._jobs))

    def registered_workers(self):
        return len(self._workers)

    def active_jobs(self):
        """Live registry size (local + client jobs)."""
        return len(self._jobs)

    def _pending_total_locked(self):
        return sum(len(j.pending) for j in self._jobs.values())

    def stats(self):
        with self._lock:
            pending = self._pending_total_locked()
        # list() snapshots the dict at C level (atomic under the GIL):
        # the dispatcher thread may register/deregister workers while a
        # consumer thread polls diagnostics.
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if time.monotonic() - w.last_heartbeat
                   <= self._liveness_timeout_s)
        return {
            'workers_alive': live,
            'workers_registered': len(self._workers),
            'workers_seen': self._workers_seen,
            'items_assigned': len(self._inflight),
            'items_pending': pending + len(self._retry),
            'items_reventilated': self._reventilated_count,
            'items_duplicate_done': self._duplicate_done_count,
            'items_retried': self._retried_count,
            'items_poisoned': self._poisoned_count,
            'metrics_deltas_merged': self._metrics_deltas_merged,
            'jobs_active': len(self._jobs),
            'jobs_seen': self._jobs_seen,
            'jobs_expired': self._jobs_expired,
        }

    def health(self):
        """The dispatcher's /health contribution: fleet liveness plus
        the back-pressure state an operator needs first — ``quiesced``
        means completions are backlogged behind a full consumer queue
        (or a client job's spent delivery credit), so the fleet is
        idling by design, not broken — plus the job registry (per-job
        pending/credit/lease state) and the quarantine ledger: every
        recently-poisoned item with its attempt count and last failure,
        so "which row-group is killing my workers" is a /health read,
        not a log dig."""
        stats = self.stats()
        jobs = list(self._jobs.values())
        stats['quiesced'] = any(job.gated() for job in jobs)
        stats['out_backlog'] = sum(len(job.out) for job in jobs)
        stats['endpoint'] = self.endpoint
        stats['items_completed'] = self._completed_count
        stats['max_retries'] = self._max_retries
        stats['draining'] = self._draining
        stats['max_jobs'] = self._max_jobs
        stats['jobs'] = [job.descriptor() for job in jobs]
        stats['poisoned'] = list(self._poisoned.values())
        # QoS / placement / HA surface (docs/service.md, "High
        # availability"): per-job delivery shares (worker fraction,
        # weight-normalized target), placement hit/miss counters, and
        # how recently a warm standby pulled a replication snapshot
        bound = sum(len(job.workers) for job in jobs) or 1
        weight_total = sum(job.weight for job in jobs) or 1.0
        stats['qos'] = [{
            'job_id': job.job_id, 'name': job.name,
            'weight': job.weight, 'priority': job.priority,
            'worker_share': round(len(job.workers) / bound, 4),
            'target_share': round(job.weight / weight_total, 4),
        } for job in jobs]
        stats['placement_enabled'] = self._placement_enabled
        stats['placement_hits'] = self._placement_hits
        stats['placement_misses'] = self._placement_misses
        stats['preemptions'] = self._preemptions
        stats['standby_syncs_served'] = self._standby_syncs_served
        stats['last_standby_sync_age_s'] = (
            round(time.monotonic() - self._last_standby_sync, 3)
            if self._last_standby_sync is not None else None)
        # fleet cache-tier directory view (docs/service.md, "Fleet cache
        # tier"): how many entries the fleet advertises, by how many
        # holders, and the advisory eviction-hint flow
        peer = dict(self._peer_dir.stats())
        peer['enabled'] = self._peer_enabled
        peer['hints_sent'] = self._peer_evict_hints_sent
        stats['peer_cache'] = peer
        return stats

    def fleet_view(self):
        """The merged fleet view the dispatcher's /report serves:
        per-worker breakdown (liveness, job binding, in-flight load, and
        the latest heartbeat-piggybacked observability summary — rates,
        pid, the worker's own obs endpoint port) plus the scheduler
        totals and the job registry. The *aggregate* metrics (fleet-wide
        stage seconds, anomaly counters) already live in this process's
        registry via the DONE-frame delta merges, so `pipeline_report()`
        alongside this IS the merged view."""
        now = time.monotonic()
        workers = {}
        for identity, worker in list(self._workers.items()):
            name = identity.decode('utf-8', 'replace')
            entry = {
                'alive': now - worker.last_heartbeat
                <= self._liveness_timeout_s,
                'ready': worker.ready,
                'inflight': len(worker.inflight),
                'heartbeat_age_s': round(now - worker.last_heartbeat, 3),
                'job_id': worker.job_id,
            }
            if worker.cordoned:
                entry['cordoned'] = True
            if worker.preempted_to is not None:
                entry['preempted_to'] = worker.preempted_to
            if worker.cache_fps:
                entry['cache_fps'] = sorted(worker.cache_fps)
            held = self._peer_dir.held_count(identity)
            if held:
                entry['peer_entries'] = held
            summary = self._worker_obs.get(identity)
            if summary is not None:
                entry['summary'] = summary
            workers[name] = entry
        view = {'workers': workers,
                'jobs': [job.descriptor()
                         for job in list(self._jobs.values())]}
        view.update(self.stats())
        return view

    # -- supervisor surface (called from the supervisor thread) --------------

    def _worker_pid(self, identity, worker):
        if worker.pid is not None:
            return worker.pid
        summary = self._worker_obs.get(identity)
        if summary and summary.get('pid'):
            return int(summary['pid'])
        return None

    def alive_worker_pids(self):
        """Pids of workers inside the liveness window — what the
        supervisor diffs against its spawned processes to find a
        wedged-but-running worker (process alive, heartbeats gone). A
        worker between jobs counts: its REGISTER re-sends refresh
        liveness and carry its pid."""
        now = time.monotonic()
        pids = set()
        for identity, worker in list(self._workers.items()):
            if now - worker.last_heartbeat > self._liveness_timeout_s:
                continue
            pid = self._worker_pid(identity, worker)
            if pid is not None:
                pids.add(pid)
        return pids

    def cordon_worker_by_pid(self, pid):
        """Stop assigning work to the worker running as ``pid`` (the
        supervisor's two-phase release: cordon, wait idle, terminate).
        Returns True when a live worker matched. The flag writes are
        benign cross-thread (single bool stores read by the dispatcher
        thread's next scheduling pass)."""
        for identity, worker in list(self._workers.items()):
            if self._worker_pid(identity, worker) == pid:
                worker.cordoned = True
                worker.ready = False
                return True
        return False

    def worker_inflight_by_pid(self, pid):
        """In-flight item count of the worker running as ``pid``; None
        when no such worker is registered (already gone)."""
        for identity, worker in list(self._workers.items()):
            if self._worker_pid(identity, worker) == pid:
                return len(worker.inflight)
        return None

    def _update_fleet_gauges(self):
        """Mirror fleet health into the process-wide registry so
        pipeline_report()'s `service` section (and the Prometheus/JSONL
        exporters) see it without holding a pool reference."""
        if metrics_disabled():
            return
        now = time.monotonic()
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if now - w.last_heartbeat <= self._liveness_timeout_s)
        registry = get_registry()
        registry.gauge(SERVICE_WORKERS_ALIVE).set(live)
        registry.gauge(SERVICE_WORKERS_REGISTERED).set(len(workers))
        with self._lock:
            pending = self._pending_total_locked()
        # backoff-delayed retries are pending work too — stats()/health()
        # already count them, and the gauge must agree
        registry.gauge(SERVICE_ITEMS_PENDING).set(pending
                                                  + len(self._retry))
        registry.gauge(SERVICE_ITEMS_ASSIGNED).set(len(self._inflight))
        registry.gauge(SERVICE_JOBS).set(len(self._jobs))

    # -- dispatcher thread ---------------------------------------------------

    def run(self):
        import zmq

        context = zmq.Context()
        sock = context.socket(zmq.ROUTER)
        try:
            if self._requested_endpoint.endswith(':0'):
                base = self._requested_endpoint.rsplit(':', 1)[0]
                port = sock.bind_to_random_port(base)
                self.endpoint = '%s:%d' % (base, port)
            else:
                sock.bind(self._requested_endpoint)
                self.endpoint = self._requested_endpoint
        except Exception as e:  # noqa: BLE001 - surfaced to start()
            self._fatal_error = RuntimeError(
                'Dispatcher failed to bind %r: %s'
                % (self._requested_endpoint, e))
            self._bound.set()
            sock.close(linger=0)
            context.term()
            return
        self._sock = sock
        self._bound.set()

        last_sweep = time.monotonic()
        last_tick = last_sweep
        backlog_prev = False
        try:
            while not self._stop_event.is_set():
                self._flush_backlogs()
                # Time spent with completions backlogged behind a full
                # LOCAL consumer queue is the service-side back-pressure
                # clock: the fleet is quiesced because the CONSUMER is
                # slow — producer wait, consumer-bound evidence (the
                # remote workers never block locally; their out channel
                # is the dispatcher, so this is measured here). Client
                # jobs' credit gates are deliberately NOT on this clock:
                # a remote consumer's stall is that job's back-pressure,
                # not this process's. An interval counts only when the
                # backlog existed at BOTH of its ends: charging the
                # interval in which a backlog first appeared would bill
                # message-handling time that preceded it as a stall.
                tick = time.monotonic()
                backlogged = self._local_backlogged()
                if backlogged and backlog_prev:
                    note_producer_wait(tick - last_tick)
                backlog_prev = backlogged
                last_tick = tick
                # While completions are backlogged the consumer's next free
                # queue slot is the event that matters, and ZMQ cannot wake
                # us for it — poll short so drained slots refill within
                # ~5ms instead of a full poll interval (otherwise every
                # marker behind a full queue costs the consumer a phantom
                # ~50ms starvation wait).
                poll_ms = 5 if backlogged else _POLL_INTERVAL_MS
                if sock.poll(poll_ms):
                    # Drain everything queued before scheduling: completions
                    # free credit that the assignment pass below can use.
                    while True:
                        try:
                            frames = sock.recv_multipart(zmq.NOBLOCK)
                        except zmq.Again:
                            break
                        if faults.ARMED and faults.fault_hit(
                                'zmq.recv',
                                key=frames[1] if len(frames) > 1
                                else b'') == 'drop':
                            continue  # injected: message lost in flight
                        self._handle(sock, frames)
                self._assign(sock)
                now = time.monotonic()
                if now - last_sweep >= self._heartbeat_interval_s:
                    last_sweep = now
                    self._sweep(now)
                    self._update_fleet_gauges()
        except Exception as e:  # noqa: BLE001 - fatal for the whole pool
            logger.exception('Dispatcher loop died')
            self._fatal_error = e
        finally:
            for _ in range(_STOP_BROADCASTS):
                for identity in list(self._workers):
                    if faults.ARMED and faults.fault_hit(
                            'zmq.stop', key=identity) == 'drop':
                        continue  # injected: died without goodbye
                    try:
                        sock.send_multipart([identity, proto.MSG_STOP],
                                            flags=zmq.NOBLOCK)
                    except Exception:  # noqa: BLE001 - peer may be gone
                        count_swallowed('dispatcher-stop-broadcast')
                time.sleep(_POLL_INTERVAL_MS / 1000.0)
            self._sock = None
            sock.close(linger=500)
            context.term()

    # -- message handling ----------------------------------------------------

    def _handle(self, sock, frames):
        identity, msg = frames[0], frames[1]
        now = time.monotonic()
        if msg == proto.MSG_REGISTER:
            worker = self._workers.get(identity)
            if worker is None:
                worker = _WorkerState(identity, now)
                self._workers[identity] = worker
                self._workers_seen += 1
                logger.info('Worker %s registered (%d registered)',
                            identity, len(self._workers))
            else:
                worker.last_heartbeat = now
            if len(frames) > 2:
                try:
                    worker.pid = int(frames[2])
                except ValueError:
                    pass  # old/foreign build: pid arrives via summaries
            if len(frames) > 3:
                # optional cache-fingerprint advert (JSON list): the
                # worker's host already holds these decoded caches, so
                # binding MUST see them before its first heartbeat
                # summary arrives — placement at registration time is
                # the whole point (docs/service.md). Absent from older
                # builds; a bad frame degrades to no advert.
                self._note_cache_advert(worker, frames[3])
            if len(frames) > 4 and self._peer_enabled:
                # fleet cache tier: the FULL decoded-entry advert from
                # the worker's startup scan — the directory is complete
                # for this holder before its first WORK is assigned
                self._peer_dir.note_advert(
                    identity, proto.load_json_params(frames[4]))
            if worker.job_id is None:
                self._bind_worker(worker)
            job = self._jobs.get(worker.job_id)
            if job is not None:
                sock.send_multipart([identity, proto.MSG_SPEC,
                                     job.spec_payload, self.token])
            # no job to serve yet: stay silent — the worker re-sends
            # REGISTER with backoff (its re-sends double as liveness)
            self._update_fleet_gauges()
        elif msg == proto.MSG_READY:
            worker = self._workers.get(identity)
            if worker is not None:
                worker.ready = (not worker.cordoned
                                and worker.preempted_to is None)
                worker.last_heartbeat = now
        elif msg == proto.MSG_HEARTBEAT:
            summary = None
            if len(frames) > 2:
                # optional trailing frames: the worker's per-heartbeat
                # observability summary (docs/telemetry.md fleet view;
                # b'' when its advisory path degraded) and — its own
                # frame, never inside the summary, because correctness
                # must not ride an advisory channel — the worker's job
                # token. Absent from older builds; a bad summary frame
                # degrades to None and liveness never depends on either.
                summary = proto.load_obs_summary(frames[2])
            # a worker still serving ANOTHER dispatcher incarnation's
            # job (this one replaced it on the endpoint) advertises that
            # incarnation's token: keep its liveness, never assign it
            # work — our ACK's token will send it back to registration
            foreign = len(frames) > 3 and frames[3] != self.token
            worker = self._workers.get(identity)
            if worker is None:
                # A lapsed worker resurfacing (its items were already
                # re-ventilated): it still holds the spec and a live
                # decode worker OF THE JOB IT LAPSED FROM, so it may
                # only re-bind there — never to the least-loaded job,
                # which under multi-tenancy could be a different spec.
                worker = _WorkerState(identity, now)
                self._workers[identity] = worker
                lapsed_job = self._jobs.get(
                    self._lapsed_bindings.pop(identity, None))
                if foreign:
                    worker.ready = False
                elif lapsed_job is not None:
                    worker.job_id = lapsed_job.job_id
                    lapsed_job.workers.add(identity)
                    worker.ready = True
                else:
                    # its job is gone (or the binding aged out): STOP it
                    # back through registration so it picks up a LIVE
                    # job's spec instead of idling on a dead one
                    worker.ready = False
                    self._send_worker(identity, [proto.MSG_STOP])
                logger.info('Worker %s re-admitted after lapse%s',
                            identity,
                            ' (foreign incarnation; not assignable)'
                            if foreign else
                            ('' if lapsed_job is not None
                             else ' (job gone; sent back to register)'))
            else:
                worker.last_heartbeat = now
                if foreign:
                    worker.ready = False
            if summary is not None:
                self._worker_obs[identity] = summary
                fps = summary.get('cache_fp')
                if isinstance(fps, list):
                    worker.cache_fps.update(str(fp) for fp in fps if fp)
                if self._peer_enabled:
                    peer = summary.get('peer')
                    if peer:
                        # bounded add/evict/touch delta of the worker's
                        # decoded-cache entries (fleet cache tier)
                        self._peer_dir.note_advert(identity, peer)
            ack = [identity, proto.MSG_HEARTBEAT_ACK, self.token]
            if self._peer_enabled:
                hints = self._peer_dir.take_hints(identity)
                if hints:
                    # advisory global-eviction hints ride the ACK as one
                    # additive trailing frame (old workers ignore it)
                    ack.append(proto.dump_json_params({'evict': hints}))
                    self._peer_evict_hints_sent += len(hints)
                    if not metrics_disabled():
                        get_registry().counter(
                            PEER_CACHE_EVICT_HINTS).inc(len(hints))
            sock.send_multipart(ack)
        elif msg == proto.MSG_DONE:
            item_id = proto.unpack_item_id(frames[2])
            # frames: [identity, DONE, item_id, metrics, result*]. The
            # wire has no version marker, and externally-started worker
            # servers may run a pre-telemetry build whose DONE is
            # [identity, DONE, item_id, result*] — so the slot is claimed
            # as metrics ONLY when it is empty (b'': "nothing changed")
            # or passes load_metrics_delta's strict delta-shape check;
            # otherwise it is treated as the first result frame. Dropping
            # a result would be silent row loss; misreading one as a
            # delta is made implausible by the strict shape.
            payload = frames[3:]
            if payload and (payload[0] == b''
                            or self._merge_metrics(payload[0])):
                payload = payload[1:]
            self._complete(identity, item_id, ('result', payload), now)
        elif msg == proto.MSG_ERROR:
            item_id = proto.unpack_item_id(frames[2])
            exc = proto.load_exception(frames[3])
            if len(frames) > 4:
                self._merge_metrics(frames[4])
            self._fail(identity, item_id, exc, now)
        elif msg == proto.MSG_BYE:
            self._deregister(identity, 'said goodbye')
        elif msg == proto.MSG_DIR_GET:
            # fleet cache-tier directory lookup: a worker's peer-cache
            # client asking (on its OWN DEALER) who holds these entry
            # digests. Disabled tier answers the empty map — the asker
            # negative-caches and decodes locally. A malformed request
            # costs that request, nothing else.
            import json
            try:
                digests = json.loads(frames[2].decode('utf-8')) \
                    if len(frames) > 2 else []
                if not isinstance(digests, list):
                    digests = []
            except Exception:  # noqa: BLE001 - the directory is advisory
                count_swallowed('dispatcher-dirget')
                digests = []
            mapping = (self._peer_dir.lookup(digests[:_DIRGET_CAP])
                       if self._peer_enabled else {})
            sock.send_multipart([identity, proto.MSG_DIR,
                                 proto.dump_json_params(mapping)])
        elif msg == proto.MSG_STANDBY_SYNC:
            # a warm standby pulling its replication snapshot
            # (docs/service.md, "High availability"). The drop faultpoint
            # models a severed replication stream: the standby's snapshot
            # goes stale (or stays empty) and a later promotion degrades
            # to a cold promote — which the chaos suite proves is still
            # multiset-exact, just slower to re-admit.
            if faults.ARMED and faults.fault_hit(
                    'zmq.replicate', key=identity) == 'drop':
                return  # injected: snapshot lost in flight
            self._standby_syncs_served += 1
            self._last_standby_sync = now
            sock.send_multipart(
                [identity, proto.MSG_STANDBY_STATE, self.token,
                 proto.dump_standby_state(self.standby_snapshot())])
        elif msg in (proto.MSG_REGISTER_JOB, proto.MSG_SUBMIT,
                     proto.MSG_CLIENT_HB, proto.MSG_JOB_GONE):
            # client frames are OTHER PROCESSES' input: a malformed one
            # (truncated multipart, unparseable field) must cost that
            # frame, never the daemon — run()'s catch-all treats an
            # escaped exception as fatal for every co-tenant job
            try:
                self._handle_client_frame(sock, identity, msg, frames,
                                          now)
            except Exception:  # noqa: BLE001 - one bad client, not all
                logger.warning('Malformed client frame %r from %s '
                               'dropped', msg, identity, exc_info=True)
                count_swallowed('daemon-malformed-client-frame')
        else:
            logger.warning('Unknown service message type %r from %s',
                           msg, identity)

    def _handle_client_frame(self, sock, identity, msg, frames, now):
        if msg == proto.MSG_REGISTER_JOB:
            self._handle_register_job(sock, identity, frames, now)
        elif msg == proto.MSG_SUBMIT:
            self._handle_submit(sock, identity, frames, now)
        elif msg == proto.MSG_CLIENT_HB:
            self._handle_client_hb(sock, identity, frames, now)
        elif msg == proto.MSG_JOB_GONE:
            job = self._job_for_client(identity, frames[2])
            if job is not None:
                self._remove_job(job, 'client goodbye')

    # -- client-job handling (the standing-service registry) -----------------

    def _job_for_client(self, identity, job_id_frame):
        """The registry entry for a client frame, or None (expired /
        never existed / spoofed identity)."""
        try:
            job_id = int(job_id_frame)
        except (TypeError, ValueError):
            return None
        job = self._jobs.get(job_id)
        if job is None or job.client != identity:
            return None
        return job

    def _handle_register_job(self, sock, identity, frames, now):
        params = proto.load_json_params(frames[3] if len(frames) > 3
                                        else b'')
        client_key = params.get('key')
        # idempotent re-registration: a client whose JOB_OK was lost, who
        # timed out waiting, or who reconnected on a FRESH socket (new
        # ZMQ identity after an ack-timeout blip) re-sends REGISTER_JOB
        # with the same key — answer with the existing job instead of
        # double-registering. Matching is on the key ALONE (a 32-hex
        # client-minted uuid): the identity changes with every socket,
        # so requiring it to match would defeat exactly the reconnect
        # case; the rebind below points the job's results at the
        # client's live identity.
        # key-alone matching also covers a job SEEDED from a promoted
        # standby's snapshot (client=None until its owner re-registers
        # with this incarnation): the reconnecting client re-binds to
        # the job identity the dead primary leased it — same id, same
        # key — instead of double-registering
        if client_key:
            for job in self._jobs.values():
                if job.client_key == client_key:
                    job.client = identity
                    # reconcile the delivery-credit clock: markers sent
                    # toward the OLD identity during the blip were
                    # dropped by the ROUTER and will never be acked —
                    # left counted, they would inflate the unacked
                    # window forever (a full window would gate the job
                    # permanently). Zeroing the window is safe: the
                    # client re-submits every un-markered item, and
                    # each re-delivery re-counts. If the identity never
                    # actually changed (a lost JOB_OK re-send), live
                    # in-flight markers go briefly under-counted — the
                    # gate opens LATE by at most one credit window,
                    # bounded, never wedged.
                    job.markers_sent = job.markers_acked
                    # frames backlogged for the old socket must NOT
                    # flush to the new one: a stale bare marker (its
                    # result frames died with the old socket) would make
                    # the client count the item delivered with zero rows
                    # and drop the re-decoded real delivery. Every
                    # out-resident item is un-markered client-side, and
                    # every registration is followed by re-submission —
                    # dropping the backlog loses nothing.
                    job.out.clear()
                    job.last_client_seen = now
                    sock.send_multipart([identity, proto.MSG_JOB_OK,
                                         b'%d' % job.job_id, self.token])
                    return
        refusal = None
        if self._draining:
            refusal = {'reason': 'draining'}
        elif len(self._jobs) >= self._max_jobs:
            refusal = {'reason': 'saturated', 'jobs': len(self._jobs),
                       'max_jobs': self._max_jobs}
        if refusal is not None:
            # admission control: a retryable refusal, never an error —
            # the client backs off and retries within its own deadline
            sock.send_multipart([identity, proto.MSG_BUSY,
                                 proto.dump_json_params(refusal)])
            return
        lease_s = params.get('lease_s')
        lease_s = float(lease_s) if lease_s else self._default_lease_s
        credit = params.get('credit')
        credit = int(credit) if credit else None
        with self._lock:
            self._job_seq += 1
            job = _Job(self._job_seq, frames[2], client=identity,
                       client_key=client_key, lease_s=lease_s,
                       credit=credit, name=params.get('name'),
                       weight=params.get('weight'),
                       priority=params.get('priority'),
                       fingerprint=params.get('fingerprint'))
            job.last_client_seen = now
            self._jobs[job.job_id] = job
        self._jobs_seen += 1
        logger.info('Job %d (%s) registered; %d active', job.job_id,
                    job.name, len(self._jobs))
        tracing.record_instant('job_register', tracing.mint(job.job_id),
                               'daemon', job=job.job_id,
                               job_name=job.name)
        sock.send_multipart([identity, proto.MSG_JOB_OK,
                             b'%d' % job.job_id, self.token])
        self._rebalance_for(job)
        self._update_fleet_gauges()

    def _handle_submit(self, sock, identity, frames, now):
        job = self._job_for_client(identity, frames[2])
        if job is None:
            # the job is gone (lease lapsed, daemon restarted): tell the
            # client so it can re-register and re-submit what its own
            # accounting says is still owed — never silently eat work
            sock.send_multipart([identity, proto.MSG_JOB_EXPIRED,
                                 frames[2]])
            return
        job.last_client_seen = now
        cid = int(frames[3])
        if cid in job.live_cids:
            # a reconnected client re-submitting an item this job still
            # holds (registration survived the socket reset): one copy
            # is enough — the client's own cid accounting would drop the
            # second delivery anyway, so dedup here saves the decode
            return
        self.submit(frames[4], job_id=job.job_id, client_item_id=cid)

    def _handle_client_hb(self, sock, identity, frames, now):
        job = self._job_for_client(identity, frames[2])
        if job is None:
            sock.send_multipart([identity, proto.MSG_JOB_EXPIRED,
                                 frames[2]])
            return
        job.last_client_seen = now
        try:
            acked = int(frames[3])
        except (IndexError, ValueError):
            acked = job.markers_acked
        # monotonic: a reordered older heartbeat must not re-open credit
        job.markers_acked = max(job.markers_acked, acked)
        status = {
            'workers_alive': sum(
                1 for w in self._workers.values()
                if now - w.last_heartbeat <= self._liveness_timeout_s),
            'workers_registered': len(self._workers),
            'job_workers': len(job.workers),
            'jobs_active': len(self._jobs),
            'pending': len(job.pending),
            'unacked': job.markers_sent - job.markers_acked,
            'draining': self._draining,
        }
        sock.send_multipart([identity, proto.MSG_CLIENT_HB_ACK, self.token,
                             proto.dump_json_params(status)])

    def _remove_job(self, job, reason):
        """Take one job out of the registry: purge its waiting items,
        reclaim its in-flight work (late completions dedup away), and
        STOP its workers back into the registration pool so surviving
        jobs inherit them. Co-tenant jobs are untouched."""
        with self._lock:
            # under the lock: submit() (any pool/ventilator thread)
            # inserts into _item_job concurrently, and iterating it
            # unlocked would be a dict-changed-size crash in the
            # scheduler thread
            owned = {i for i, j in self._item_job.items()
                     if j == job.job_id}
            self._jobs.pop(job.job_id, None)
            purged_pending = len(job.pending)
            job.pending.clear()
            job.pending_ids.clear()
        if self._retry and any(e[2] in owned for e in self._retry):
            self._retry = [e for e in self._retry if e[2] not in owned]
            heapq.heapify(self._retry)
        reclaimed = 0
        for item_id in owned:
            self._item_job.pop(item_id, None)
            entry = self._inflight.pop(item_id, None)
            if entry is not None:
                reclaimed += 1
                owner = self._workers.get(entry[0])
                if owner is not None:
                    owner.inflight.discard(item_id)
                # a late DONE for reclaimed work must dedup away: the
                # job it belonged to no longer exists to deliver to
                self._done.add(item_id)
            self._attempts.pop(item_id, None)
            self._last_error.pop(item_id, None)
            self._trace_ctx.pop(item_id, None)
            self._item_owners.pop(item_id, None)
        job.client_item_ids.clear()
        job.live_cids.clear()
        job.out.clear()
        for identity in list(job.workers):
            worker = self._workers.get(identity)
            if worker is not None:
                worker.job_id = None
                worker.ready = False
                self._send_worker(identity, [proto.MSG_STOP])
        job.workers.clear()
        logger.warning('Job %d (%s) removed (%s): %d pending purged, '
                       '%d in-flight reclaimed', job.job_id, job.name,
                       reason, purged_pending, reclaimed)
        tracing.record_instant('job_gone', tracing.mint(job.job_id),
                               'daemon', job=job.job_id, reason=reason,
                               pending=purged_pending, inflight=reclaimed)
        self._update_fleet_gauges()

    def _send_worker(self, identity, frames):
        """Best-effort dispatcher-thread send to a worker peer."""
        import zmq
        if self._sock is None:
            return
        try:
            self._sock.send_multipart([identity] + frames,
                                      flags=zmq.NOBLOCK)
        except Exception:  # noqa: BLE001 - peer may be gone
            count_swallowed('dispatcher-worker-send')

    # -- worker <-> job binding ----------------------------------------------

    def _note_cache_advert(self, worker, frame):
        """Fold a worker's REGISTER-time cache advert (JSON list of
        decode fingerprints) into its fleet cache-directory entry. A
        bad frame degrades to no advert — placement is advisory."""
        import json
        try:
            fps = json.loads(bytes(frame).decode('utf-8'))
        except Exception:  # noqa: BLE001 - placement is advisory
            count_swallowed('dispatcher-cache-advert')
            return
        if isinstance(fps, list):
            worker.cache_fps.update(str(fp) for fp in fps if fp)

    def _bind_worker(self, worker):
        """Bind a fresh/unbound worker to the job that needs it most:
        jobs with pending work before idle ones (a drained tier —
        however senior — must not hoard fresh workers while a co-tenant
        has rows waiting; priority gates SERVICE, not possession), then
        highest priority tier, then lowest weight-normalized load, then
        cache-aware placement (the job whose decode fingerprint the
        worker's host already advertises wins the tie — its cache is
        warm there), ties to the oldest job. With default QoS params and
        no fingerprints this reduces exactly to the original
        least-loaded-first binding."""
        candidates = [job for job in self._jobs.values()]
        if not candidates:
            return None

        def warmth(job):
            if not (self._placement_enabled and job.fingerprint):
                return 1
            return 0 if job.fingerprint in worker.cache_fps else 1

        job = min(candidates,
                  key=lambda j: (0 if j.pending else 1,
                                 -j.priority if j.pending else 0,
                                 len(j.workers) / j.weight,
                                 warmth(j), j.job_id))
        if self._placement_enabled and job.fingerprint:
            if job.fingerprint in worker.cache_fps:
                self._placement_hits += 1
                if not metrics_disabled():
                    get_registry().counter(SERVICE_PLACEMENT_HITS).inc()
            else:
                self._placement_misses += 1
                if not metrics_disabled():
                    get_registry().counter(SERVICE_PLACEMENT_MISSES).inc()
        worker.job_id = job.job_id
        job.workers.add(worker.identity)
        return job

    def _rebalance_for(self, needy_job):
        """A newly-registered job with zero workers steals ONE idle
        worker from the best-served job (STOP → the worker re-registers
        with a fresh identity and lands on the needy job via
        :meth:`_bind_worker`); further convergence happens one worker
        per sweep, bounding churn."""
        if needy_job.workers:
            return
        self._rebalance_step()

    def _rebalance_step(self):
        """At most one worker moves per call: find the most-served and
        least-served jobs by WEIGHT-NORMALIZED load; when the move
        narrows the normalized gap (at equal weights: the raw gap
        exceeds one worker) or the least-served has none, STOP one IDLE
        worker of the donor. Idle only: STOPping a busy worker would
        re-ventilate its items and charge their retry budgets for a
        scheduling decision. Priority preemption runs first — it is the
        one path allowed to cordon a BUSY worker (drained at row-group
        granularity, never mid-item)."""
        self._preempt_step()
        jobs = list(self._jobs.values())
        if len(jobs) < 2:
            return
        # demand classes before load: a job with NO pending work is the
        # preferred donor (its workers are idle capital) and is never
        # needy, whatever its weight-normalized load — without this, an
        # idle high-priority job and a pending-first _bind_worker churn
        # a STOP/rebind loop while a busy co-tenant starves
        donor = max(jobs, key=lambda j: (0 if j.pending else 1,
                                         len(j.workers) / j.weight,
                                         -j.job_id))
        needy = min(jobs, key=lambda j: (0 if j.pending else 1,
                                         len(j.workers) / j.weight,
                                         j.job_id))
        if donor is needy or not donor.workers:
            return
        if needy.priority < donor.priority and donor.pending:
            # strict priority: a busy higher tier keeps its fleet — the
            # lower tier waits (the documented starvation semantics,
            # docs/troubleshoot.md). An IDLE higher tier still donates.
            return
        starved = len(needy.workers) == 0 and bool(needy.pending)
        idle_donor = not donor.pending and bool(needy.pending)
        donor_after = (len(donor.workers) - 1) / donor.weight
        needy_after = (len(needy.workers) + 1) / needy.weight
        if donor_after < needy_after and not starved and not idle_donor:
            # starved: a zero-worker job WITH pending work may steal an
            # idle worker even from a one-worker donor — with more jobs
            # than workers that degenerates to time-multiplexing at
            # sweep cadence (the donor steals back when ITS queue is
            # the starved one): crude, but strictly better than the 9th
            # job wedging against a fully-partitioned fleet.
            # idle_donor: a drained job's workers all flow to a pending
            # co-tenant, one per sweep, whatever the load gap says.
            return
        for identity in list(donor.workers):
            worker = self._workers.get(identity)
            if worker is None or worker.inflight or worker.cordoned \
                    or worker.preempted_to is not None:
                continue
            worker.job_id = None
            worker.ready = False
            donor.workers.discard(identity)
            self._send_worker(identity, [proto.MSG_STOP])
            logger.info('Rebalancing: moved worker %s off job %d toward '
                        'job %d', identity, donor.job_id, needy.job_id)
            return

    def _preempt_step(self):
        """Priority admission: the highest-priority job with pending
        work and no workers' worth of service takes ONE worker per sweep
        from a lower tier. An idle victim moves immediately (same STOP →
        re-register → priority-first rebind path as rebalancing); a busy
        one is marked ``preempted_to`` — no new assignments, drained at
        row-group granularity, moved once its in-flight empties — so
        exactly-once accounting is untouched and the preempted job is
        never charged a retry or a quarantine for the scheduling
        decision."""
        # release drained preempted workers first: their in-flight hit
        # zero since the mark, so the move completes this sweep
        for worker in list(self._workers.values()):
            if worker.preempted_to is None or worker.inflight:
                continue
            old_job = self._jobs.get(worker.job_id)
            if old_job is not None:
                old_job.workers.discard(worker.identity)
            worker.job_id = None
            worker.ready = False
            worker.preempted_to = None
            self._send_worker(worker.identity, [proto.MSG_STOP])
        jobs = [j for j in self._jobs.values()]
        if len(jobs) < 2:
            return
        contenders = [j for j in jobs
                      if j.pending and not j.gated() and not j.workers]
        if not contenders:
            return
        high = max(contenders, key=lambda j: (j.priority, -j.job_id))
        victims = [j for j in jobs if j.priority < high.priority
                   and j.workers]
        if not victims:
            return
        victim = max(victims, key=lambda j: (len(j.workers) / j.weight,
                                             -j.priority))
        # prefer an idle victim worker (moves this sweep); else cordon
        # one busy worker to drain — skip ones already marked
        chosen = None
        for identity in sorted(victim.workers):
            worker = self._workers.get(identity)
            if worker is None or worker.cordoned \
                    or worker.preempted_to is not None:
                continue
            if chosen is None or (chosen.inflight and not worker.inflight):
                chosen = worker
            if not worker.inflight:
                break
        if chosen is None:
            return
        self._preemptions += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_PREEMPTIONS).inc()
        tracing.record_instant(
            'job_preempt', tracing.mint(high.job_id), 'daemon',
            job=high.job_id, victim_job=victim.job_id,
            worker=chosen.identity.decode('utf-8', 'replace'),
            draining=bool(chosen.inflight))
        logger.warning('Preempting worker %s from job %d (priority %d) '
                       'toward job %d (priority %d)%s', chosen.identity,
                       victim.job_id, victim.priority, high.job_id,
                       high.priority,
                       ' after drain' if chosen.inflight else '')
        if chosen.inflight:
            chosen.preempted_to = high.job_id
            chosen.ready = False
            return
        victim.workers.discard(chosen.identity)
        chosen.job_id = None
        chosen.ready = False
        self._send_worker(chosen.identity, [proto.MSG_STOP])

    def _merge_metrics(self, frame):
        """Fold one worker server's piggybacked telemetry delta into this
        (client) process's registry — the dispatcher is where per-worker
        deltas become the fleet-wide aggregate. Returns whether the frame
        WAS a delta (the DONE path uses this to tell the metrics slot from
        a result frame sent by a pre-telemetry worker build). Duplicate
        completions double-merge in the worst case (telemetry is advisory;
        item delivery, not metrics, carries the exactly-once guarantee)."""
        delta = proto.load_metrics_delta(frame)
        if delta is None:
            return False
        self._metrics_deltas_merged += 1
        merge_worker_delta(delta)
        return True

    def _complete(self, identity, item_id, outcome, now):
        worker = self._workers.get(identity)
        if worker is not None:
            worker.last_heartbeat = now
            worker.inflight.discard(item_id)
        if item_id in self._done:
            # Duplicate completion from a lapsed-then-reassigned race (or
            # a late completion of lease-reclaimed work); the first DONE
            # already delivered this item's rows — or the job that owned
            # them was already declared gone.
            logger.debug('Dropping duplicate completion of item %d from %s',
                         item_id, identity)
            self._duplicate_done_count += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_DUPLICATE_DONE).inc()
            # both completions have now been seen: the trace entry has
            # served its purpose (the dedup drop is marked on the timeline)
            dup_entry = self._trace_ctx.pop(item_id, None)
            if dup_entry is not None:
                tracing.record_instant(
                    'duplicate_done', dup_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'))
            return
        if identity not in self._item_owners.get(item_id, ()):
            # a completion from a worker this dispatcher NEVER assigned
            # the item to: stale cross-incarnation traffic (a restarted
            # daemon's id space collides with its predecessor's) — the
            # rows belong to some OTHER item/job and accepting them
            # would be silent duplication plus silent loss
            logger.warning('Dropping completion of item %d from %s: not '
                           'an owner (stale cross-incarnation frame?)',
                           item_id, identity)
            count_swallowed('dispatcher-stale-completion')
            return
        job = self._jobs.get(self._item_job.get(item_id))
        assignment = self._inflight.pop(item_id, None)
        if assignment is None:
            # Ghost completion: the item lapsed back onto the pending queue
            # (or the retry backoff heap) but its original owner finished
            # after all. Accept the result and withdraw the waiting copy
            # so it is not run twice.
            if not self._withdraw_waiting(item_id):
                logger.warning('Completion of unknown item %d from %s '
                               'dropped', item_id, identity)
                return
        else:
            owner = self._workers.get(assignment[0])
            if owner is not None:
                owner.inflight.discard(item_id)
        if job is None:
            # unreachable in practice (jobless live items are purged with
            # their job), kept as a loud guard instead of a KeyError in
            # the scheduler thread
            logger.warning('Completion of item %d belongs to no live job',
                           item_id)
            return
        # a delivered completion clears the item's suspect record: its
        # budget was for THIS traversal, and innocent items that shared a
        # dying worker must not carry the black mark forever
        self._attempts.pop(item_id, None)
        self._last_error.pop(item_id, None)
        if item_id in self._risky_ids:
            self._done.add(item_id)
            # a risky item keeps its trace entry so a RACED second DONE
            # can be marked as deduped — but a SIGKILLed first owner never
            # sends one, so stamp the completion time and let the sweep
            # age the entry out (the ghost race window is a few liveness
            # timeouts at most); without this the map would grow with
            # failure churn for the life of the process
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None and trace_entry.completed_at is None:
                trace_entry.completed_at = now
        else:
            trace_entry = self._trace_ctx.pop(item_id, None)
        if trace_entry is not None:
            # the item's ONE delivered completion
            tracing.record_instant(
                'done', trace_entry.ctx, 'dispatcher',
                worker=identity.decode('utf-8', 'replace'),
                attempts=trace_entry.attempts, outcome=outcome[0])
        self._completed_count += 1
        self._item_job.pop(item_id, None)
        self._item_owners.pop(item_id, None)
        job.completed += 1
        kind, payload = outcome
        if kind == 'result':
            for result_frame in payload:
                self._emit(job, item_id, ('result', result_frame))
        else:
            self._emit(job, item_id, ('error', payload))
        self._emit(job, item_id, ('marker', item_id))

    # -- delivery (local callback or client RESULT frames) -------------------

    def _emit(self, job, item_id, entry):
        """Hand one entry toward ``job``'s consumer, preserving order:
        direct only while the job's backlog is empty AND the destination
        (bounded queue / socket) has room."""
        if job.is_local:
            if job.out or not self._deliver(entry):
                job.out.append(entry)
            return
        kind = entry[0]
        cid = job.client_item_ids.get(item_id)
        if kind == 'marker':
            job.client_item_ids.pop(item_id, None)
            if cid is not None:
                job.live_cids.discard(cid)
            job.markers_sent += 1
        cid_frame = b'%d' % (cid if cid is not None else -1)
        if kind == 'result':
            frames = [proto.MSG_RESULT, b'result', cid_frame, entry[1]]
        elif kind == 'error':
            frames = [proto.MSG_RESULT, b'error', cid_frame,
                      proto.dump_exception(entry[1])]
        elif kind == 'poisoned':
            frames = [proto.MSG_RESULT, b'poisoned', cid_frame,
                      proto.dump_poisoned_info(entry[1])]
        else:
            frames = [proto.MSG_RESULT, b'marker', cid_frame]
        if job.out or not self._send_client(job, frames):
            job.out.append(frames)

    def _send_client(self, job, frames):
        """Non-blocking RESULT send toward a client job; False when the
        socket's peer pipe is momentarily full (the frames then wait in
        the job's backlog — rare: credit gating keeps in-flight results
        far below the HWM)."""
        import zmq
        if self._sock is None:
            return True  # shutting down: accounting no longer matters
        try:
            self._sock.send_multipart([job.client] + frames,
                                      flags=zmq.NOBLOCK)
            return True
        except zmq.Again:
            return False
        except Exception:  # noqa: BLE001 - peer gone; lease will reap
            count_swallowed('daemon-client-send')
            return True

    def _local_backlogged(self):
        job = self._jobs.get(LOCAL_JOB_ID)
        return bool(job is not None and job.out)

    def _flush_backlogs(self):
        for job in list(self._jobs.values()):
            if job.is_local:
                while job.out:
                    if not self._deliver(job.out[0]):
                        break
                    job.out.popleft()
            else:
                while job.out:
                    if not self._send_client(job, job.out[0]):
                        break
                    job.out.popleft()

    # -- failure handling: retry budget, backoff, quarantine -----------------

    def _withdraw_waiting(self, item_id):
        """Remove a waiting (pending or backoff-heap) copy of ``item_id``
        after a ghost completion delivered it; False when no copy was
        waiting (a genuinely unknown completion)."""
        job = self._jobs.get(self._item_job.get(item_id))
        if job is None:
            return False
        with self._lock:
            if item_id not in job.pending_ids:
                return False
            job.pending_ids.discard(item_id)
            job.pending = collections.deque(
                (i, p) for i, p in job.pending if i != item_id)
        if any(entry[2] == item_id for entry in self._retry):
            self._retry = [entry for entry in self._retry
                           if entry[2] != item_id]
            heapq.heapify(self._retry)
        return True

    def _fail(self, identity, item_id, exc, now):
        """One failed worker attempt (an ERROR frame): charge the item's
        retry budget and reschedule with backoff — or quarantine."""
        worker = self._workers.get(identity)
        if worker is not None:
            worker.last_heartbeat = now
            worker.inflight.discard(item_id)
        if item_id in self._done:
            # raced failure of an item whose ghost already delivered —
            # same dedup shape as a duplicate DONE
            self._duplicate_done_count += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_DUPLICATE_DONE).inc()
            return
        assignment = self._inflight.get(item_id)
        if assignment is None:
            # ghost failure from a lapsed owner; the re-ventilated copy
            # is already waiting (or assigned) and will speak for itself
            return
        if assignment[0] != identity:
            # ghost ERROR from a PRIOR owner racing its replacement: the
            # live assignment stands — cancelling it here would charge a
            # phantom attempt and let the item run twice concurrently
            return
        self._inflight.pop(item_id)
        self._record_failure(item_id, assignment[1],
                             'worker error: %s: %s'
                             % (type(exc).__name__, exc), exc, now)

    @staticmethod
    def _jitter(item_id, attempt):
        """Deterministic backoff jitter factor in [0.5, 1.5): seeded by
        the item identity so replayed chaos runs reschedule identically
        (no ``random`` module state involved)."""
        return 0.5 + ((item_id * 2654435761 + attempt * 40503)
                      % 4093) / 4093.0

    def _record_failure(self, item_id, payload, reason, exc, now):
        """Charge one failed attempt. Under budget: backoff-requeue.
        Budget exhausted: quarantine."""
        job = self._jobs.get(self._item_job.get(item_id))
        if job is None:
            return  # the owning job is gone; nothing left to retry FOR
        attempt = self._attempts.get(item_id, 0) + 1
        self._attempts[item_id] = attempt
        if exc is not None:
            self._last_error[item_id] = exc
        if attempt >= self._max_retries:
            self._quarantine(job, item_id, reason, now)
            return
        delay = (self._retry_backoff_s * (2 ** (attempt - 1))
                 * self._jitter(item_id, attempt))
        heapq.heappush(self._retry,
                       (now + delay, self._retry_seq, item_id, payload))
        self._retry_seq += 1
        with self._lock:
            job.pending_ids.add(item_id)
        self._retried_count += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_RETRIES).inc()
        entry = self._trace_ctx.get(item_id)
        if entry is not None:
            tracing.record_instant('retry', entry.ctx, 'dispatcher',
                                   attempt=attempt, reason=reason,
                                   backoff_s=round(delay, 4))
        logger.warning('Item %d failed attempt %d/%d (%s); retrying in '
                       '%.3fs', item_id, attempt, self._max_retries,
                       reason, delay)

    def _quarantine(self, job, item_id, reason, now):
        """Retry budget exhausted: skip the item, record it, surface it.
        The consumer receives a ``('poisoned', info)`` entry (policy
        applied pool-side) plus the accounting marker, so the epoch
        completes with the loss REPORTED instead of the fleet
        crash-looping or the read wedging."""
        attempts = self._attempts.pop(item_id, 0)
        exc = self._last_error.pop(item_id, None)
        # late ghost completions of a quarantined item must dedup away:
        # its rows were declared lost, and delivering them afterwards
        # would turn "reported loss" into silent duplication
        self._done.add(item_id)
        info = {'item_id': item_id, 'attempts': attempts,
                'reason': reason, 'error': exc,
                'max_retries': self._max_retries}
        descriptor = {'item_id': item_id, 'attempts': attempts,
                      'reason': reason,
                      'error': repr(exc) if exc is not None else None,
                      'job_id': job.job_id,
                      'quarantined_at': time.time()}
        self._poisoned[item_id] = descriptor
        while len(self._poisoned) > _POISONED_KEEP:
            self._poisoned.popitem(last=False)
        self._poisoned_count += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_POISONED).inc()
        record_anomaly('row_group_poisoned',
                       detail={k: v for k, v in descriptor.items()
                               if k != 'quarantined_at'})
        trace_entry = self._trace_ctx.pop(item_id, None)
        if trace_entry is not None:
            tracing.record_instant('poisoned', trace_entry.ctx,
                                   'dispatcher', attempts=attempts,
                                   reason=reason)
        self._item_job.pop(item_id, None)
        self._item_owners.pop(item_id, None)
        job.completed += 1
        self._emit(job, item_id, ('poisoned', info))
        self._emit(job, item_id, ('marker', item_id))

    def _promote_due_retries(self, now):
        """Move backoff-expired retries to the FRONT of their job's
        pending queue (oldest first): lapsed work is the oldest and gates
        epoch completion through the ventilator's in-flight bound."""
        due = []
        while self._retry and self._retry[0][0] <= now:
            _, _, item_id, payload = heapq.heappop(self._retry)
            due.append((item_id, payload))
        if due:
            with self._lock:
                for item_id, payload in reversed(due):
                    job = self._jobs.get(self._item_job.get(item_id))
                    if job is not None and item_id in job.pending_ids:
                        job.pending.appendleft((item_id, payload))

    def _pop_assignable(self, job, allow_suspect):
        """Pop the leftmost assignable pending item of ``job``. Suspects
        (items with a failed attempt) are skipped unless
        ``allow_suspect`` — they are only ever assigned alone to an idle
        worker."""
        with self._lock:
            for idx in range(len(job.pending)):
                item_id, payload = job.pending[idx]
                if not allow_suspect and item_id in self._attempts:
                    continue
                del job.pending[idx]
                job.pending_ids.discard(item_id)
                return item_id, payload
        return None

    # -- scheduling ----------------------------------------------------------

    def _assign(self, sock):
        self._promote_due_retries(time.monotonic())
        # Least-loaded first, so a fresh (or re-admitted) worker fills up
        # before busy ones receive more. Each worker draws ONLY from the
        # job it was built for; a gated job (stalled local consumer /
        # spent client credit) idles its slice of the fleet — quiescence,
        # not decay — while co-tenant jobs keep flowing.
        workers = sorted((w for w in self._workers.values() if w.ready),
                         key=lambda w: len(w.inflight))
        for worker in workers:
            job = self._jobs.get(worker.job_id)
            if job is None or job.gated():
                continue
            if any(i in self._attempts for i in worker.inflight):
                # suspect isolation: a worker running a retried item gets
                # NOTHING else — if the item kills it, it dies alone and
                # no innocent item's budget is charged for the crash
                continue
            while len(worker.inflight) < self._max_inflight_per_worker:
                popped = self._pop_assignable(
                    job, allow_suspect=not worker.inflight)
                if popped is None:
                    break
                item_id, payload = popped
                if item_id in self._done:
                    continue
                if faults.ARMED and faults.fault_hit(
                        'zmq.work', key=item_id) == 'drop':
                    pass  # injected: WORK frame lost; accounting intact
                else:
                    work_frames = [worker.identity, proto.MSG_WORK,
                                   proto.pack_item_id(item_id), payload]
                    if self._peer_enabled:
                        # piggyback the directory entries advertised
                        # since this worker's last WORK (one additive
                        # trailing frame, capped; DIRGET covers the rest)
                        version, delta = self._peer_dir.delta_since(
                            worker.peer_dir_seen,
                            exclude_identity=worker.identity)
                        worker.peer_dir_seen = version
                        if delta:
                            work_frames.append(
                                proto.dump_json_params(delta))
                    sock.send_multipart(work_frames)
                self._inflight[item_id] = (worker.identity, payload)
                worker.inflight.add(item_id)
                self._item_owners.setdefault(item_id,
                                             set()).add(worker.identity)
                entry = self._trace_ctx.get(item_id)
                if entry is not None:
                    entry.attempts += 1
                    tracing.record_instant(
                        'dispatch', entry.ctx, 'dispatcher',
                        worker=worker.identity.decode('utf-8', 'replace'),
                        attempt=entry.attempts)
                if item_id in self._attempts:
                    break  # nothing rides along with a suspect

    def _sweep(self, now):
        for identity, worker in list(self._workers.items()):
            window = self._liveness_timeout_s if worker.job_id is not None \
                else max(self._liveness_timeout_s,
                         _UNBOUND_LIVENESS_FLOOR_S)
            if now - worker.last_heartbeat > window:
                self._deregister(
                    identity, 'heartbeat lapsed (%.1fs > %.1fs)'
                    % (now - worker.last_heartbeat, window))
        # job leases: a client that died without a goodbye stops
        # submitting AND heartbeating — reclaim its job so the fleet
        # serves the living (docs/service.md, "Standing service")
        for job in [j for j in list(self._jobs.values())
                    if not j.is_local and j.lease_s]:
            silent_s = now - job.last_client_seen
            if silent_s > job.lease_s:
                self._jobs_expired += 1
                # count in-flight via _inflight (dispatcher-thread-only;
                # iterating _item_job here would race submit()'s
                # under-lock inserts from pool threads)
                inflight_n = sum(
                    1 for iid in self._inflight
                    if self._item_job.get(iid) == job.job_id)
                record_anomaly('job_lease_expired', detail={
                    'job_id': job.job_id, 'name': job.name,
                    'silent_s': round(silent_s, 3),
                    'lease_s': job.lease_s,
                    'pending': len(job.pending),
                    'inflight': inflight_n})
                self._remove_job(
                    job, 'lease expired (%.1fs > %.1fs silent)'
                    % (silent_s, job.lease_s))
        self._rebalance_step()
        if self._peer_enabled \
                and now - self._peer_hint_at > _PEER_HINT_INTERVAL_S:
            # fleet-global eviction pressure, recomputed coarsely: hints
            # queue per worker and drain on heartbeat ACKs; failover
            # seeds nobody re-claimed age out here too
            self._peer_hint_at = now
            self._peer_dir.compute_evict_hints(time.time())
            self._peer_dir.expire_seeds(now)
        # age out trace entries retained past completion for dedup marking
        # (see _complete): a ghost DONE races within ZMQ buffering of one
        # lapse, so several liveness timeouts is a generous window
        retention_s = 10.0 * self._liveness_timeout_s
        stale = [item_id for item_id, entry in list(self._trace_ctx.items())
                 if entry.completed_at is not None
                 and now - entry.completed_at > retention_s]
        for item_id in stale:
            self._trace_ctx.pop(item_id, None)
        with self._lock:
            outstanding = self._pending_total_locked() > 0 \
                or bool(self._inflight) or bool(self._retry)
        if outstanding and not self._workers:
            if self._no_workers_since is None:
                self._no_workers_since = now
            elif not self._standing \
                    and now - self._no_workers_since \
                    > self._no_workers_timeout_s:
                # embedded pools fail fast; a STANDING dispatcher keeps
                # serving — zero workers is the supervisor's condition to
                # repair (respawn), not a reason to take jobs down
                raise RuntimeError(
                    'No live worker servers for %.1fs with work outstanding; '
                    'is the dispatcher endpoint (%s) reachable from the '
                    'workers?' % (self._no_workers_timeout_s, self.endpoint))
        else:
            self._no_workers_since = None

    _LAPSED_BINDINGS_KEEP = 512

    def _deregister(self, identity, reason):
        worker = self._workers.pop(identity, None)
        self._worker_obs.pop(identity, None)
        self._peer_dir.drop(identity)
        if worker is None:
            return
        job = self._jobs.get(worker.job_id)
        if job is not None:
            job.workers.discard(identity)
            # remember the binding: if this worker resurfaces (it was
            # stalled, not dead) it must re-bind HERE — it still runs
            # this job's spec
            self._lapsed_bindings[identity] = worker.job_id
            while len(self._lapsed_bindings) > self._LAPSED_BINDINGS_KEEP:
                self._lapsed_bindings.popitem(last=False)
        now = time.monotonic()
        reventilated = 0
        for item_id in worker.inflight:
            entry = self._inflight.pop(item_id, None)
            if entry is None or item_id in self._done:
                continue
            # From here the item can complete twice (ghost + reassigned
            # copy); only such items need completion dedup.
            self._risky_ids.add(item_id)
            reventilated += 1
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None:
                tracing.record_instant(
                    'reventilate', trace_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'),
                    reason=reason)
            # every re-ventilation charges the item's retry budget: a
            # row-group that deterministically kills its worker runs out
            # of budget and quarantines instead of crash-looping the
            # whole fleet forever (docs/service.md, failure semantics)
            self._record_failure(
                item_id, entry[1],
                'worker %s %s' % (identity.decode('utf-8', 'replace'),
                                  reason),
                None, now)
        self._reventilated_count += reventilated
        if reventilated and not metrics_disabled():
            get_registry().counter(SERVICE_REVENTILATED).inc(reventilated)
        self._update_fleet_gauges()
        logger.warning('Worker %s deregistered (%s); re-ventilated %d '
                       'in-flight item(s)', identity, reason, reventilated)
