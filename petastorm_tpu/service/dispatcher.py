"""Dispatcher: the service's item scheduler and liveness tracker.

Runs as a single thread that owns the ROUTER socket (ZMQ sockets are not
thread-safe; every socket operation happens here). Other threads interact
through three thread-safe surfaces only: :meth:`submit` (the ventilator
hands in work items), the ``deliver`` callback (results flow out to the
:class:`~petastorm_tpu.service.service_pool.ServicePool`'s bounded queue),
and :meth:`stats` (gauges).

Scheduling is credit-based: each live, READY worker server holds at most
``max_inflight_per_worker`` assigned items, so a slow worker never hoards
the queue and back-pressure composes with the ventilator's own in-flight
bound.

Fault tolerance — the exactly-once core:

* Every ventilated item gets a monotonically increasing id; ownership
  (``item id -> worker identity``) is recorded at assignment.
* A worker whose heartbeat lapses past ``liveness_timeout_s`` is
  deregistered and its in-flight items go back to the FRONT of the pending
  queue (**re-ventilation**) for reassignment.
* Completions are deduplicated by item id: a lapsed-but-actually-alive
  worker (GC pause, network stall) racing its replacement can produce two
  DONEs for one item — the first wins and is delivered, the second is
  dropped. Worker servers buffer an item's results and send them in a
  single DONE, so a worker killed mid-item has delivered nothing for it
  and the re-run is not a duplicate. Together: every item's row set reaches
  the consumer exactly once.
"""

import collections
import logging
import threading
import time

from petastorm_tpu.service import protocol as proto
from petastorm_tpu.telemetry import (
    get_registry, merge_worker_delta, metrics_disabled, note_producer_wait,
    tracing,
)

logger = logging.getLogger(__name__)

_POLL_INTERVAL_MS = 50
_STOP_BROADCASTS = 3

# Fleet-health metric names (docs/telemetry.md): the dispatcher runs in
# the CONSUMER process, so these land straight in its process-wide
# registry and surface through pipeline_report()'s `service` section —
# re-ventilation/dedupe activity visible without reading dispatcher logs.
SERVICE_REVENTILATED = 'petastorm_tpu_service_reventilated_total'
SERVICE_DUPLICATE_DONE = 'petastorm_tpu_service_duplicate_done_total'
SERVICE_WORKERS_ALIVE = 'petastorm_tpu_service_workers_alive'
SERVICE_WORKERS_REGISTERED = 'petastorm_tpu_service_workers_registered'
SERVICE_ITEMS_PENDING = 'petastorm_tpu_service_items_pending'
SERVICE_ITEMS_ASSIGNED = 'petastorm_tpu_service_items_assigned'


class _WorkerState:
    __slots__ = ('identity', 'last_heartbeat', 'ready', 'inflight')

    def __init__(self, identity, now):
        self.identity = identity
        self.last_heartbeat = now
        self.ready = False
        self.inflight = set()


class _TraceEntry:
    """Lifecycle of one traced item at the dispatcher: how many times it
    was dispatched, and — once delivered while still dedup-risky — when,
    so the sweep can age the retained entry out."""

    __slots__ = ('ctx', 'attempts', 'completed_at')

    def __init__(self, ctx):
        self.ctx = ctx
        self.attempts = 0
        self.completed_at = None


class Dispatcher:
    """Single-threaded scheduler loop behind a :class:`ServicePool`.

    :param endpoint: ``tcp://host:port`` to bind; port ``0`` binds a random
        free port (the resolved endpoint appears as :attr:`endpoint` once
        :meth:`wait_bound` returns).
    :param job_spec_payload: :func:`protocol.dump_job_spec` bytes replied to
        every REGISTER.
    :param deliver: NON-BLOCKING callable ``(kind, payload) -> bool``
        pushing ``('result', bytes)`` / ``('error', exc)`` /
        ``('marker', None)`` entries to the consumer; returns False when
        the consumer queue is momentarily full (the entry is then kept in
        an internal backlog and retried) and True when accepted or the
        pool is stopping. It must never block: this thread also acks
        worker heartbeats, and a consumer pause (recompile, checkpoint
        save) must quiesce the fleet, not starve its liveness protocol.
    :param stop_event: shared :class:`threading.Event`; setting it makes
        :meth:`run` broadcast STOP to all workers and exit.
    """

    def __init__(self, endpoint, job_spec_payload, deliver, stop_event,
                 heartbeat_interval_s=1.0, liveness_timeout_s=4.0,
                 max_inflight_per_worker=2, no_workers_timeout_s=30.0):
        self._requested_endpoint = endpoint
        self._job_spec_payload = job_spec_payload
        self._deliver = deliver
        self._stop_event = stop_event
        self._heartbeat_interval_s = heartbeat_interval_s
        self._liveness_timeout_s = liveness_timeout_s
        self._max_inflight_per_worker = max_inflight_per_worker
        self._no_workers_timeout_s = no_workers_timeout_s

        self.endpoint = None
        self._bound = threading.Event()
        self._lock = threading.Lock()
        self._pending = collections.deque()   # (item_id, payload)
        self._pending_ids = set()
        self._next_item_id = 0
        self._workers = {}                    # identity -> _WorkerState
        self._inflight = {}                   # item_id -> (identity, payload)
        # Completion dedup applies ONLY to items that were ever
        # re-ventilated: a single-assignment item produces exactly one DONE
        # (one WORK message -> one completion), so keeping every finished id
        # would leak memory across an infinite-epoch stream for nothing.
        # _risky_ids marks re-ventilated items; _done records their
        # completions. Both stay bounded by failure churn, not stream length.
        self._risky_ids = set()
        self._done = set()
        # Results awaiting consumer-queue space. Bounded in steady state:
        # while it is non-empty no new items are assigned, so it can never
        # exceed the completions already in flight when the consumer
        # stalled (≈ max_inflight_per_worker × workers).
        self._out_backlog = collections.deque()
        self._completed_count = 0
        self._reventilated_count = 0
        self._duplicate_done_count = 0
        self._workers_seen = 0
        self._metrics_deltas_merged = 0
        # identity -> latest heartbeat-piggybacked observability summary
        # (JSON dict); the per-worker breakdown of the fleet view. Kept
        # alongside _workers and pruned on deregister, so it is bounded
        # by fleet size.
        self._worker_obs = {}
        self._fatal_error = None
        self._no_workers_since = None
        # item_id -> _TraceEntry for traced items: the
        # work payload is opaque dill here, so the ServicePool registers
        # the context at submit time and the dispatcher stamps lifecycle
        # instants (dispatch/reventilate/done/duplicate_done) — which is
        # exactly what makes the exactly-once machinery OBSERVABLE: a
        # re-ventilated item's timeline shows every dispatch attempt and
        # its single deduped completion. Entries drop at completion; risky
        # ones are retained briefly for dedup marking and aged out by the
        # sweep, so the map stays bounded by in-flight work, never by
        # stream length or failure churn.
        self._trace_ctx = {}

    # -- thread-safe surface (called from pool / ventilator threads) ---------

    def submit(self, payload, trace_ctx=None):
        """Enqueue one dill-framed work item; returns its item id.
        ``trace_ctx`` (when the item is traced) keys the dispatcher's
        lifecycle instants to the trace minted at ventilation."""
        with self._lock:
            item_id = self._next_item_id
            self._next_item_id += 1
            self._pending.append((item_id, payload))
            self._pending_ids.add(item_id)
            if trace_ctx is not None:
                self._trace_ctx[item_id] = _TraceEntry(trace_ctx)
            return item_id

    def wait_bound(self, timeout):
        """Block until the ROUTER socket is bound (or binding failed)."""
        if not self._bound.wait(timeout):
            raise RuntimeError('Dispatcher did not bind %r within %.1fs'
                               % (self._requested_endpoint, timeout))
        if self._fatal_error is not None:
            raise self._fatal_error

    @property
    def fatal_error(self):
        return self._fatal_error

    def registered_workers(self):
        return len(self._workers)

    def stats(self):
        with self._lock:
            pending = len(self._pending)
        # list() snapshots the dict at C level (atomic under the GIL):
        # the dispatcher thread may register/deregister workers while a
        # consumer thread polls diagnostics.
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if time.monotonic() - w.last_heartbeat
                   <= self._liveness_timeout_s)
        return {
            'workers_alive': live,
            'workers_registered': len(self._workers),
            'workers_seen': self._workers_seen,
            'items_assigned': len(self._inflight),
            'items_pending': pending,
            'items_reventilated': self._reventilated_count,
            'items_duplicate_done': self._duplicate_done_count,
            'metrics_deltas_merged': self._metrics_deltas_merged,
        }

    def health(self):
        """The dispatcher's /health contribution: fleet liveness plus
        the back-pressure state an operator needs first — ``quiesced``
        means completions are backlogged behind a full consumer queue,
        so the fleet is idling by design, not broken."""
        stats = self.stats()
        stats['quiesced'] = bool(self._out_backlog)
        stats['out_backlog'] = len(self._out_backlog)
        stats['endpoint'] = self.endpoint
        stats['items_completed'] = self._completed_count
        return stats

    def fleet_view(self):
        """The merged fleet view the dispatcher's /report serves:
        per-worker breakdown (liveness, in-flight load, and the latest
        heartbeat-piggybacked observability summary — rates, pid, the
        worker's own obs endpoint port) plus the scheduler totals. The
        *aggregate* metrics (fleet-wide stage seconds, anomaly counters)
        already live in this process's registry via the DONE-frame delta
        merges, so `pipeline_report()` alongside this IS the merged
        view."""
        now = time.monotonic()
        workers = {}
        for identity, worker in list(self._workers.items()):
            name = identity.decode('utf-8', 'replace')
            entry = {
                'alive': now - worker.last_heartbeat
                <= self._liveness_timeout_s,
                'ready': worker.ready,
                'inflight': len(worker.inflight),
                'heartbeat_age_s': round(now - worker.last_heartbeat, 3),
            }
            summary = self._worker_obs.get(identity)
            if summary is not None:
                entry['summary'] = summary
            workers[name] = entry
        view = {'workers': workers}
        view.update(self.stats())
        return view

    def _update_fleet_gauges(self):
        """Mirror fleet health into the process-wide registry so
        pipeline_report()'s `service` section (and the Prometheus/JSONL
        exporters) see it without holding a pool reference."""
        if metrics_disabled():
            return
        now = time.monotonic()
        workers = list(self._workers.values())
        live = sum(1 for w in workers
                   if now - w.last_heartbeat <= self._liveness_timeout_s)
        registry = get_registry()
        registry.gauge(SERVICE_WORKERS_ALIVE).set(live)
        registry.gauge(SERVICE_WORKERS_REGISTERED).set(len(workers))
        with self._lock:
            pending = len(self._pending)
        registry.gauge(SERVICE_ITEMS_PENDING).set(pending)
        registry.gauge(SERVICE_ITEMS_ASSIGNED).set(len(self._inflight))

    # -- dispatcher thread ---------------------------------------------------

    def run(self):
        import zmq

        context = zmq.Context()
        sock = context.socket(zmq.ROUTER)
        try:
            if self._requested_endpoint.endswith(':0'):
                base = self._requested_endpoint.rsplit(':', 1)[0]
                port = sock.bind_to_random_port(base)
                self.endpoint = '%s:%d' % (base, port)
            else:
                sock.bind(self._requested_endpoint)
                self.endpoint = self._requested_endpoint
        except Exception as e:  # noqa: BLE001 - surfaced to start()
            self._fatal_error = RuntimeError(
                'Dispatcher failed to bind %r: %s'
                % (self._requested_endpoint, e))
            self._bound.set()
            sock.close(linger=0)
            context.term()
            return
        self._bound.set()

        last_sweep = time.monotonic()
        last_tick = last_sweep
        backlog_prev = False
        try:
            while not self._stop_event.is_set():
                self._flush_backlog()
                # Time spent with completions backlogged behind a full
                # consumer queue is the service-side back-pressure clock:
                # the fleet is quiesced because the CONSUMER is slow —
                # producer wait, consumer-bound evidence (the remote
                # workers never block locally; their out channel is the
                # dispatcher, so this is measured here). An interval
                # counts only when the backlog existed at BOTH of its
                # ends: charging the interval in which a backlog first
                # appeared would bill message-handling time that preceded
                # it as a stall.
                tick = time.monotonic()
                backlogged = bool(self._out_backlog)
                if backlogged and backlog_prev:
                    note_producer_wait(tick - last_tick)
                backlog_prev = backlogged
                last_tick = tick
                # While completions are backlogged the consumer's next free
                # queue slot is the event that matters, and ZMQ cannot wake
                # us for it — poll short so drained slots refill within
                # ~5ms instead of a full poll interval (otherwise every
                # marker behind a full queue costs the consumer a phantom
                # ~50ms starvation wait).
                poll_ms = 5 if self._out_backlog else _POLL_INTERVAL_MS
                if sock.poll(poll_ms):
                    # Drain everything queued before scheduling: completions
                    # free credit that the assignment pass below can use.
                    while True:
                        try:
                            frames = sock.recv_multipart(zmq.NOBLOCK)
                        except zmq.Again:
                            break
                        self._handle(sock, frames)
                self._assign(sock)
                now = time.monotonic()
                if now - last_sweep >= self._heartbeat_interval_s:
                    last_sweep = now
                    self._sweep(now)
                    self._update_fleet_gauges()
        except Exception as e:  # noqa: BLE001 - fatal for the whole pool
            logger.exception('Dispatcher loop died')
            self._fatal_error = e
        finally:
            for _ in range(_STOP_BROADCASTS):
                for identity in list(self._workers):
                    try:
                        sock.send_multipart([identity, proto.MSG_STOP],
                                            flags=zmq.NOBLOCK)
                    except Exception:  # noqa: BLE001 - peer may be gone
                        pass
                time.sleep(_POLL_INTERVAL_MS / 1000.0)
            sock.close(linger=500)
            context.term()

    # -- message handling ----------------------------------------------------

    def _handle(self, sock, frames):
        identity, msg = frames[0], frames[1]
        now = time.monotonic()
        if msg == proto.MSG_REGISTER:
            if identity not in self._workers:
                self._workers[identity] = _WorkerState(identity, now)
                self._workers_seen += 1
                logger.info('Worker %s registered (%d registered)',
                            identity, len(self._workers))
            else:
                self._workers[identity].last_heartbeat = now
            sock.send_multipart([identity, proto.MSG_SPEC,
                                 self._job_spec_payload])
            self._update_fleet_gauges()
        elif msg == proto.MSG_READY:
            worker = self._workers.get(identity)
            if worker is not None:
                worker.ready = True
                worker.last_heartbeat = now
        elif msg == proto.MSG_HEARTBEAT:
            worker = self._workers.get(identity)
            if worker is None:
                # A lapsed worker resurfacing (its items were already
                # re-ventilated): re-admit it with a clean slate — it
                # already holds the spec and a live decode worker.
                worker = _WorkerState(identity, now)
                worker.ready = True
                self._workers[identity] = worker
                logger.info('Worker %s re-admitted after lapse', identity)
            else:
                worker.last_heartbeat = now
            if len(frames) > 2:
                # optional trailing frame: the worker's per-heartbeat
                # observability summary (docs/telemetry.md fleet view);
                # absent from pre-observability builds, and a bad frame
                # degrades to None — liveness never depends on it
                summary = proto.load_obs_summary(frames[2])
                if summary is not None:
                    self._worker_obs[identity] = summary
            sock.send_multipart([identity, proto.MSG_HEARTBEAT_ACK])
        elif msg == proto.MSG_DONE:
            item_id = proto.unpack_item_id(frames[2])
            # frames: [identity, DONE, item_id, metrics, result*]. The
            # wire has no version marker, and externally-started worker
            # servers may run a pre-telemetry build whose DONE is
            # [identity, DONE, item_id, result*] — so the slot is claimed
            # as metrics ONLY when it is empty (b'': "nothing changed")
            # or passes load_metrics_delta's strict delta-shape check;
            # otherwise it is treated as the first result frame. Dropping
            # a result would be silent row loss; misreading one as a
            # delta is made implausible by the strict shape.
            payload = frames[3:]
            if payload and (payload[0] == b''
                            or self._merge_metrics(payload[0])):
                payload = payload[1:]
            self._complete(identity, item_id, ('result', payload), now)
        elif msg == proto.MSG_ERROR:
            item_id = proto.unpack_item_id(frames[2])
            exc = proto.load_exception(frames[3])
            if len(frames) > 4:
                self._merge_metrics(frames[4])
            self._complete(identity, item_id, ('error', exc), now)
        elif msg == proto.MSG_BYE:
            self._deregister(identity, 'said goodbye')
        else:
            logger.warning('Unknown service message type %r from %s',
                           msg, identity)

    def _merge_metrics(self, frame):
        """Fold one worker server's piggybacked telemetry delta into this
        (client) process's registry — the dispatcher is where per-worker
        deltas become the fleet-wide aggregate. Returns whether the frame
        WAS a delta (the DONE path uses this to tell the metrics slot from
        a result frame sent by a pre-telemetry worker build). Duplicate
        completions double-merge in the worst case (telemetry is advisory;
        item delivery, not metrics, carries the exactly-once guarantee)."""
        delta = proto.load_metrics_delta(frame)
        if delta is None:
            return False
        self._metrics_deltas_merged += 1
        merge_worker_delta(delta)
        return True

    def _complete(self, identity, item_id, outcome, now):
        worker = self._workers.get(identity)
        if worker is not None:
            worker.last_heartbeat = now
            worker.inflight.discard(item_id)
        if item_id in self._done:
            # Duplicate completion from a lapsed-then-reassigned race; the
            # first DONE already delivered this item's rows.
            logger.debug('Dropping duplicate completion of item %d from %s',
                         item_id, identity)
            self._duplicate_done_count += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_DUPLICATE_DONE).inc()
            # both completions have now been seen: the trace entry has
            # served its purpose (the dedup drop is marked on the timeline)
            dup_entry = self._trace_ctx.pop(item_id, None)
            if dup_entry is not None:
                tracing.record_instant(
                    'duplicate_done', dup_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'))
            return
        assignment = self._inflight.pop(item_id, None)
        if assignment is None:
            # Ghost completion: the item lapsed back onto the pending queue
            # but its original owner finished after all. Accept the result
            # and withdraw the pending copy so it is not run twice.
            with self._lock:
                if item_id not in self._pending_ids:
                    logger.warning('Completion of unknown item %d from %s '
                                   'dropped', item_id, identity)
                    return
                self._pending_ids.discard(item_id)
                self._pending = collections.deque(
                    (i, p) for i, p in self._pending if i != item_id)
        else:
            owner = self._workers.get(assignment[0])
            if owner is not None:
                owner.inflight.discard(item_id)
        if item_id in self._risky_ids:
            self._done.add(item_id)
            # a risky item keeps its trace entry so a RACED second DONE
            # can be marked as deduped — but a SIGKILLed first owner never
            # sends one, so stamp the completion time and let the sweep
            # age the entry out (the ghost race window is a few liveness
            # timeouts at most); without this the map would grow with
            # failure churn for the life of the process
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None and trace_entry.completed_at is None:
                trace_entry.completed_at = now
        else:
            trace_entry = self._trace_ctx.pop(item_id, None)
        if trace_entry is not None:
            # the item's ONE delivered completion
            tracing.record_instant(
                'done', trace_entry.ctx, 'dispatcher',
                worker=identity.decode('utf-8', 'replace'),
                attempts=trace_entry.attempts, outcome=outcome[0])
        self._completed_count += 1
        kind, payload = outcome
        if kind == 'result':
            for result_frame in payload:
                self._emit(('result', result_frame))
        else:
            self._emit(('error', payload))
        self._emit(('marker', item_id))

    def _emit(self, entry):
        """Hand one entry toward the consumer, preserving order: direct
        only while the backlog is empty AND the queue has room."""
        if self._out_backlog or not self._deliver(entry):
            self._out_backlog.append(entry)

    def _flush_backlog(self):
        while self._out_backlog:
            if not self._deliver(self._out_backlog[0]):
                return
            self._out_backlog.popleft()

    # -- scheduling ----------------------------------------------------------

    def _assign(self, sock):
        if self._out_backlog:
            # The consumer is stalled; assigning more work would just grow
            # the backlog unboundedly. Workers idle (heartbeating, acked)
            # until the consumer drains — quiescence, not decay.
            return
        # Least-loaded first, so a fresh (or re-admitted) worker fills up
        # before busy ones receive more.
        workers = sorted((w for w in self._workers.values() if w.ready),
                         key=lambda w: len(w.inflight))
        for worker in workers:
            while len(worker.inflight) < self._max_inflight_per_worker:
                with self._lock:
                    if not self._pending:
                        return
                    item_id, payload = self._pending.popleft()
                    self._pending_ids.discard(item_id)
                if item_id in self._done:
                    continue
                sock.send_multipart([worker.identity, proto.MSG_WORK,
                                     proto.pack_item_id(item_id), payload])
                self._inflight[item_id] = (worker.identity, payload)
                worker.inflight.add(item_id)
                entry = self._trace_ctx.get(item_id)
                if entry is not None:
                    entry.attempts += 1
                    tracing.record_instant(
                        'dispatch', entry.ctx, 'dispatcher',
                        worker=worker.identity.decode('utf-8', 'replace'),
                        attempt=entry.attempts)

    def _sweep(self, now):
        for identity, worker in list(self._workers.items()):
            if now - worker.last_heartbeat > self._liveness_timeout_s:
                self._deregister(
                    identity, 'heartbeat lapsed (%.1fs > %.1fs)'
                    % (now - worker.last_heartbeat, self._liveness_timeout_s))
        # age out trace entries retained past completion for dedup marking
        # (see _complete): a ghost DONE races within ZMQ buffering of one
        # lapse, so several liveness timeouts is a generous window
        retention_s = 10.0 * self._liveness_timeout_s
        stale = [item_id for item_id, entry in list(self._trace_ctx.items())
                 if entry.completed_at is not None
                 and now - entry.completed_at > retention_s]
        for item_id in stale:
            self._trace_ctx.pop(item_id, None)
        with self._lock:
            outstanding = bool(self._pending) or bool(self._inflight)
        if outstanding and not self._workers:
            if self._no_workers_since is None:
                self._no_workers_since = now
            elif now - self._no_workers_since > self._no_workers_timeout_s:
                raise RuntimeError(
                    'No live worker servers for %.1fs with work outstanding; '
                    'is the dispatcher endpoint (%s) reachable from the '
                    'workers?' % (self._no_workers_timeout_s, self.endpoint))
        else:
            self._no_workers_since = None

    def _deregister(self, identity, reason):
        worker = self._workers.pop(identity, None)
        self._worker_obs.pop(identity, None)
        if worker is None:
            return
        reventilated = 0
        for item_id in worker.inflight:
            entry = self._inflight.pop(item_id, None)
            if entry is None or item_id in self._done:
                continue
            with self._lock:
                # Front of the queue: lapsed work is the oldest and gates
                # epoch completion through the ventilator's in-flight bound.
                self._pending.appendleft((item_id, entry[1]))
                self._pending_ids.add(item_id)
            # From here the item can complete twice (ghost + reassigned
            # copy); only such items need completion dedup.
            self._risky_ids.add(item_id)
            reventilated += 1
            trace_entry = self._trace_ctx.get(item_id)
            if trace_entry is not None:
                tracing.record_instant(
                    'reventilate', trace_entry.ctx, 'dispatcher',
                    worker=identity.decode('utf-8', 'replace'),
                    reason=reason)
        self._reventilated_count += reventilated
        if reventilated and not metrics_disabled():
            get_registry().counter(SERVICE_REVENTILATED).inc(reventilated)
        self._update_fleet_gauges()
        logger.warning('Worker %s deregistered (%s); re-ventilated %d '
                       'in-flight item(s)', identity, reason, reventilated)
