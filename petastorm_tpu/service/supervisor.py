"""Worker supervisor: the standing service's self-healing actuator.

PR 10 built the sensor (windowed rollups, ``heartbeat_gap`` /
``queue_saturated`` anomaly events) and PR 11 made every failure domain
injectable — but nothing *acted* on any of it: a dead worker stayed
dead, a saturated fleet stayed saturated. This module closes the loop
for a daemonized fleet (docs/service.md, "Standing service"):

* **Replacement**: a worker-server process that exits unexpectedly (or
  wedges — alive but heartbeat-lapsed, the ``heartbeat_gap`` shape) is
  replaced within one supervision tick, so a SIGKILL costs the fleet one
  heartbeat window, not a worker.
* **Recruitment**: sustained saturation — the dispatcher's queue holding
  pending work while every live worker is loaded, judged over rollup
  WINDOWS of ``PETASTORM_TPU_SERVICE_SCALE_WINDOW_S`` seconds rather
  than raw ticks (:class:`_ScaleRollup`, the autotuner's windowed-
  verdict discipline) — recruits workers one per episode up to
  ``PETASTORM_TPU_SERVICE_MAX_WORKERS``.
* **Release**: a sustained idle fleet (nothing pending, nothing
  assigned over the same windows — the consumer-bound regime) releases
  workers down to
  ``PETASTORM_TPU_SERVICE_MIN_WORKERS``, two-phase so no work is ever
  re-ventilated for a scaling decision: *cordon* (the dispatcher stops
  assigning to that worker), wait idle, then SIGTERM (the worker server
  says BYE and exits cleanly).
* **Circuit breaker**: a slot whose worker keeps dying —
  ``PETASTORM_TPU_SERVICE_BREAKER_DEATHS`` deaths inside
  ``PETASTORM_TPU_SERVICE_BREAKER_WINDOW_S`` — stops being respawned
  eagerly: respawns back off exponentially and a ``worker_flapping``
  anomaly event (with its troubleshoot.md runbook) announces the slot,
  instead of fork-bombing the host while a bad image/config burns every
  process it starts. A respawned worker that survives a full window
  closes the breaker. Spawn *failures* (the ``service.spawn``
  faultpoint, or a real OSError from process creation) feed the same
  breaker, which is what makes the breaker chaos-testable without
  burning real processes.

Every scaling/repair action is recorded three ways: a canonical trace
instant (``worker_spawn`` / ``worker_release`` / ``breaker_open`` /
``breaker_close`` on the ``supervisor`` track — Perfetto shows *why*
the fleet changed), a bounded decision log served on ``/report``, and
the ``petastorm_tpu_service_workers_spawned_total`` /
``..._released_total`` / ``..._breaker_open`` metrics.

The supervisor is deliberately dispatcher-agnostic in its inputs: it
reads :meth:`Dispatcher.stats` / :meth:`Dispatcher.alive_worker_pids`
(duck-typed — tests drive it with a stub) and owns only processes it
spawned itself. Externally-started worker servers are never touched.
"""

import collections
import logging
import os
import signal
import threading
import time

from petastorm_tpu import faults
from petastorm_tpu.telemetry import count_swallowed, knobs, tracing
from petastorm_tpu.telemetry.registry import get_registry
from petastorm_tpu.telemetry.spans import metrics_disabled
from petastorm_tpu.telemetry.timeseries import record_anomaly

logger = logging.getLogger(__name__)

SERVICE_SPAWNED = 'petastorm_tpu_service_workers_spawned_total'
SERVICE_RELEASED = 'petastorm_tpu_service_workers_released_total'
SERVICE_BREAKER_OPEN = 'petastorm_tpu_service_breaker_open'

#: consecutive saturated windows before one worker is recruited
_SCALE_UP_TICKS = 3
#: consecutive idle windows before one worker is released
_SCALE_DOWN_TICKS = 10
#: wall-clock grace for a spawned worker's FIRST registration (a fresh
#: interpreter pays import time before it can heartbeat at all)
_REGISTER_GRACE_S = 60.0
#: floor of the wedge threshold: a between-jobs worker re-REGISTERs on
#: a backoff capped at 2s, so a shorter threshold would kill healthy
#: idle workers waiting for the next job
_WEDGE_FLOOR_S = 3.0
#: exponential respawn backoff base/cap once a slot's breaker is open
_BREAKER_BACKOFF_BASE_S = 1.0
_BREAKER_BACKOFF_CAP_S = 60.0
#: decision-log ring served on /report
_DECISION_KEEP = 50


class _Slot:
    """One worker seat: the process currently holding it plus the seat's
    crash history (the breaker state lives with the SEAT, not the
    process — that is what makes a crash LOOP visible)."""

    __slots__ = ('index', 'proc', 'pid', 'spawned_at', 'seen_alive',
                 'deaths', 'backoff_level', 'open_until', 'flapping',
                 'releasing')

    def __init__(self, index):
        self.index = index
        self.proc = None
        self.pid = None
        self.spawned_at = None
        self.seen_alive = False
        self.deaths = collections.deque(maxlen=32)  # monotonic stamps
        self.backoff_level = 0
        self.open_until = 0.0
        self.flapping = False
        self.releasing = False

    def breaker_open(self, now):
        return now < self.open_until

    def descriptor(self, now):
        return {
            'slot': self.index,
            'pid': self.pid,
            'alive': self.proc is not None and self.proc.poll() is None,
            'uptime_s': (round(now - self.spawned_at, 1)
                         if self.spawned_at is not None else None),
            'recent_deaths': len(self.deaths),
            'breaker_open': self.breaker_open(now),
            'breaker_backoff_level': self.backoff_level,
            'breaker_reopens_in_s': (round(self.open_until - now, 1)
                                     if self.breaker_open(now) else 0),
            'releasing': self.releasing,
        }


class _ScaleRollup:
    """Windowed scaling verdicts — the autotuner's discipline applied to
    recruit/release (docs/service.md). Raw per-tick streaks made every
    transient spike a vote: one tick of backlog while a worker was
    between row-groups counted toward recruitment exactly like a tick of
    real saturation. Instead, ticks accumulate into a rollup window of
    ``PETASTORM_TPU_SERVICE_SCALE_WINDOW_S`` seconds and each CLOSED
    window casts one verdict from its MEANS — saturated, idle, or
    neither — so a decision needs sustained evidence, not a lucky
    sample. A window of 0 (the default) closes one window per tick:
    verdicts degenerate to the original per-tick readings and the
    scaling cadence is unchanged."""

    __slots__ = ('window_s', 'sat_windows', 'idle_windows',
                 '_samples', '_window_start')

    def __init__(self, window_s):
        self.window_s = window_s
        self.sat_windows = 0
        self.idle_windows = 0
        self._samples = []
        self._window_start = None

    def add(self, now, pending, assigned, alive):
        """Fold one tick's dispatcher sample. Returns the closed
        window's stats (the decision log's evidence) when this sample
        completed a window, else None."""
        if self._window_start is None:
            self._window_start = now
        self._samples.append((pending, assigned, alive))
        if now - self._window_start < self.window_s:
            return None
        n = len(self._samples)
        mean_pending = sum(s[0] for s in self._samples) / n
        mean_assigned = sum(s[1] for s in self._samples) / n
        mean_alive = sum(s[2] for s in self._samples) / n
        self._samples = []
        self._window_start = now
        # the same conditions the per-tick reading used, over the
        # window means: queued work while every live worker carries
        # load, vs. a fleet with nothing queued and nothing assigned
        saturated = mean_pending > 0 and (mean_alive == 0
                                          or mean_assigned >= mean_alive)
        idle = mean_pending == 0 and mean_assigned == 0
        self.sat_windows = self.sat_windows + 1 if saturated else 0
        self.idle_windows = self.idle_windows + 1 if idle else 0
        return {'ticks': n, 'mean_pending': round(mean_pending, 2),
                'mean_assigned': round(mean_assigned, 2),
                'mean_alive': round(mean_alive, 2),
                'saturated': saturated, 'idle': idle}


class WorkerSupervisor:
    """Process-spawning supervision loop for a daemon's worker fleet.

    :param dispatcher: the live :class:`~petastorm_tpu.service.dispatcher
        .Dispatcher` (duck-typed: ``stats() / alive_worker_pids() /
        cordon_worker_by_pid() / worker_inflight_by_pid()``).
    :param endpoint: resolved ``tcp://`` endpoint spawned workers
        register with.
    :param initial_workers: fleet size at start (clamped into
        [min_workers, max_workers]).
    :param min_workers/max_workers: the release floor and recruitment
        ceiling (default knobs ``PETASTORM_TPU_SERVICE_MIN_WORKERS`` /
        ``..._MAX_WORKERS``).
    :param tick_s: supervision cadence; also the definition of "one
        heartbeat window" for replacement latency.
    :param spawn: test seam — replaces the real worker-process spawn;
        must return an object with ``poll()/terminate()/kill()/wait()``
        and ``pid``.
    """

    def __init__(self, dispatcher, endpoint, initial_workers=1,
                 min_workers=None, max_workers=None, tick_s=1.0,
                 heartbeat_interval_s=1.0, breaker_deaths=None,
                 breaker_window_s=None, spawn=None):
        self._dispatcher = dispatcher
        self._endpoint = endpoint
        self._heartbeat_interval_s = heartbeat_interval_s
        self._min_workers = (min_workers if min_workers is not None
                             else knobs.get_int(
                                 'PETASTORM_TPU_SERVICE_MIN_WORKERS', 1,
                                 floor=0))
        self._max_workers = (max_workers if max_workers is not None
                             else knobs.get_int(
                                 'PETASTORM_TPU_SERVICE_MAX_WORKERS', 8,
                                 floor=1))
        self._breaker_deaths = (breaker_deaths
                                if breaker_deaths is not None
                                else knobs.get_int(
                                    'PETASTORM_TPU_SERVICE_BREAKER'
                                    '_DEATHS', 3, floor=1))
        self._breaker_window_s = (breaker_window_s
                                  if breaker_window_s is not None
                                  else knobs.get_float(
                                      'PETASTORM_TPU_SERVICE_BREAKER'
                                      '_WINDOW_S', 30.0, floor=0.1))
        self.tick_s = tick_s
        self._spawn_fn = spawn
        self.target = max(self._min_workers,
                          min(initial_workers, self._max_workers))
        self._slots = []
        self._slot_seq = 0
        self._scale = _ScaleRollup(knobs.get_float(
            'PETASTORM_TPU_SERVICE_SCALE_WINDOW_S', 0.0, floor=0.0))
        self._wedge_streaks = {}            # pid -> lapsed-since timestamp
        self._decision_seq = 0
        self._decisions = collections.deque(maxlen=_DECISION_KEEP)
        self._spawned_total = 0
        self._released_total = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        with self._lock:
            while len(self._slots) < self.target:
                self._add_slot(time.monotonic())
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name='service-supervisor')
            self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self._reap_all()

    def _run(self):
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - supervision must survive
                # a broken tick (stats race, proc teardown) loses one
                # supervision interval, never the supervisor
                count_swallowed('supervisor-tick')
                logger.debug('Supervision tick failed', exc_info=True)

    def _reap_all(self):
        deadline = time.monotonic() + 10.0
        for slot in self._slots:
            proc = slot.proc
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.terminate()
            except Exception:  # noqa: BLE001 - already gone
                count_swallowed('supervisor-reap')
        for slot in self._slots:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 - escalate once, then move on
                count_swallowed('supervisor-reap')
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except Exception:  # noqa: BLE001 - OS will reap
                    count_swallowed('supervisor-reap')
        self._slots = []

    # -- the supervision tick ------------------------------------------------

    def tick(self):
        """One supervision pass (the thread's body; callable directly
        from tests for deterministic stepping)."""
        now = time.monotonic()
        with self._lock:
            self._reap_and_respawn(now)
            self._replace_wedged(now)
            self._autoscale(now)
            self._advance_releases(now)
            self._update_gauges(now)

    def _reap_and_respawn(self, now):
        for slot in self._slots:
            proc = slot.proc
            if proc is not None and proc.poll() is None:
                # a worker that survived a full breaker window proves the
                # seat stable again: close the breaker, forget the streak
                if slot.deaths and slot.spawned_at is not None \
                        and now - slot.spawned_at > self._breaker_window_s:
                    slot.deaths.clear()
                    slot.backoff_level = 0
                    if slot.flapping:
                        slot.flapping = False
                        self._record('breaker_close', slot=slot.index,
                                     pid=slot.pid)
                continue
            if slot.releasing:
                # expected death: the two-phase release finishing — the
                # seat retires with its process
                self._retire_slot(slot)
                continue
            if proc is not None:
                self._note_death(slot, now,
                                 'exit code %s' % proc.poll())
                self._wedge_streaks.pop(slot.pid, None)
                slot.proc = None
                slot.pid = None
            keepable = sum(1 for s in self._slots if not s.releasing)
            if keepable > self.target:
                # fleet is above target (scale-down raced a death): let
                # the empty seat retire instead of respawning it.
                # Releasing seats are already leaving and must NOT count
                # toward the surplus — counting them would retire a
                # crashed seat alongside them and leave the fleet
                # permanently below target.
                self._retire_slot(slot)
                continue
            if slot.breaker_open(now):
                continue  # backoff not served yet
            self._spawn_into(slot, now)

    def _replace_wedged(self, now):
        """A spawned process that is alive but fell out of the
        dispatcher's liveness window (``heartbeat_gap``: wedged decode,
        hung runtime) is killed and its seat respawned — the
        observability loop's repair arm. Only workers that have been
        SEEN alive are eligible: a fresh interpreter takes seconds to
        boot and register, and killing it mid-boot would BE the crash
        loop (the registration-stuck case gets its own long grace)."""
        try:
            alive_pids = self._dispatcher.alive_worker_pids()
        except Exception:  # noqa: BLE001 - stats race during teardown
            count_swallowed('supervisor-stats')
            return
        for slot in self._slots:
            proc = slot.proc
            if proc is None or proc.poll() is not None or slot.releasing:
                continue
            if slot.pid in alive_pids:
                slot.seen_alive = True
                self._wedge_streaks.pop(slot.pid, None)
                continue
            if not slot.seen_alive:
                # never registered yet: interpreter boot / import time.
                # Tolerate up to the registration grace, then treat a
                # silent process as wedged after all.
                if slot.spawned_at is None \
                        or now - slot.spawned_at < _REGISTER_GRACE_S:
                    continue
            absent_since = self._wedge_streaks.setdefault(slot.pid, now)
            wedge_after = max(_WEDGE_FLOOR_S,
                              12 * self._heartbeat_interval_s)
            if now - absent_since < wedge_after:
                continue
            self._wedge_streaks.pop(slot.pid, None)
            logger.warning('Worker pid %s is running but heartbeat-lapsed '
                           'for %.1fs; killing for replacement',
                           slot.pid, now - absent_since)
            try:
                proc.kill()
            except Exception:  # noqa: BLE001 - it may have just exited
                count_swallowed('supervisor-kill')
            # the kill lands as an unexpected death next tick, feeding
            # the breaker exactly like any other crash

    def _autoscale(self, now):
        try:
            stats = self._dispatcher.stats()
        except Exception:  # noqa: BLE001 - stats race during teardown
            count_swallowed('supervisor-stats')
            return
        pending = stats.get('items_pending', 0)
        assigned = stats.get('items_assigned', 0)
        alive = stats.get('workers_alive', 0)
        # every tick feeds the rollup; only a CLOSED window casts a
        # saturated/idle verdict (window 0 = one window per tick, the
        # original cadence) — see _ScaleRollup
        window = self._scale.add(now, pending, assigned, alive)
        if window is None:
            return
        if self._scale.sat_windows >= _SCALE_UP_TICKS \
                and self.target < self._max_workers:
            self.target += 1
            self._scale.sat_windows = 0
            # decision-log only: _add_slot's spawn records the canonical
            # worker_spawn trace instant (one instant per actual spawn)
            self._record('scale_up_decision', target=self.target,
                         pending=pending, workers_alive=alive,
                         window=window)
            self._add_slot(now)
        elif self._scale.idle_windows >= _SCALE_DOWN_TICKS \
                and self.target > self._min_workers \
                and len(self._slots) > self._min_workers:
            self.target -= 1
            self._scale.idle_windows = 0
            self._begin_release(now)

    def _advance_releases(self, now):
        """Phase two of worker release: once the cordoned worker reports
        idle (or is already gone from the dispatcher), terminate it."""
        for slot in self._slots:
            if not slot.releasing or slot.proc is None:
                continue
            if slot.proc.poll() is not None:
                continue  # death path retires it next tick
            try:
                inflight = self._dispatcher.worker_inflight_by_pid(slot.pid)
            except Exception:  # noqa: BLE001 - stats race
                count_swallowed('supervisor-stats')
                continue
            if inflight:
                continue
            try:
                slot.proc.send_signal(signal.SIGTERM)
            except Exception:  # noqa: BLE001 - already exiting
                count_swallowed('supervisor-release')

    # -- actions -------------------------------------------------------------

    def _add_slot(self, now):
        slot = _Slot(self._slot_seq)
        self._slot_seq += 1
        self._slots.append(slot)
        self._spawn_into(slot, now)
        return slot

    def _spawn_into(self, slot, now):
        """(Re)spawn a worker-server process into ``slot``. A spawn
        failure — the ``service.spawn`` faultpoint or a real process-
        creation error — is a death of the seat: it feeds the breaker,
        so a host that cannot start workers backs off instead of
        hot-looping the spawn syscall."""
        try:
            if faults.ARMED:
                faults.fault_hit('service.spawn', key=slot.index)
            slot.proc = self._spawn_process(slot.index)
        except Exception as e:  # noqa: BLE001 - incl. FaultInjected
            self._note_death(slot, now, 'spawn failed: %s' % e)
            return
        slot.pid = slot.proc.pid
        slot.spawned_at = now
        slot.seen_alive = False
        self._spawned_total += 1
        if not metrics_disabled():
            get_registry().counter(SERVICE_SPAWNED).inc()
        self._record('worker_spawn', slot=slot.index, pid=slot.pid,
                     fleet=len(self._slots))
        logger.info('Spawned worker pid %s into slot %d (fleet %d, '
                    'target %d)', slot.pid, slot.index,
                    len(self._slots), self.target)

    def _spawn_process(self, worker_id):
        if self._spawn_fn is not None:
            return self._spawn_fn(worker_id)
        from petastorm_tpu.service.worker_server import serve
        from petastorm_tpu.workers.exec_in_new_process import (
            exec_in_new_process,
        )
        return exec_in_new_process(
            serve, self._endpoint, worker_id=worker_id,
            heartbeat_interval_s=self._heartbeat_interval_s,
            parent_pid=os.getpid(), once=False)

    def _note_death(self, slot, now, reason):
        """One unexpected death of ``slot``'s occupant: charge the
        breaker window; K deaths inside it open the breaker
        (exponentially backed-off respawn + ``worker_flapping``)."""
        slot.deaths.append(now)
        recent = sum(1 for t in slot.deaths
                     if now - t <= self._breaker_window_s)
        self._record('worker_death', slot=slot.index, reason=reason,
                     recent_deaths=recent)
        logger.warning('Worker slot %d died (%s): %d death(s) in the '
                       'last %.0fs', slot.index, reason, recent,
                       self._breaker_window_s)
        if recent < self._breaker_deaths:
            return
        backoff = min(_BREAKER_BACKOFF_CAP_S,
                      _BREAKER_BACKOFF_BASE_S * (2 ** slot.backoff_level))
        slot.backoff_level += 1
        slot.open_until = now + backoff
        if not slot.flapping:
            slot.flapping = True
            record_anomaly('worker_flapping', detail={
                'slot': slot.index, 'deaths': recent,
                'window_s': self._breaker_window_s,
                'backoff_s': round(backoff, 1), 'reason': reason})
            self._record('breaker_open', slot=slot.index,
                         deaths=recent, backoff_s=round(backoff, 1))
        else:
            # already announced: just extend the backoff (the ramp)
            self._record('breaker_backoff', event='breaker_open',
                         slot=slot.index, backoff_s=round(backoff, 1))

    def _begin_release(self, now):
        """Phase one of a scale-down: cordon the youngest non-releasing
        worker so the dispatcher stops feeding it; `_advance_releases`
        terminates it once idle."""
        candidates = [s for s in self._slots
                      if not s.releasing and s.proc is not None
                      and s.proc.poll() is None]
        if not candidates:
            return
        slot = max(candidates, key=lambda s: s.spawned_at or 0)
        slot.releasing = True
        try:
            self._dispatcher.cordon_worker_by_pid(slot.pid)
        except Exception:  # noqa: BLE001 - not registered yet: SIGTERM
            count_swallowed('supervisor-cordon')
        self._record('worker_release', slot=slot.index, pid=slot.pid,
                     target=self.target)
        logger.info('Releasing worker pid %s (slot %d): cordoned, will '
                    'terminate when idle (target %d)', slot.pid,
                    slot.index, self.target)

    def _retire_slot(self, slot):
        if slot.releasing:
            self._released_total += 1
            if not metrics_disabled():
                get_registry().counter(SERVICE_RELEASED).inc()
        try:
            idx = self._slots.index(slot)
        except ValueError:
            return
        self._slots[idx] = None
        self._slots = [s for s in self._slots if s is not None]

    # -- observability -------------------------------------------------------

    def _record(self, action, event=None, **detail):
        """One scaling/repair decision: bounded log (→ /report) + a
        canonical trace instant so Perfetto shows why the fleet
        changed. ``event`` overrides the trace-event name when the log
        action is more specific than the canonical vocabulary."""
        self._decision_seq += 1
        entry = {'action': action, 'ts': time.time()}
        entry.update(detail)
        self._decisions.append(entry)
        name = event or action
        if name in ('worker_spawn', 'worker_release', 'breaker_open',
                    'breaker_close'):
            tracing.record_instant(name, tracing.mint(self._decision_seq),
                                   'supervisor', **detail)

    def _update_gauges(self, now):
        if metrics_disabled():
            return
        open_breakers = sum(1 for s in self._slots if s.breaker_open(now))
        get_registry().gauge(SERVICE_BREAKER_OPEN).set(open_breakers)

    def status(self):
        """The supervisor's /health contribution. Deliberately lockless:
        tick() holds the lock across real process spawns (tens of ms
        each), and a /health scrape must not stall behind a respawn
        batch — list() snapshots the slot list at C level and the
        descriptor fields are single-value reads, so the worst case is
        one scrape seeing a mid-transition seat."""
        now = time.monotonic()
        slots = [s.descriptor(now) for s in list(self._slots)]
        return {
            'target': self.target,
            'min_workers': self._min_workers,
            'max_workers': self._max_workers,
            'breaker_deaths': self._breaker_deaths,
            'breaker_window_s': self._breaker_window_s,
            'scale_window_s': self._scale.window_s,
            'spawned_total': self._spawned_total,
            'released_total': self._released_total,
            'slots': slots,
        }

    def decisions(self):
        """The bounded scaling/repair decision log (/report)."""
        return list(self._decisions)
