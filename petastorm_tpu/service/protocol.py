"""Wire protocol of the disaggregated decode service.

One ZMQ ROUTER socket on the dispatcher; one DEALER per worker server. All
messages are multipart frames; the first payload frame is the message type.
The DEALER side sends ``[TYPE, ...]``; the ROUTER side sees
``[identity, TYPE, ...]`` and addresses replies with the same identity.

    worker ──► dispatcher                      dispatcher ──► worker
    REGISTER                                   SPEC <job payload> [<token>]
    READY                                      WORK <item id> <item payload>
    HEARTBEAT [<obs summary> [<token>]]        HEARTBEAT_ACK [<token>]
    DONE <item id> <metrics> <result>*         STOP
    ERROR <item id> <exc payload> <metrics>
    BYE

A *standing* daemonized dispatcher (docs/service.md, "Standing
service") additionally speaks a client vocabulary on the SAME ROUTER
socket — clients are DEALER peers exactly like workers, told apart by
message type alone:

    client ──► daemon                          daemon ──► client
    REGISTER_JOB <spec> <params json>          JOB_OK <job id> <token>
                                               BUSY <info json>  (retryable)
    SUBMIT <job id> <client item id> <item>    RESULT <kind> <cid> <payload>*
    CLIENT_HB <job id> <acked count>           CLIENT_HB_ACK <token> <status>
    JOB_GONE <job id>                          JOB_EXPIRED <job id>

The optional trailing ``<token>`` frames carry the dispatcher
*incarnation token* (random per Dispatcher instance). A worker
remembers the token its SPEC carried, echoes it on every HEARTBEAT (its
own frame after the — possibly empty — summary frame, deliberately NOT
a field inside the advisory summary: the dispatcher's is-this-my-worker
check is a correctness signal and must survive the summary path
degrading), and treats an ack bearing a DIFFERENT token as proof that a
new dispatcher took the endpoint (client restart) — its job spec and
item-id numbering are dead, so it abandons the job and re-registers
instead of mixing two incarnations' item ids (docs/service.md,
"Failure semantics"). The dispatcher, symmetrically, re-admits a
foreign-token worker for liveness but never assigns it work. Both
directions stay compatible with token-less builds: an old worker
ignores the trailing frames, an old dispatcher simply never sends one
(the worker then falls back to the ack-timeout path).

The ``<metrics>`` frame piggybacks the worker server's telemetry delta
(:meth:`~petastorm_tpu.telemetry.registry.MetricsRegistry.collect_delta`)
on each completion — an empty frame when nothing changed — so the
dispatcher aggregates stage timings and stall clocks fleet-wide without a
separate metrics channel (docs/telemetry.md). The HEARTBEAT's optional
trailing frame piggybacks the worker server's per-heartbeat
observability summary (JSON: pid, uptime, headline counter rates, local
anomaly counts, its own obs endpoint port) the same way — the
dispatcher keeps the latest per worker and serves the merged fleet view
with per-worker breakdown on its ``/report`` endpoint. Both directions
stay compatible with builds lacking the frame: an old worker sends a
bare HEARTBEAT, an old dispatcher ignores trailing frames. With per-item
tracing on
(``PETASTORM_TPU_TRACE=1``) the same frame also carries the server's
flight-recorder batch (``trace_events``): a traced item's context rides
in the WORK payload's kwargs, its worker-side events ride back here, and
the dispatcher lands them in the consumer-side recorder — one export
then shows the whole distributed timeline.

Payload encodings reuse the local pools' codecs: work items and the job spec
ride dill (same framing the :class:`~petastorm_tpu.workers.process_pool
.ProcessPool` uses for its work channel); result payloads ride the pluggable
:mod:`~petastorm_tpu.serializers` codec named in the job spec
(:class:`~petastorm_tpu.serializers.PickleSerializer` by default).

Trust model: payloads are dill/pickle — arbitrary code execution by design
(the job spec IS code). Bind the dispatcher to loopback or a private
cluster network only, exactly like the tf.data service's gRPC workers.
"""

import dill

# worker -> dispatcher
MSG_REGISTER = b'REG'
MSG_READY = b'RDY'
MSG_HEARTBEAT = b'HB'
MSG_DONE = b'DONE'
MSG_ERROR = b'ERR'
MSG_BYE = b'BYE'

# dispatcher -> worker
MSG_SPEC = b'SPEC'
MSG_WORK = b'WORK'
MSG_STOP = b'STOP'
MSG_HEARTBEAT_ACK = b'HBACK'

# client -> daemonized dispatcher (docs/service.md, "Standing service").
# These frames are ADDITIVE: a standing daemon still speaks the whole
# worker vocabulary above unchanged (an old-build worker server needs no
# REGISTER_JOB awareness), and an old embedded dispatcher that receives
# one of these simply logs an unknown message type — both directions
# stay compatible with frame-less builds.
MSG_REGISTER_JOB = b'REGJOB'     # [REGJOB, <spec payload>, <params json>]
MSG_SUBMIT = b'SUBMIT'           # [SUBMIT, <job id>, <client item id>, <payload>]
MSG_CLIENT_HB = b'CHB'           # [CHB, <job id>, <acked count>]
MSG_JOB_GONE = b'JOBGONE'        # [JOBGONE, <job id>]

# daemonized dispatcher -> client
MSG_JOB_OK = b'JOBOK'            # [JOBOK, <job id>, <token>]
MSG_BUSY = b'BUSY'               # [BUSY, <info json>] — retryable refusal
MSG_JOB_EXPIRED = b'JOBEXP'      # [JOBEXP, <job id>] — lease lapsed / unknown
MSG_CLIENT_HB_ACK = b'CHBACK'    # [CHBACK, <token>, <status json>]
MSG_RESULT = b'RES'              # [RES, <kind>, <client item id>, <payload>*]
# MSG_RESULT's kind frame carries b'result' / b'error' / b'marker' /
# b'poisoned' — the wire form of the dispatcher's local delivery tuples

# warm-standby replication (docs/service.md, "High availability"). The
# standby daemon is one more DEALER peer on the primary's ROUTER socket
# — told apart by message type exactly like clients — that periodically
# pulls a registry snapshot. Pull, not push: the primary stays ignorant
# of how many standbys watch it, and a lapsed standby costs nothing.
# These frames are ADDITIVE like the client vocabulary: an old
# dispatcher logs an unknown message type and the standby degrades to a
# cold promote (re-registration only).
MSG_STANDBY_SYNC = b'SSYNC'      # [SSYNC] — standby pulls a snapshot
MSG_STANDBY_STATE = b'SSTATE'    # [SSTATE, <token>, <state payload>]

# fleet-wide decoded-cache tier (docs/service.md, "Fleet cache tier").
# Two ADDITIVE vocabularies. (1) Directory lookups on the dispatcher's
# ROUTER: a worker's peer-cache client is one more DEALER peer (its own
# socket — the worker's network loop owns the main DEALER) asking which
# fleet members hold a decoded entry digest. (2) Entry fetches on a
# worker server's OWN serve ROUTER: a fetching peer asks for the
# finished Arrow IPC bytes of one entry. Old builds on either side log
# an unknown message type and the fetcher degrades to local decode —
# never wrong, only decode-priced.
MSG_DIR_GET = b'DIRGET'          # [DIRGET, <digests json list>]
MSG_DIR = b'DIR'                 # [DIR, <{digest: [[endpoint, size], ...]} json>]
MSG_PEER_FETCH = b'PFETCH'       # [PFETCH, <digest>]
MSG_PEER_ENTRY = b'PENTRY'       # [PENTRY, <digest>, <meta json>, <chunk>*]
MSG_PEER_MISS = b'PMISS'         # [PMISS, <digest>] — holder no longer has it


def pack_item_id(item_id):
    return b'%d' % item_id


def unpack_item_id(frame):
    return int(frame)


def dump_job_spec(worker_class, worker_args, serializer):
    """The payload a worker server needs to become this job's decode worker."""
    return dill.dumps((worker_class, worker_args, serializer))


def load_job_spec(payload):
    return dill.loads(payload)


def dump_work_item(args, kwargs):
    return dill.dumps((args, kwargs))


def load_work_item(payload):
    return dill.loads(payload)


def dump_exception(exc):
    try:
        return dill.dumps(exc)
    except Exception:  # noqa: BLE001 - unpicklable exception
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('exception-pickle')
        return dill.dumps(RuntimeError('%s: %s' % (type(exc).__name__, exc)))


def load_exception(payload):
    return dill.loads(payload)


def dump_metrics_delta():
    """The calling process's registry increments since the previous call,
    framed for the wire (b'' when nothing changed — telemetry must never
    fail a completion, so errors degrade to the empty frame). One shared
    framing with the process pool's markers
    (:func:`petastorm_tpu.telemetry.registry.dump_delta_frame`)."""
    from petastorm_tpu.telemetry.registry import dump_delta_frame
    return dump_delta_frame()


def load_metrics_delta(frame):
    """Inverse of :func:`dump_metrics_delta`; None for empty, undecodable
    or non-delta-shaped frames (a dropped delta loses gauge freshness,
    nothing more)."""
    from petastorm_tpu.telemetry.registry import load_delta_frame
    return load_delta_frame(frame)


def dump_obs_summary(summary):
    """Frame a worker server's per-heartbeat observability summary
    (:class:`~petastorm_tpu.telemetry.timeseries.HeartbeatSummarizer`)
    for the HEARTBEAT message's optional trailing frame. JSON, not dill:
    the payload is plain scalars and the dispatcher must be able to
    serve it to an HTTP scrape verbatim. Errors degrade to ``b''``
    (observability must never fail a heartbeat)."""
    import json

    try:
        return json.dumps(summary).encode()
    except Exception:  # noqa: BLE001 - telemetry is advisory
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('obs-summary-encode')
        return b''


def load_obs_summary(frame):
    """Inverse of :func:`dump_obs_summary`; None for empty, undecodable
    or non-dict frames (a pre-observability worker build sends a bare
    HEARTBEAT — the absence of the frame is the compatible case)."""
    if not frame:
        return None
    import json

    try:
        summary = json.loads(frame)
    except Exception:  # noqa: BLE001 - telemetry is advisory
        return None
    return summary if isinstance(summary, dict) else None


def dump_poisoned_info(info):
    """Frame a quarantine descriptor for a client job's RESULT channel.
    dill-first so ``poison_policy='raise'`` can surface the ORIGINAL
    worker exception on the client; an unpicklable member degrades the
    ``error`` field to its repr (the loss is cosmetic — the quarantine
    itself, the attempts count and the reason always arrive)."""
    import dill

    try:
        return dill.dumps(info)
    except Exception:  # noqa: BLE001 - unpicklable member
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('poisoned-info-pickle')
        degraded = dict(info)
        if degraded.get('error') is not None:
            degraded['error'] = RuntimeError(repr(degraded['error']))
        try:
            return dill.dumps(degraded)
        except Exception:  # noqa: BLE001 - give the client SOMETHING
            return dill.dumps({'item_id': info.get('item_id'),
                               'attempts': info.get('attempts'),
                               'reason': str(info.get('reason')),
                               'error': None})


def load_poisoned_info(payload):
    import dill

    return dill.loads(payload)


def dump_json_params(params):
    """Frame a small scalar dict (job params, BUSY info, heartbeat-ack
    status) as JSON — NOT dill: these frames cross trust-relevant
    client/daemon boundaries where arbitrary-code payloads are reserved
    for the job spec alone, and the daemon must be able to serve them to
    an HTTP scrape verbatim. Errors degrade to ``b'{}'``."""
    import json

    try:
        return json.dumps(params or {}).encode()
    except Exception:  # noqa: BLE001 - params are advisory metadata
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('json-params-encode')
        return b'{}'


def load_json_params(frame):
    """Inverse of :func:`dump_json_params`; ``{}`` for empty, undecodable
    or non-dict frames (a missing param falls back to its default)."""
    if not frame:
        return {}
    import json

    try:
        params = json.loads(frame)
    except Exception:  # noqa: BLE001 - advisory metadata
        return {}
    return params if isinstance(params, dict) else {}


def dump_standby_state(state):
    """Frame the dispatcher's replication snapshot (job specs, leases,
    credit watermarks, QoS params, fleet cache directory — see
    ``Dispatcher.standby_snapshot``) for the SSTATE reply. dill, not
    JSON: the snapshot embeds the jobs' spec payloads verbatim, which
    are dill by design (the job spec IS code — same trust model as the
    rest of the wire). Errors degrade to ``b''`` (a lost snapshot costs
    one sync round, never the primary)."""
    try:
        return dill.dumps(state)
    except Exception:  # noqa: BLE001 - replication is advisory
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('standby-state-encode')
        return b''


def load_standby_state(payload):
    """Inverse of :func:`dump_standby_state`; None for empty or
    undecodable frames (the standby keeps its previous snapshot and the
    lag gauge shows the staleness)."""
    if not payload:
        return None
    try:
        state = dill.loads(payload)
    except Exception:  # noqa: BLE001 - replication is advisory
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('standby-state-decode')
        return None
    return state if isinstance(state, dict) else None


def free_tcp_port(host='127.0.0.1'):
    """A currently-free TCP port on ``host`` (small bind race accepted;
    used by tests and the benchmark CLI to pre-agree an endpoint)."""
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]
