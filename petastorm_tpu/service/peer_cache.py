"""Fleet-wide decoded-cache tier: decode once per FLEET, not per host.

PR 7's materialized decoded-row-group cache is host-local: N hosts
reading one hot dataset each pay their own cold decode, and N
independent LRUs evict shared data blindly. Cache-aware placement
(service/placement.py) reduces how often a cold host sees a warm
dataset; this module makes the residual misses wire-priced instead of
decode-priced, the shape both the tf.data service paper (PAPERS.md,
arxiv 2210.14826) and the reproducible-pipelines work (arxiv
2604.21275) identify as where fleet throughput is won. Four planes:

* **ADVERT** — each worker server's :class:`PeerCacheServer` scans its
  decoded-cache directory at startup (durable across restarts) and
  advertises the entry digests it holds: a full set on REGISTER, then
  bounded add/remove/touch deltas inside the existing heartbeat obs
  summary (``summary['peer']``), with hard caps and carry-over so one
  huge tier can never blow the heartbeat frame.
* **DIRECTORY** — the dispatcher folds adverts into a
  :class:`FleetCacheDirectory` (digest → holder identities), pruned on
  deregister, replicated into the standby snapshot (failover keeps the
  map), answered on-demand (``DIRGET``/``DIR`` — the fetcher brings its
  OWN DEALER; the worker's network loop owns the main socket) and
  piggybacked as an additive trailing frame on WORK messages.
* **PEER FETCH** — on a local disk miss with a known holder,
  :class:`PeerCacheClient` fetches the finished Arrow IPC entry bytes
  from the holder's serve ROUTER (streamed as zero-copy multipart
  frames) into a byte-budgeted receive arena (the readahead
  ``_BufferPool``), verifies length + content sha1, publishes through
  the cache's atomic tmp+rename path and serves the batch under the
  canonical ``peer_fetch`` stage — decode never runs. EVERY failure
  (no holder, peer gone, timeout, budget exhausted, corrupt frame)
  returns None, is counted by reason, and falls back to local decode:
  degraded is never wrong. Faultpoints ``zmq.peer_serve`` /
  ``zmq.peer_fetch`` make peer loss chaos-drillable.
* **GLOBAL EVICTION** — the dispatcher computes fleet-wide LRU pressure
  from the adverts (holder count + last touch) and ships advisory
  evict-hints on heartbeat ACKs to the stale holders of over-replicated
  cold entries; the holder re-checks its OWN atime before acting, so
  local recency — and local size bounds — stay authoritative.

``PETASTORM_TPU_PEER_CACHE=0`` disables every plane and is the
exact-parity host-local oracle (docs/service.md, "Fleet cache tier").
"""

import hashlib
import json
import logging
import os
import re
import threading
import time

from petastorm_tpu import faults
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.telemetry import count_swallowed, knobs, span
from petastorm_tpu.telemetry.registry import get_registry

logger = logging.getLogger(__name__)

# telemetry counter names (read back by telemetry.export's peer-cache
# section); hits/bytes count successful fetches on the FETCHING worker,
# misses carry the degrade reason, evict_hints counts dispatcher hints
PEER_CACHE_HITS = 'petastorm_tpu_peer_cache_hits_total'
PEER_CACHE_MISSES = 'petastorm_tpu_peer_cache_misses_total'
PEER_CACHE_BYTES = 'petastorm_tpu_peer_cache_bytes_total'
PEER_CACHE_EVICT_HINTS = 'petastorm_tpu_peer_cache_evict_hints_total'

#: a decoded-cache entry basename is its key's sha1 hexdigest
_DIGEST_RE = re.compile(r'[0-9a-f]{40}')
_ENTRY_SUFFIX = '.arrow'

#: serve-side chunking: slices of ONE read, sent zero-copy — multipart
#: framing, not multiple copies
_CHUNK_BYTES = 4 << 20

# advert bounds: the heartbeat frame must stay small no matter how big
# the tier is; anything over a cap carries over to the next heartbeat
_ADVERT_CAP = 64
_TOUCH_CAP = 32
_REGISTER_CAP = 1024
_RESCAN_INTERVAL_S = 2.0
#: atime churn below this granularity is not re-advertised (global
#: eviction only needs coarse last-touch)
_TOUCH_GRANULARITY_S = 5.0

# dispatcher-side bounds
_DIR_LOG_CAP = 512          # recent-digest log feeding WORK piggybacks
_WORK_PIGGYBACK_CAP = 32    # digests per WORK trailing frame
_HINTS_PER_ACK_CAP = 16     # evict-hints per heartbeat ACK
_PENDING_HINTS_CAP = 64     # queued hints per worker
_SNAPSHOT_CAP = 4096        # digests replicated to the standby
_SEED_TTL_S = 60.0          # failover-seeded entries age out unclaimed
_SEED_PREFIX = b'@seed/'    # synthetic holder identity for seeded rows

#: a digest the directory just said nobody holds is not re-asked for
#: this long (cold-start protection: the first epoch would otherwise
#: pay one DIRGET round-trip per miss)
_NEGATIVE_TTL_S = 3.0
_MIRROR_CAP = 8192


def peer_cache_enabled():
    """On by default; ``PETASTORM_TPU_PEER_CACHE=0`` is the host-local
    exact-parity oracle."""
    return not knobs.is_disabled('PETASTORM_TPU_PEER_CACHE')


def entry_digest(path):
    """The advertised digest of a decoded-cache entry path (the sha1
    basename), or None for anything that is not an entry."""
    name = os.path.basename(path)
    if not name.endswith(_ENTRY_SUFFIX):
        return None
    stem = name[:-len(_ENTRY_SUFFIX)]
    return stem if _DIGEST_RE.fullmatch(stem) else None


def digest_entry_path(cache_dir, digest):
    """Inverse of :func:`entry_digest` under the cache's sharded layout."""
    return os.path.join(cache_dir, digest[:2], digest + _ENTRY_SUFFIX)


# -- worker side: the serve socket + advert source ---------------------------


class PeerCacheServer:
    """One per worker-server process: a ROUTER serving finished entry
    bytes to fleet peers, plus the digest registry the adverts are cut
    from (startup directory scan → durable across restarts; periodic
    rescan + in-process publish notifications keep it fresh)."""

    def __init__(self, cache_dir, host=None):
        import zmq
        self.cache_dir = cache_dir
        host = (host
                or knobs.get_str('PETASTORM_TPU_PEER_CACHE_HOST')
                or '127.0.0.1')
        self._context = zmq.Context()
        self._sock = self._context.socket(zmq.ROUTER)
        self._sock.setsockopt(zmq.LINGER, 0)
        port = self._sock.bind_to_random_port('tcp://%s' % host)
        self.endpoint = 'tcp://%s:%d' % (host, port)
        self._lock = threading.Lock()
        self._entries = {}     # digest -> (size, atime)
        self._announced = {}   # digest -> (size, atime) as last advertised
        self._last_scan = 0.0
        self._closed = threading.Event()
        self.served = 0
        self.evicted_on_hint = 0
        self._rescan(force=True)
        self._thread = threading.Thread(target=self._serve_loop,
                                        daemon=True,
                                        name='peer-cache-serve')
        self._thread.start()
        # immediate adverts for entries THIS process publishes (the scan
        # would lag by its rescan interval)
        from petastorm_tpu import materialized_cache
        materialized_cache.add_publish_listener(self._note_published)
        logger.info('Peer cache serving %s at %s', cache_dir, self.endpoint)

    # -- digest registry -----------------------------------------------------

    def _note_published(self, path, size):
        digest = entry_digest(path)
        if digest is None or os.path.dirname(os.path.dirname(path)) \
                != self.cache_dir.rstrip(os.sep):
            return
        with self._lock:
            self._entries[digest] = (size, time.time())

    def _rescan(self, force=False):
        now = time.monotonic()
        if not force and now - self._last_scan < _RESCAN_INTERVAL_S:
            return
        self._last_scan = now
        from petastorm_tpu.cache import scan_dir_entries
        try:
            found, _ = scan_dir_entries(self.cache_dir)
        except Exception:  # noqa: BLE001 - adverts are advisory
            count_swallowed('peer-cache-scan')
            return
        entries = {}
        for atime, size, path in found:
            digest = entry_digest(path)
            if digest:
                entries[digest] = (size, atime)
        with self._lock:
            self._entries = entries

    def full_advert(self):
        """The REGISTER advert: every held digest (freshest first, capped
        — an over-cap tier trickles the tail through heartbeat deltas).
        Resets the delta baseline to what this advert carries."""
        self._rescan(force=True)
        with self._lock:
            items = sorted(self._entries.items(),
                           key=lambda kv: -kv[1][1])[:_REGISTER_CAP]
            self._announced = dict(items)
            full = [[d, size, int(atime)] for d, (size, atime) in items]
        return {'ep': self.endpoint, 'full': full}

    def advert_delta(self):
        """The bounded per-heartbeat delta (``summary['peer']``): adds,
        removes and coarse last-touch updates since the previous advert,
        hard-capped with carry-over. None when nothing changed."""
        self._rescan()
        adds, removes, touches = [], [], []
        with self._lock:
            for digest, (size, atime) in self._entries.items():
                old = self._announced.get(digest)
                if old is None:
                    if len(adds) < _ADVERT_CAP:
                        adds.append([digest, size, int(atime)])
                        self._announced[digest] = (size, atime)
                elif atime - old[1] >= _TOUCH_GRANULARITY_S:
                    if len(touches) < _TOUCH_CAP:
                        touches.append([digest, int(atime)])
                        self._announced[digest] = (size, atime)
            for digest in list(self._announced):
                if digest not in self._entries and len(removes) < _ADVERT_CAP:
                    removes.append(digest)
                    del self._announced[digest]
        if not (adds or removes or touches):
            return None
        out = {'ep': self.endpoint}
        if adds:
            out['add'] = adds
        if removes:
            out['rm'] = removes
        if touches:
            out['t'] = touches
        return out

    def apply_evict_hints(self, digests):
        """Advisory global-eviction hints from the dispatcher: drop an
        over-replicated entry ONLY if it is cold locally too — local
        recency (and local size bounds) stay authoritative. Returns the
        number removed."""
        cold_s = knobs.get_float('PETASTORM_TPU_PEER_CACHE_COLD_S', 300.0,
                                 floor=0.0)
        removed = 0
        now = time.time()
        for digest in list(digests)[:_HINTS_PER_ACK_CAP]:
            if not isinstance(digest, str) \
                    or not _DIGEST_RE.fullmatch(digest):
                continue
            path = digest_entry_path(self.cache_dir, digest)
            try:
                if now - os.stat(path).st_atime < cold_s:
                    continue  # locally hot: decline the hint
                os.remove(path)
            except OSError:
                continue
            removed += 1
            with self._lock:
                self._entries.pop(digest, None)
        if removed:
            self.evicted_on_hint += removed
        return removed

    # -- the serve loop ------------------------------------------------------

    def _serve_loop(self):
        import zmq
        poller = zmq.Poller()
        poller.register(self._sock, zmq.POLLIN)
        while not self._closed.is_set():
            try:
                if not poller.poll(100):
                    continue
                frames = self._sock.recv_multipart()
            except Exception:  # noqa: BLE001 - context shut down under us
                if self._closed.is_set():
                    return
                count_swallowed('peer-serve-recv')
                continue
            try:
                self._serve_one(frames)
            except Exception:  # noqa: BLE001 - serving is advisory: the
                # fetcher times out into local decode, never an error
                count_swallowed('peer-serve')

    def _serve_one(self, frames):
        if len(frames) < 3 or frames[1] != proto.MSG_PEER_FETCH:
            return  # unknown vocabulary: additive compatibility — ignore
        identity, digest_frame = frames[0], frames[2]
        digest = digest_frame.decode('ascii', 'replace')
        reply = None
        if _DIGEST_RE.fullmatch(digest):
            reply = self._entry_reply(digest, digest_frame)
        if reply is None:
            reply = [proto.MSG_PEER_MISS, digest_frame]
        if faults.ARMED and faults.fault_hit('zmq.peer_serve',
                                             key=digest) == 'drop':
            return  # injected peer loss: no reply, fetcher degrades
        self._sock.send_multipart([identity] + reply, copy=False)
        if reply[0] == proto.MSG_PEER_ENTRY:
            self.served += 1

    def _entry_reply(self, digest, digest_frame):
        path = digest_entry_path(self.cache_dir, digest)
        try:
            with open(path, 'rb') as f:
                data = f.read()
        except OSError:
            return None  # evicted since advertised: honest PMISS
        meta = {'size': len(data), 'sha1': hashlib.sha1(data).hexdigest()}
        # memoryview slices of the ONE read: zmq ships each chunk frame
        # without another copy (it holds the buffer until sent)
        view = memoryview(data)
        chunks = [view[i:i + _CHUNK_BYTES]
                  for i in range(0, len(data), _CHUNK_BYTES)]
        return [proto.MSG_PEER_ENTRY, digest_frame,
                proto.dump_json_params(meta)] + chunks

    # -- observability / lifecycle -------------------------------------------

    def health_snapshot(self):
        with self._lock:
            entries = len(self._entries)
            nbytes = sum(size for size, _ in self._entries.values())
        return {'endpoint': self.endpoint, 'cache_dir': self.cache_dir,
                'entries': entries, 'bytes': nbytes, 'served': self.served,
                'evicted_on_hint': self.evicted_on_hint}

    def close(self):
        self._closed.set()
        from petastorm_tpu import materialized_cache
        materialized_cache.remove_publish_listener(self._note_published)
        self._thread.join(2.0)
        try:
            self._sock.close(0)
            self._context.term()
        except Exception:  # noqa: BLE001 - best-effort shutdown
            count_swallowed('peer-serve-close')


_SERVER = None
_SERVER_LOCK = threading.Lock()


def get_server(cache_dir, host=None):
    """The process-wide peer serve socket over ``cache_dir``, started on
    first use (restarted when a job reroots to a different directory)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None and _SERVER.cache_dir != cache_dir:
            _SERVER.close()
            _SERVER = None
        if _SERVER is None:
            _SERVER = PeerCacheServer(cache_dir, host=host)
        return _SERVER


def server_snapshot():
    """The live server's health view, or None when none is running."""
    server = _SERVER
    return server.health_snapshot() if server is not None else None


def close_server():
    """Shut the process-wide serve socket down (worker-server exit)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None


# -- worker side: the fetch path ---------------------------------------------


class PeerCacheClient:
    """The miss-path fetcher a job's :class:`~petastorm_tpu
    .materialized_cache.MaterializedRowGroupCache` calls before paying a
    decode. Owns its own DEALER sockets — the worker's network loop owns
    the main dispatcher socket, and the fetch runs on the executor
    thread. Every failure degrades to local decode, counted by reason."""

    def __init__(self, dispatcher_endpoint, self_endpoint=None):
        self._dispatcher_endpoint = dispatcher_endpoint
        self._self_endpoint = self_endpoint
        self._timeout_s = knobs.get_float(
            'PETASTORM_TPU_PEER_CACHE_TIMEOUT_S', 2.0, floor=0.05)
        budget_mb = knobs.get_int('PETASTORM_TPU_PEER_CACHE_BUDGET_MB', 64,
                                  floor=1)
        # the readahead plane's byte-budgeted arena: all-or-nothing
        # acquire, so an oversized fetch degrades to decode instead of
        # unbounded receive buffering
        from petastorm_tpu.readahead import _BufferPool
        self._pool = _BufferPool(budget_mb << 20)
        self._lock = threading.Lock()
        self._mirror = {}    # digest -> [[endpoint, size], ...]
        self._negative = {}  # digest -> monotonic expiry of "nobody has it"
        self._context = None
        self._dir_sock = None
        self._acquired_now = 0
        self.hits = 0
        self.misses = 0

    # -- directory mirror ----------------------------------------------------

    def update_directory(self, mapping):
        """Fold a directory fragment (WORK piggyback / DIR reply) into
        the local mirror. Called from the worker's network loop."""
        if not isinstance(mapping, dict):
            return
        with self._lock:
            for digest, holders in mapping.items():
                if not isinstance(holders, list):
                    continue
                if holders:
                    self._mirror[digest] = holders
                    self._negative.pop(digest, None)
                else:
                    self._mirror.pop(digest, None)
            while len(self._mirror) > _MIRROR_CAP:
                self._mirror.pop(next(iter(self._mirror)))

    def _resolve(self, digest):
        now = time.monotonic()
        with self._lock:
            holders = self._mirror.get(digest)
            if holders:
                return holders
            if self._negative.get(digest, 0.0) > now:
                return None
        holders = self._dir_lookup(digest)
        if not holders:
            with self._lock:
                if len(self._negative) > 4096:
                    self._negative = {d: t for d, t in
                                      self._negative.items() if t > now}
                self._negative[digest] = now + _NEGATIVE_TTL_S
            return None
        return holders

    def _dir_lookup(self, digest):
        """One on-demand DIRGET round-trip on the client's own DEALER."""
        try:
            sock = self._dir_socket()
            sock.send_multipart([proto.MSG_DIR_GET,
                                 json.dumps([digest]).encode()])
            deadline = time.monotonic() + self._timeout_s
            while True:
                remaining_ms = int((deadline - time.monotonic()) * 1000)
                if remaining_ms <= 0 or not sock.poll(remaining_ms):
                    self._reset_dir_socket()
                    return None
                frames = sock.recv_multipart()
                if not frames or frames[0] != proto.MSG_DIR:
                    continue  # foreign frame on our private socket
                mapping = proto.load_json_params(
                    frames[1] if len(frames) > 1 else b'')
                self.update_directory(mapping)
                if digest in mapping:
                    holders = mapping[digest]
                    return holders if holders else None
                # a stale reply from an earlier timed-out lookup: folded
                # into the mirror above, keep draining for ours
        except Exception:  # noqa: BLE001 - the directory is advisory
            count_swallowed('peer-dir-lookup')
            self._reset_dir_socket()
            return None

    def _dir_socket(self):
        import zmq
        with self._lock:
            if self._context is None:
                self._context = zmq.Context()
            if self._dir_sock is None:
                sock = self._context.socket(zmq.DEALER)
                sock.setsockopt(zmq.LINGER, 0)
                sock.connect(self._dispatcher_endpoint)
                self._dir_sock = sock
            return self._dir_sock

    def _reset_dir_socket(self):
        # a timed-out lookup may leave a late reply in flight; a fresh
        # socket next time beats matching stale replies forever
        with self._lock:
            if self._dir_sock is not None:
                try:
                    self._dir_sock.close(0)
                except Exception:  # noqa: BLE001 - already dead
                    pass
                self._dir_sock = None

    def _forget(self, digest, endpoint):
        with self._lock:
            holders = self._mirror.get(digest)
            if not holders:
                return
            holders = [h for h in holders if not h or h[0] != endpoint]
            if holders:
                self._mirror[digest] = holders
            else:
                self._mirror.pop(digest, None)

    # -- the fetch -----------------------------------------------------------

    def fetch(self, key, entry, cache):
        """Fetch the finished entry for ``key`` from a peer, publish it
        into ``cache``'s disk tier and return ``(columns, length)`` —
        or None after ANY failure (counted by reason; the caller then
        decodes locally, so a degraded fetch is never wrong)."""
        digest = entry_digest(entry)
        if digest is None:
            return None
        holders = [h for h in (self._resolve(digest) or ())
                   if isinstance(h, (list, tuple)) and len(h) >= 2
                   and h[0] != self._self_endpoint]
        if not holders:
            return self._miss('no_holder')
        endpoint, advertised_size = str(holders[0][0]), int(holders[0][1])
        acquired = max(advertised_size, 1)
        if not self._pool.acquire(acquired):
            return self._miss('budget')
        self._acquired_now = acquired
        try:
            with span('peer_fetch'):
                # The fetched entry's mmap'd views transfer to the caller
                # exactly like a local cache hit: the published disk
                # entry owns the memory.  # pipesan: owns
                return self._fetch_from(endpoint, digest, entry, cache,
                                        acquired)
        except faults.FaultInjected:
            return self._miss('injected')
        except Exception:  # noqa: BLE001 - degrade to local decode
            logger.debug('peer fetch of %s from %s failed', digest,
                         endpoint, exc_info=True)
            count_swallowed('peer-fetch')
            return self._miss('error')
        finally:
            self._pool.free(self._acquired_now)

    def _fetch_from(self, endpoint, digest, entry, cache, acquired):
        self._acquired_now = acquired
        if faults.ARMED and faults.fault_hit('zmq.peer_fetch',
                                             key=digest) == 'drop':
            return self._miss('injected')
        frames = self._request(endpoint, digest)
        if frames is None:
            self._forget(digest, endpoint)
            return self._miss('timeout')
        if frames and frames[0] == proto.MSG_PEER_MISS:
            self._forget(digest, endpoint)
            return self._miss('peer_miss')
        if len(frames) < 3 or frames[0] != proto.MSG_PEER_ENTRY:
            return self._miss('protocol')
        meta = proto.load_json_params(frames[2])
        chunks = frames[3:]
        got = sum(len(c) for c in chunks)
        if got != int(meta.get('size', -1)):
            return self._miss('corrupt')
        if got > acquired:
            # the advert under-sold the entry (re-written since): the
            # arena stays authoritative — grow or degrade
            if not self._pool.acquire(got - acquired):
                return self._miss('budget')
            self._acquired_now = got
        sha = hashlib.sha1()
        for chunk in chunks:
            sha.update(chunk)
        if meta.get('sha1') and sha.hexdigest() != meta['sha1']:
            return self._miss('corrupt')

        def write(tmp):
            with open(tmp, 'wb') as f:
                for chunk in chunks:
                    f.write(chunk)

        cache.publish_fetched(entry, write)
        from petastorm_tpu.materialized_cache import read_entry
        try:
            columns, length, _, _ = read_entry(entry)
        except Exception:  # noqa: BLE001 - holder's entry itself corrupt
            cache._remove_entry(entry)
            return self._miss('corrupt')
        registry = get_registry()
        registry.counter(PEER_CACHE_HITS).inc()
        registry.counter(PEER_CACHE_BYTES).inc(got)
        self.hits += 1
        # read_entry's views are backed by the just-published disk entry
        # (mmap'd, same contract as a cache hit).  # pipesan: owns
        return columns, length

    def _request(self, endpoint, digest):
        """One fetch round-trip on a fresh per-fetch DEALER (fetches are
        the residual-miss path; connection reuse is not worth matching
        replies across entries). None on timeout."""
        import zmq
        with self._lock:
            if self._context is None:
                self._context = zmq.Context()
            context = self._context
        sock = context.socket(zmq.DEALER)
        try:
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(endpoint)
            sock.send_multipart([proto.MSG_PEER_FETCH, digest.encode()])
            if not sock.poll(int(self._timeout_s * 1000)):
                return None
            return sock.recv_multipart()
        finally:
            sock.close(0)

    def _miss(self, reason):
        self.misses += 1
        get_registry().counter(PEER_CACHE_MISSES, reason=reason).inc()
        return None

    def stats(self):
        return {'hits': self.hits, 'misses': self.misses,
                'mirror': len(self._mirror),
                'budget_bytes': self._pool.budget,
                'budget_used': self._pool.used}

    def close(self):
        self._reset_dir_socket()
        with self._lock:
            if self._context is not None:
                try:
                    self._context.term()
                except Exception:  # noqa: BLE001 - already dead
                    pass
                self._context = None


# -- dispatcher side: the fleet directory ------------------------------------


class FleetCacheDirectory:
    """The dispatcher's fold of every worker's adverts: entry digest →
    holder identities (endpoint, size, last touch). Single-threaded with
    the dispatcher loop; every public mutation swallows its own failures
    — the directory is advisory, a stale or lost row costs a wire fetch
    or a redundant decode, never correctness."""

    def __init__(self):
        self._holders = {}        # digest -> {identity: [ep, size, atime]}
        self._digests_of = {}     # identity -> set(digests)
        self._version = 0
        self._log = []            # (version, digest) ring for piggybacks
        self._pending_hints = {}  # identity -> set(digests)
        self._seed_until = 0.0
        self.hints_queued = 0

    # -- folding adverts -----------------------------------------------------

    def note_advert(self, identity, info):
        """Fold one advert dict (REGISTER ``full`` or heartbeat delta)."""
        try:
            self._note_advert(identity, info)
        except Exception:  # noqa: BLE001 - adverts are advisory
            count_swallowed('peer-directory-advert')

    def _note_advert(self, identity, info):
        if not isinstance(info, dict):
            return
        endpoint = info.get('ep')
        if not isinstance(endpoint, str) or not endpoint:
            return
        # a live advert for this endpoint supersedes its failover seed
        self.drop(_SEED_PREFIX + endpoint.encode())
        if 'full' in info:
            self.drop(identity)
            for item in info.get('full') or ():
                self._add(identity, endpoint, item)
            return
        for item in info.get('add') or ():
            self._add(identity, endpoint, item)
        for digest in info.get('rm') or ():
            self._remove(identity, digest)
        for pair in info.get('t') or ():
            self._touch(identity, pair)

    def _add(self, identity, endpoint, item):
        digest = item[0]
        if not isinstance(digest, str) or not _DIGEST_RE.fullmatch(digest):
            return
        self._holders.setdefault(digest, {})[identity] = [
            endpoint, int(item[1]), float(item[2])]
        self._digests_of.setdefault(identity, set()).add(digest)
        self._version += 1
        self._log.append((self._version, digest))
        del self._log[:-_DIR_LOG_CAP]

    def _remove(self, identity, digest):
        holders = self._holders.get(digest)
        if holders is not None:
            holders.pop(identity, None)
            if not holders:
                self._holders.pop(digest, None)
        digests = self._digests_of.get(identity)
        if digests is not None:
            digests.discard(digest)

    def _touch(self, identity, pair):
        digest, atime = pair[0], float(pair[1])
        info = (self._holders.get(digest) or {}).get(identity)
        if info is not None:
            info[2] = atime

    def drop(self, identity):
        """Prune every row of a deregistered worker."""
        for digest in self._digests_of.pop(identity, ()):
            holders = self._holders.get(digest)
            if holders is not None:
                holders.pop(identity, None)
                if not holders:
                    self._holders.pop(digest, None)
        self._pending_hints.pop(identity, None)

    # -- serving lookups -----------------------------------------------------

    def lookup(self, digests, exclude_identity=None):
        """``{digest: [[endpoint, size], ...]}`` (freshest holder first;
        an unknown digest maps to ``[]`` so the asker can negative-cache
        it)."""
        out = {}
        for digest in digests:
            if not isinstance(digest, str):
                continue
            rows = [info for identity, info in
                    (self._holders.get(digest) or {}).items()
                    if identity != exclude_identity]
            rows.sort(key=lambda info: -info[2])
            out[digest] = [[info[0], info[1]] for info in rows]
        return out

    def delta_since(self, since_version, exclude_identity=None):
        """``(new_version, mapping-or-None)`` of digests advertised after
        ``since_version`` — the WORK-frame piggyback, capped; anything
        beyond the window is served by DIRGET on demand."""
        if since_version >= self._version:
            return self._version, None
        seen = set()
        digests = []
        for version, digest in reversed(self._log):
            if version <= since_version:
                break
            if digest in seen:
                continue
            seen.add(digest)
            digests.append(digest)
            if len(digests) >= _WORK_PIGGYBACK_CAP:
                break
        mapping = {d: rows for d, rows in
                   self.lookup(digests, exclude_identity).items() if rows}
        return self._version, (mapping or None)

    # -- global eviction -----------------------------------------------------

    def compute_evict_hints(self, now_epoch):
        """Fleet-global LRU pressure: an entry held by more than one
        worker whose FLEET-WIDE freshest touch is older than the cold
        threshold gets hinted away on every holder except the freshest
        — K copies of cold data shrink toward one while hot single-copy
        entries are never touched. Hints queue per worker (bounded) and
        ride the next heartbeat ACK; the holder re-checks its own atime,
        so this stays advisory."""
        cold_s = knobs.get_float('PETASTORM_TPU_PEER_CACHE_COLD_S', 300.0,
                                 floor=0.0)
        if cold_s <= 0:
            return
        for digest, holders in self._holders.items():
            if len(holders) < 2:
                continue
            freshest = max(holders.values(), key=lambda info: info[2])
            if now_epoch - freshest[2] < cold_s:
                continue
            for identity, info in holders.items():
                if info is freshest or identity.startswith(_SEED_PREFIX):
                    continue
                pending = self._pending_hints.setdefault(identity, set())
                if digest not in pending \
                        and len(pending) < _PENDING_HINTS_CAP:
                    pending.add(digest)
                    self.hints_queued += 1

    def take_hints(self, identity):
        """Up to :data:`_HINTS_PER_ACK_CAP` queued hints for one worker's
        heartbeat ACK (the rest stay queued), or None."""
        pending = self._pending_hints.pop(identity, None)
        if not pending:
            return None
        hints = sorted(pending)[:_HINTS_PER_ACK_CAP]
        leftover = pending.difference(hints)
        if leftover:
            self._pending_hints[identity] = leftover
        return hints

    # -- failover ------------------------------------------------------------

    def snapshot(self):
        """Replication view for the standby: the digest → holder map
        keyed by ENDPOINT (identities die with the primary; the serve
        sockets — and their entries — survive it)."""
        out = []
        for digest, holders in self._holders.items():
            out.append([digest, [list(info) for info in holders.values()]])
            if len(out) >= _SNAPSHOT_CAP:
                break
        return out

    def seed(self, snapshot, now_mono):
        """Adopt a failed-over primary's directory under synthetic
        per-endpoint holder identities: DIRGET answers stay warm through
        the failover window. A worker's first real advert for an
        endpoint supersedes its seed; unclaimed seeds age out
        (:data:`_SEED_TTL_S`) via :meth:`expire_seeds`."""
        try:
            for digest, holders in snapshot:
                for info in holders:
                    endpoint = str(info[0])
                    self._add(_SEED_PREFIX + endpoint.encode(), endpoint,
                              [digest, info[1], info[2]])
            self._seed_until = now_mono + _SEED_TTL_S
        except Exception:  # noqa: BLE001 - replication is advisory
            count_swallowed('peer-directory-seed')

    def expire_seeds(self, now_mono):
        if not self._seed_until or now_mono < self._seed_until:
            return
        self._seed_until = 0.0
        for identity in [i for i in self._digests_of
                         if i.startswith(_SEED_PREFIX)]:
            self.drop(identity)

    # -- observability -------------------------------------------------------

    def held_count(self, identity):
        """How many entries one worker advertises (fleet-view row)."""
        return len(self._digests_of.get(identity, ()))

    def stats(self):
        try:
            return {
                'digests': len(self._holders),
                'holders': sum(len(h) for h in self._holders.values()),
                'pending_hints': sum(len(p) for p in
                                     self._pending_hints.values()),
                'hints_queued': self.hints_queued,
                'seeded': any(i.startswith(_SEED_PREFIX)
                              for i in self._digests_of),
            }
        except Exception:  # noqa: BLE001 - racing the dispatcher loop
            return {'digests': -1}
