"""Disaggregated decode service: a remote worker pool over ZMQ ``tcp://``.

The local pools (:mod:`petastorm_tpu.workers`) decode Parquet row-groups with
the consumer host's own CPUs — on a TPU VM those are scarce, and "tf.data
service" (PAPERS.md) shows that moving input processing onto separate CPU
hosts is the single biggest lever for input-bound accelerator jobs. This
package is that lever for petastorm_tpu:

* :mod:`~petastorm_tpu.service.dispatcher` — item scheduler that registers
  worker servers, hands out ventilated row-group items with per-worker
  credit, and **re-ventilates** items owned by workers whose heartbeats
  lapse (fault tolerance = every item delivered exactly once).
* :mod:`~petastorm_tpu.service.worker_server` — a standalone process
  (``python -m petastorm_tpu.service.worker_server``) that runs the existing
  :class:`~petastorm_tpu.workers.worker_base.WorkerBase` decode workers and
  streams results back over ``tcp://``.
* :class:`~petastorm_tpu.service.service_pool.ServicePool` — the client,
  implementing the same pool contract as
  :class:`~petastorm_tpu.workers.thread_pool.ThreadPool` /
  :class:`~petastorm_tpu.workers.process_pool.ProcessPool`, so
  ``Reader(..., reader_pool_type='service')`` and ``make_jax_loader(...)``
  work unchanged.
* :mod:`~petastorm_tpu.service.daemon` — the STANDING service
  (``python -m petastorm_tpu.service``): a daemonized dispatcher that
  outlives any single job (job registry, leases, per-job fair sharing,
  admission control) with the client-side
  :class:`~petastorm_tpu.service.daemon.DaemonClientPool`, plus
  :mod:`~petastorm_tpu.service.supervisor` — the self-healing fleet
  loop (replacement, recruitment, release, circuit breaker).

See ``docs/service.md`` for the topology, the heartbeat/re-ventilation
semantics, the standing-service lifecycle, and when to disaggregate
(keyed to ``JaxLoader.autotune_report()``).
"""

from petastorm_tpu.service.daemon import (  # noqa: F401
    DaemonClientPool, ServiceDaemon,
)
from petastorm_tpu.service.service_pool import ServicePool  # noqa: F401
from petastorm_tpu.service.supervisor import WorkerSupervisor  # noqa: F401
