"""Standing decode service: the daemonized dispatcher and its client.

Before this module the dispatcher lived inside the consumer process and
died with it — one Reader, one fleet, one lifetime. This is the other
half of the tf.data-service design (PAPERS.md, arxiv 2210.14826): a
**control plane that outlives any single job**.

* :class:`ServiceDaemon` — the standing process
  (``python -m petastorm_tpu.service``): hosts a multi-job
  :class:`~petastorm_tpu.service.dispatcher.Dispatcher` (job registry,
  leases, per-job credit, admission control) and a
  :class:`~petastorm_tpu.service.supervisor.WorkerSupervisor`
  (self-healing fleet: replacement, recruitment, release, circuit
  breaker). SIGTERM drains: registered jobs finish while new ones get a
  retryable BUSY; a second signal stops hard. With
  ``PETASTORM_TPU_OBS_PORT`` set the daemon serves ``/health`` (job
  registry, leases, breaker states) and ``/report`` (fleet view +
  scaling-decision log) over HTTP.
* :class:`DaemonClientPool` — the consumer side, implementing the exact
  pool contract of :class:`~petastorm_tpu.service.service_pool
  .ServicePool` (``start / ventilate / get_results / stop / join /
  diagnostics``), so ``Reader(..., reader_pool_type='service')`` with
  ``PETASTORM_TPU_SERVICE_DAEMON`` set — or an explicit pool instance —
  reads through a shared standing fleet instead of hosting its own.

Client-side exactly-once over an unreliable control plane:

* every ventilated item carries a client-side id; the daemon echoes it
  on every RESULT frame;
* an item's result frames are buffered client-side and released into
  the consumer queue only with their **marker** — a daemon that dies
  mid-delivery leaves no half-delivered item behind;
* on daemon loss (heartbeat-ack silence, a ``JOB_EXPIRED`` answer, or
  an incarnation-token change) the client re-registers its job — same
  idempotency key, fresh socket — and **re-submits exactly the items
  its own accounting says were never markered**; markered items are
  never re-sent, and late duplicate deliveries for a re-submitted id
  are dropped by the same accounting. Multiset-exact delivery survives
  a SIGKILLed daemon (``tests/test_daemon.py``).
"""

import collections
import logging
import os
import queue
import signal
import threading
import time
import uuid

from petastorm_tpu.errors import ServiceWedgedError
from petastorm_tpu.serializers import PickleSerializer
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.service.supervisor import WorkerSupervisor
from petastorm_tpu.telemetry import count_swallowed, knobs

logger = logging.getLogger(__name__)

_POLL_INTERVAL_S = 0.05
_NET_POLL_MS = 50
_BIND_TIMEOUT_S = 10.0
_JOIN_TIMEOUT_S = 10.0
_REGISTER_RESEND_S = 1.0
_BUSY_BACKOFF_BASE_S = 0.25
_BUSY_BACKOFF_CAP_S = 5.0
#: fleet-size hint for the ventilator before the first heartbeat-ack
#: status arrives (mirrors ServicePool's hint)
_WORKERS_COUNT_HINT = 4


class ServiceDaemon:
    """The standing control plane: dispatcher + supervisor, one process.

    :param endpoint: ``tcp://host:port`` to bind (port 0 = random; the
        resolved address is :attr:`endpoint` after :meth:`start`).
    :param initial_workers: supervisor fleet size at boot.
    :param supervise: False runs a daemon with NO spawned fleet — for
        externally-managed worker servers (k8s, systemd) pointing their
        ``--endpoint`` here; replacement/recruitment is then the
        external manager's job.
    """

    def __init__(self, endpoint, initial_workers=1, min_workers=None,
                 max_workers=None, heartbeat_interval_s=1.0,
                 liveness_timeout_s=None, max_inflight_per_worker=2,
                 max_retries=None, retry_backoff_s=None, max_jobs=None,
                 lease_s=None, supervise=True, supervisor_tick_s=None,
                 spawn=None, seed_state=None):
        self._stop_event = threading.Event()
        self._heartbeat_interval_s = heartbeat_interval_s
        self._liveness_timeout_s = (liveness_timeout_s
                                    if liveness_timeout_s is not None
                                    else 4.0 * heartbeat_interval_s)
        # seed_state: a promoted standby's replicated registry snapshot
        # (docs/service.md, "High availability") — job identities and
        # QoS params survive the failover; items re-ventilate
        self.dispatcher = Dispatcher(
            endpoint, None, None, self._stop_event,
            heartbeat_interval_s=heartbeat_interval_s,
            liveness_timeout_s=self._liveness_timeout_s,
            max_inflight_per_worker=max_inflight_per_worker,
            max_retries=max_retries, retry_backoff_s=retry_backoff_s,
            standing=True, max_jobs=max_jobs, default_lease_s=lease_s,
            seed_state=seed_state)
        self._initial_workers = initial_workers
        self._min_workers = min_workers
        self._max_workers = max_workers
        self._supervise = supervise
        self._supervisor_tick_s = (supervisor_tick_s
                                   if supervisor_tick_s is not None
                                   else heartbeat_interval_s)
        self._spawn = spawn
        self.supervisor = None
        self._dispatcher_thread = None
        self._obs_mount = None
        self._signals = 0

    @property
    def endpoint(self):
        return self.dispatcher.endpoint

    def start(self):
        if self._dispatcher_thread is not None:
            raise RuntimeError('ServiceDaemon already started')
        self._dispatcher_thread = threading.Thread(
            target=self.dispatcher.run, daemon=True,
            name='service-daemon-dispatcher')
        self._dispatcher_thread.start()
        self.dispatcher.wait_bound(_BIND_TIMEOUT_S)
        if self._supervise:
            self.supervisor = WorkerSupervisor(
                self.dispatcher, self.dispatcher.endpoint,
                initial_workers=self._initial_workers,
                min_workers=self._min_workers,
                max_workers=self._max_workers,
                tick_s=self._supervisor_tick_s,
                heartbeat_interval_s=self._heartbeat_interval_s,
                spawn=self._spawn)
            self.supervisor.start()
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount(
            'service-daemon', health=self.health, report=self.report)
        logger.info('Service daemon up at %s (supervised fleet: %s)',
                    self.dispatcher.endpoint,
                    self._initial_workers if self._supervise
                    else 'external')

    def health(self):
        doc = self.dispatcher.health()
        # HA role: a ServiceDaemon is always the serving head; the warm
        # mirror is a StandbyDaemon whose /health says 'standby' (then
        # 'promoting' / 'primary' as it takes over)
        doc['role'] = 'primary'
        if self.supervisor is not None:
            doc['supervisor'] = self.supervisor.status()
        if doc.get('qos'):
            # per-job SLO view: while an error budget burns, jobs starved
            # below their declared share are flagged raise_weight and
            # over-share jobs lower_weight — advisory, the operator (or a
            # rebinding loop) acts on it
            from petastorm_tpu.telemetry import slo
            doc['slo_advice'] = slo.qos_weight_advice(doc['qos'])
        return doc

    def report(self):
        doc = {'fleet': self.dispatcher.fleet_view(),
               'role': 'primary'}
        if self.supervisor is not None:
            doc['scaling_decisions'] = self.supervisor.decisions()
        return doc

    def begin_drain(self):
        self.dispatcher.begin_drain()

    @property
    def drained(self):
        """True once a draining daemon has no registered jobs left."""
        return self.dispatcher.active_jobs() == 0

    def stop(self):
        self._stop_event.set()
        if self.supervisor is not None:
            self.supervisor.stop()
        if self._obs_mount is not None:
            self._obs_mount.close()
        if self._dispatcher_thread is not None:
            # run() broadcasts STOP to every registered worker on its
            # way out
            self._dispatcher_thread.join(_JOIN_TIMEOUT_S)
            self._dispatcher_thread = None

    # -- the daemon main loop (CLI entry) ------------------------------------

    def _on_signal(self, signum, frame):
        self._signals += 1
        if self._signals == 1:
            logger.warning('Signal %s: draining (in-flight jobs finish; '
                           'new jobs get BUSY; signal again to stop '
                           'hard)', signum)
            self.begin_drain()
        else:
            logger.warning('Signal %s again: stopping hard', signum)
            self._stop_event.set()

    def run_forever(self, install_signals=True, drain_poll_s=0.2):
        """Serve until SIGTERM/SIGINT drains the registry empty (or a
        second signal forces a hard stop). The CLI's body."""
        if install_signals:
            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
        try:
            while not self._stop_event.is_set():
                if self.dispatcher.fatal_error is not None:
                    raise self.dispatcher.fatal_error
                if self.dispatcher.draining and self.drained:
                    logger.info('Drained: no jobs left; exiting')
                    break
                time.sleep(drain_poll_s)
        finally:
            self.stop()


class DaemonClientPool:
    """Client pool registering one job with a standing service daemon.

    Implements the local pools' contract, so the Reader/JaxLoader stack
    is unchanged — the decode fleet is simply *shared* and *standing*.
    The network loop owns the DEALER socket on its own thread; consumer
    threads interact through the bounded results queue and counters.
    """

    def __init__(self, endpoint=None, results_queue_size=50,
                 serializer=None, heartbeat_interval_s=1.0,
                 lease_s=None, connect_timeout_s=30.0,
                 ack_timeout_s=None, poison_policy='raise',
                 read_deadline_s=None, name=None, weight=None,
                 priority=None):
        """
        :param endpoint: the daemon's ``tcp://`` address (default: the
            ``PETASTORM_TPU_SERVICE_DAEMON`` knob).
        :param lease_s: job lease the daemon applies — the client goes
            this silent (no SUBMIT, no heartbeat) and the job is
            reclaimed (default: the daemon's
            ``PETASTORM_TPU_SERVICE_LEASE_S``).
        :param connect_timeout_s: how long ``start()`` (and any later
            re-registration after a daemon loss) retries REGISTER_JOB —
            including through retryable BUSY answers — before failing.
        :param ack_timeout_s: heartbeat-ack silence after which the
            daemon is presumed dead and re-registration begins
            (default ``max(10 × heartbeat_interval, 10s)``).
        :param weight: QoS fair-share weight the job registers with (a
            weight-3 job targets 3x the workers of a weight-1
            co-tenant); default: the ``PETASTORM_TPU_SERVICE_JOB_WEIGHT``
            knob, else the daemon's default of 1.
        :param priority: QoS priority tier (strict admission: a higher
            tier with pending work preempts workers from lower tiers);
            default: the ``PETASTORM_TPU_SERVICE_JOB_PRIORITY`` knob,
            else 0.
        """
        if poison_policy not in ('raise', 'skip'):
            raise ValueError("poison_policy must be 'raise' or 'skip'; "
                             'got %r' % (poison_policy,))
        endpoint = endpoint or knobs.get_str('PETASTORM_TPU_SERVICE_DAEMON')
        if not endpoint:
            raise ValueError('DaemonClientPool needs a daemon endpoint '
                             '(argument or PETASTORM_TPU_SERVICE_DAEMON)')
        self._endpoint = endpoint
        self._results_queue_size = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._heartbeat_interval_s = heartbeat_interval_s
        self._lease_s = lease_s
        self._connect_timeout_s = connect_timeout_s
        self._ack_timeout_s = (ack_timeout_s if ack_timeout_s is not None
                               else max(10 * heartbeat_interval_s, 10.0))
        self.poison_policy = poison_policy
        self._read_deadline_s = (read_deadline_s
                                 if read_deadline_s is not None
                                 else knobs.get_float(
                                     'PETASTORM_TPU_SERVICE_READ'
                                     '_DEADLINE_S', 300.0, floor=0.0))
        self._name = name or 'client-%d' % os.getpid()
        # QoS params ride the REGISTER_JOB params dict; knob defaults so
        # a Reader-embedded client is governable without code changes
        self._weight = (float(weight) if weight is not None
                        else knobs.get_float(
                            'PETASTORM_TPU_SERVICE_JOB_WEIGHT', 0.0,
                            floor=0.0)) or None
        self._priority = (int(priority) if priority is not None
                          else knobs.get_int(
                              'PETASTORM_TPU_SERVICE_JOB_PRIORITY', 0))
        #: decode fingerprint for cache-aware placement, derived from
        #: the job's worker args at start()
        self._fingerprint = None
        #: idempotency key: a re-sent REGISTER_JOB (lost JOB_OK, socket
        #: reset) answers with the SAME job instead of a duplicate
        self._client_key = uuid.uuid4().hex

        self.poisoned_items = []
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._stop_event = threading.Event()
        self._lock = threading.Lock()
        self._ventilated_items = 0
        self._processed_items = 0
        #: markers consumed by get_results — the credit the heartbeat
        #: reports back, which is what lets the daemon bound what it
        #: buffers toward this client
        self._acked = 0
        #: _acked snapshot at the LAST successful registration: each
        #: registration creates a fresh daemon-side job whose
        #: markers_sent starts at 0, so the heartbeat must report the
        #: markers consumed AGAINST THAT JOB (lifetime totals would
        #: leave the new job's credit gate permanently open)
        self._acked_base = 0
        self._item_seq = 0
        #: client item id -> work payload, until its marker arrives:
        #: exactly the set a daemon restart requires re-submitting
        self._outstanding = collections.OrderedDict()
        self._submit_queue = collections.deque()
        self._spec_payload = None
        #: complete-item entries awaiting bounded-queue space. Survives
        #: re-registration: its items were popped from _outstanding (so
        #: they will never be re-submitted) and MUST reach the consumer.
        self._delivery = collections.deque()
        self._registered = threading.Event()
        self._job_id = None
        self._daemon_token = None
        self._job_identity = None
        self._status = {}
        self._reregistrations = 0
        self._net_thread = None
        self._ventilator = None
        self._error = None
        self._joined = False
        self._obs_mount = None
        self._last_progress = None

    # -- pool contract -------------------------------------------------------

    @property
    def workers_count(self):
        """This job's slice of the standing fleet (the ventilator
        re-reads it for its in-flight bound); the whole-fleet count
        before the first status arrives."""
        status = self._status
        count = status.get('job_workers') or status.get('workers_alive')
        return count or _WORKERS_COUNT_HINT

    @property
    def job_id(self):
        return self._job_id

    def start(self, worker_class, worker_args=None, ventilator=None,
              start_ventilator=True):
        if self._net_thread is not None:
            raise RuntimeError('DaemonClientPool already started')
        self._spec_payload = proto.dump_job_spec(worker_class, worker_args,
                                                 self._serializer)
        # cache-aware placement: stamp the registration with the SAME
        # fingerprint the worker servers advertise for their decoded
        # caches (one helper on both sides — placement.py), so the
        # dispatcher can bind this job to a warm host first
        from petastorm_tpu.service.placement import placement_fingerprint
        self._fingerprint = placement_fingerprint(worker_args)
        self._net_thread = threading.Thread(
            target=self._net_loop, daemon=True, name='service-daemon-client')
        self._net_thread.start()
        deadline = time.monotonic() + self._connect_timeout_s + 1.0
        while not self._registered.wait(_POLL_INTERVAL_S):
            if self._error is not None:
                self.stop()
                self.join()
                raise self._error
            if time.monotonic() > deadline:
                self.stop()
                self.join()
                raise RuntimeError(
                    'No job registration with the service daemon at %s '
                    'within %.1fs (is the daemon running? is it '
                    'draining?)' % (self._endpoint,
                                    self._connect_timeout_s))
        # the net loop sets _registered on its way OUT too (so a failed
        # registration can't leave start() waiting forever) — the set
        # event alone is not success
        if self._error is not None or self._job_id is None:
            self.stop()
            self.join()
            raise (self._error if self._error is not None
                   else RuntimeError('Daemon-client network loop exited '
                                     'before registering a job'))
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount('service-daemon-client',
                                           health=self.client_health)
        self._ventilator = ventilator
        if ventilator is not None and start_ventilator:
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        payload = proto.dump_work_item(args, kwargs)
        with self._lock:
            self._ventilated_items += 1
            cid = self._item_seq
            self._item_seq += 1
            self._outstanding[cid] = payload
            self._submit_queue.append(cid)

    def get_results(self, timeout=None):
        from petastorm_tpu.service.service_pool import consume_results
        return consume_results(self, timeout, self._lock,
                               on_marker=self._on_marker,
                               wedge_error=self._wedge_error)

    def _on_marker(self):
        """Shared-loop hook, runs UNDER ``self._lock`` with the
        processed-item increment: count the marker into the ack credit
        the heartbeat reports back to the daemon."""
        self._acked += 1

    def _note_poisoned(self, info):
        """Shared ``poison_policy`` semantics with the embedded pool
        (:func:`~petastorm_tpu.service.service_pool.apply_poison_policy`
        is the one implementation — the two topologies cannot drift)."""
        from petastorm_tpu.service.service_pool import apply_poison_policy
        apply_poison_policy(self, info, "the daemon's /health")

    def _wedge_error(self, waited, inflight):
        """The daemon client's wedge diagnosis — carrying the last
        daemon status this client saw."""
        return ServiceWedgedError(
            'Daemon-backed service read made no progress for %.1fs with '
            '%d item(s) outstanding (deadline PETASTORM_TPU_SERVICE_READ'
            '_DEADLINE_S=%.1fs). Last daemon status: %r'
            % (waited, inflight, self._read_deadline_s, self._status),
            fleet=dict(self._status))

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('Must call stop() before join()')
        if self._joined:
            return
        self._joined = True
        if self._obs_mount is not None:
            self._obs_mount.close()
        if self._net_thread is not None:
            self._net_thread.join(_JOIN_TIMEOUT_S)

    @property
    def diagnostics(self):
        with self._lock:
            ventilated = self._ventilated_items
            processed = self._processed_items
        status = dict(self._status)
        return {
            'items_ventilated': ventilated,
            'items_processed': processed,
            'items_inflight': ventilated - processed,
            'output_queue_size': self._results_queue.qsize(),
            'job_id': self._job_id,
            'daemon_endpoint': self._endpoint,
            'daemon_status': status,
            'reregistrations': self._reregistrations,
            'workers_alive': status.get('workers_alive', 0),
            'workers_registered': status.get('workers_registered', 0),
            'items_pending': status.get('pending', 0),
        }

    def client_health(self):
        return self.diagnostics

    @property
    def results_qsize(self):
        return self._results_queue.qsize()

    # -- network loop (owns the DEALER socket) -------------------------------

    def _net_loop(self):
        import zmq
        try:
            while not self._stop_event.is_set():
                context = zmq.Context()
                sock = context.socket(zmq.DEALER)
                sock.setsockopt(zmq.LINGER, 500)
                sock.connect(self._endpoint)
                try:
                    if not self._register_job(sock):
                        return
                    self._serve_job(sock)
                finally:
                    sock.close(linger=500)
                    context.term()
        except Exception as e:  # noqa: BLE001 - surfaced to the consumer
            logger.exception('Daemon-client network loop died')
            if self._error is None:
                self._error = e
        finally:
            self._registered.set()  # unblock a start() still waiting

    def _register_job(self, sock):
        """REGISTER_JOB with resend + BUSY backoff until JOB_OK (True) or
        the connect deadline / stop (False, with ``self._error`` set on
        timeout)."""
        import zmq
        params = {'key': self._client_key, 'name': self._name,
                  'credit': self._results_queue_size}
        if self._lease_s:
            params['lease_s'] = self._lease_s
        if self._weight:
            params['weight'] = self._weight
        if self._priority:
            params['priority'] = self._priority
        if self._fingerprint:
            params['fingerprint'] = self._fingerprint
        deadline = time.monotonic() + self._connect_timeout_s
        busy_backoff = _BUSY_BACKOFF_BASE_S
        next_send = 0.0
        while not self._stop_event.is_set():
            now = time.monotonic()
            if now > deadline:
                if self._error is None:
                    self._error = RuntimeError(
                        'Service daemon at %s did not admit job %r '
                        'within %.1fs' % (self._endpoint, self._name,
                                          self._connect_timeout_s))
                return False
            if now >= next_send:
                sock.send_multipart([proto.MSG_REGISTER_JOB,
                                     self._spec_payload,
                                     proto.dump_json_params(params)])
                next_send = now + _REGISTER_RESEND_S
            if not sock.poll(_NET_POLL_MS):
                continue
            try:
                frames = sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                continue
            if frames[0] == proto.MSG_JOB_OK:
                self._job_id = int(frames[1])
                self._daemon_token = frames[2] if len(frames) > 2 else None
                identity = (self._daemon_token, self._job_id)
                if identity != self._job_identity:
                    # a genuinely FRESH daemon-side job (its markers_sent
                    # starts at 0): re-base the ack clock so heartbeats
                    # report markers consumed against THIS job. The
                    # same-(token, id) case is the daemon deduping our
                    # key after a socket blip — the job kept its
                    # counters, so the base must keep too (re-basing
                    # there would under-report acks and wedge the gate).
                    # Markers still buffered toward the consumer
                    # (delivery deque + bounded queue) belong to the
                    # OLD job: they join the base, or their eventual
                    # consumption would count as acks against a job
                    # that never sent them and loosen its credit gate.
                    self._job_identity = identity
                    in_delivery = sum(1 for e in self._delivery
                                      if e[0] == 'marker')
                    with self._results_queue.mutex:
                        in_queue = sum(1 for e in self._results_queue.queue
                                       if e[0] == 'marker')
                    with self._lock:
                        self._acked_base = (self._acked + in_delivery
                                            + in_queue)
                self._registered.set()
                logger.info('Registered job %d (%s) with daemon %s',
                            self._job_id, self._name, self._endpoint)
                return True
            if frames[0] == proto.MSG_BUSY:
                info = proto.load_json_params(frames[1]
                                              if len(frames) > 1 else b'')
                logger.warning('Daemon busy (%s); retrying in %.2fs',
                               info.get('reason', '?'), busy_backoff)
                # back off instead of erroring: BUSY is retryable by
                # contract (drain / admission control)
                next_send = now + busy_backoff
                busy_backoff = min(busy_backoff * 2, _BUSY_BACKOFF_CAP_S)
            # other frames: stale RESULT traffic from a previous
            # incarnation of this socket — meaningless here

    def _resubmit_outstanding(self, sock):
        """After (re-)registration: re-send every item our accounting
        says was never markered. Late duplicate deliveries (the old
        daemon's copy racing the new submission) are dropped by the
        unknown-cid check in :meth:`_serve_job`."""
        with self._lock:
            pending = list(self._outstanding.items())
            self._submit_queue.clear()
        for cid, payload in pending:
            sock.send_multipart([proto.MSG_SUBMIT, b'%d' % self._job_id,
                                 b'%d' % cid, payload])
        if pending:
            logger.info('Re-submitted %d outstanding item(s) to job %d',
                        len(pending), self._job_id)

    def _serve_job(self, sock):
        """One job session: pump submits, heartbeats and results until
        the daemon is lost (→ return to re-register) or we stop."""
        import zmq
        self._resubmit_outstanding(sock)
        partial = {}          # cid -> [delivery entries]
        delivery = self._delivery
        last_hb_sent = 0.0
        last_ack = time.monotonic()
        while not self._stop_event.is_set():
            now = time.monotonic()
            if now - last_hb_sent >= self._heartbeat_interval_s:
                last_hb_sent = now
                with self._lock:
                    acked = max(0, self._acked - self._acked_base)
                sock.send_multipart([proto.MSG_CLIENT_HB,
                                     b'%d' % self._job_id, b'%d' % acked])
            # drain freshly-ventilated items
            while True:
                with self._lock:
                    if not self._submit_queue:
                        break
                    cid = self._submit_queue.popleft()
                    payload = self._outstanding.get(cid)
                if payload is not None:
                    sock.send_multipart([proto.MSG_SUBMIT,
                                         b'%d' % self._job_id,
                                         b'%d' % cid, payload])
            # feed buffered complete items into the bounded queue
            # (non-blocking: this thread must keep heartbeating through
            # a consumer stall; the daemon's credit gate bounds what can
            # pile up here)
            while delivery:
                try:
                    self._results_queue.put_nowait(delivery[0])
                except queue.Full:
                    break
                delivery.popleft()
            if sock.poll(_NET_POLL_MS):
                while True:
                    try:
                        frames = sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    verdict = self._handle_frames(frames, partial,
                                                  delivery)
                    if verdict == 'reregister':
                        self._reregistrations += 1
                        return
                    if verdict == 'ack':
                        last_ack = time.monotonic()
            if time.monotonic() - last_ack > self._ack_timeout_s:
                logger.warning('No daemon heartbeat ack for %.1fs; '
                               're-registering job', self._ack_timeout_s)
                self._reregistrations += 1
                return
        # clean goodbye so the daemon reclaims the job NOW instead of at
        # lease expiry
        try:
            if self._job_id is not None:
                sock.send_multipart([proto.MSG_JOB_GONE,
                                     b'%d' % self._job_id])
        except Exception:  # noqa: BLE001 - daemon may be gone
            count_swallowed('daemon-client-goodbye')

    def _handle_frames(self, frames, partial, delivery):
        """One inbound message; returns 'reregister', 'ack' or None."""
        msg = frames[0]
        if msg == proto.MSG_RESULT:
            kind = frames[1]
            try:
                cid = int(frames[2])
            except ValueError:
                return None
            with self._lock:
                known = cid in self._outstanding
            if not known:
                # late duplicate from a pre-restart copy of a
                # re-submitted item (its first delivery already popped
                # the id) — dropping it is what keeps re-submission
                # duplicate-free
                logger.debug('Dropping duplicate/unknown result for '
                             'item %d', cid)
                return None
            if kind == b'result':
                partial.setdefault(cid, []).append(('result', frames[3]))
            elif kind == b'error':
                partial.setdefault(cid, []).append(
                    ('error', proto.load_exception(frames[3])))
            elif kind == b'poisoned':
                partial.setdefault(cid, []).append(
                    ('poisoned', proto.load_poisoned_info(frames[3])))
            elif kind == b'marker':
                # the item is COMPLETE: release its buffered entries +
                # the marker atomically — a daemon lost mid-item leaves
                # nothing half-delivered
                entries = partial.pop(cid, [])
                with self._lock:
                    self._outstanding.pop(cid, None)
                delivery.extend(entries)
                delivery.append(('marker', cid))
            return None
        if msg == proto.MSG_CLIENT_HB_ACK:
            token = frames[1] if len(frames) > 1 else None
            self._status = proto.load_json_params(frames[2]
                                                  if len(frames) > 2
                                                  else b'')
            if token and self._daemon_token and token != self._daemon_token:
                # a NEW daemon incarnation answered on this endpoint:
                # our job id lives in a dead registry — re-register
                logger.warning('Daemon incarnation changed; '
                               're-registering job')
                return 'reregister'
            return 'ack'
        if msg == proto.MSG_JOB_EXPIRED:
            logger.warning('Daemon reports job expired/unknown; '
                           're-registering')
            return 'reregister'
        if msg == proto.MSG_BUSY:
            return None  # stale refusal from a raced registration
        return None
