"""Worker server: a standalone decode host process for the service pool.

Runs any :class:`~petastorm_tpu.workers.worker_base.WorkerBase` against work
items streamed from a dispatcher over ``tcp://``:

    python -m petastorm_tpu.service.worker_server \\
        --endpoint tcp://10.0.0.5:7777 --worker-id 0

Design points:

* **Registration with retry/backoff**: REGISTER is re-sent on an
  exponential backoff until the dispatcher answers with the job SPEC, so
  worker servers can start before the dispatcher exists (ZMQ reconnects
  transparently underneath).
* **Network loop owns the socket**: the main thread polls, heartbeats, and
  ships buffered results; a single executor thread runs ``process()``.
  Heartbeats therefore keep flowing during a long decode — a busy worker
  never reads as dead.
* **Atomic item results**: ``publish_func`` appends to a per-item buffer;
  the whole buffer ships in ONE ``DONE`` message after ``process()``
  returns. A worker killed mid-item has delivered nothing for that item, so
  the dispatcher's re-ventilation re-runs it without duplicating rows.
* **Persistence**: after a job ends (STOP, or the dispatcher vanishes —
  no HEARTBEAT_ACK for ``ack_timeout``) the server shuts the worker down
  and goes back to registering, tf.data-service style, so one fleet of
  worker servers outlives any number of reader lifetimes. ``--once`` (or a
  dead ``--parent-pid``) exits instead.
* **Dispatcher-restart survival**: the SPEC carries the dispatcher
  incarnation's random token and every HEARTBEAT_ACK echoes it. When the
  acks suddenly carry a DIFFERENT token, a new dispatcher has taken the
  endpoint (client restart) — this server's job spec and item-id space
  are dead, so it abandons the job immediately and re-registers (fresh
  socket, fresh identity, registration backoff) instead of decoding the
  new dispatcher's items against the old job's spec or waiting out the
  full ack timeout. A vanished-and-silent dispatcher is still caught by
  the ack timeout; both paths converge on re-registration, so a standing
  fleet survives any number of dispatcher restarts
  (docs/service.md, "Failure semantics").
"""

import argparse
import logging
import os
import queue
import signal
import sys
import threading
import time
import uuid

from petastorm_tpu import faults
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.telemetry import (
    count_swallowed, knobs, obs_server, timeseries, tracing,
)

logger = logging.getLogger(__name__)

_POLL_INTERVAL_MS = 50
_REGISTER_BACKOFF_MAX_S = 2.0
_EXECUTOR_JOIN_TIMEOUT_S = 5.0


def _parent_died(parent_pid):
    if parent_pid is None:
        return False
    import psutil

    return not psutil.pid_exists(parent_pid)


def _register(sock, parent_pid, register_timeout_s, term_event=None,
              cache_fps=(), peer_server=None):
    """REGISTER with exponential backoff until the SPEC arrives.

    Returns ``(spec payload, dispatcher token)`` — token None from a
    pre-token dispatcher build — or ``(None, None)`` when the server
    should exit (orphaned, SIGTERMed, or the registration window
    closed).
    """
    import json

    backoff_s = 0.1
    deadline = (None if register_timeout_s is None
                else time.monotonic() + register_timeout_s)
    last_parent_check = 0.0
    # cache-fingerprint advert (JSON list, additive frame like the pid):
    # the dispatcher must see which decoded caches this HOST already
    # holds BEFORE it binds us to a job — placement happens at
    # registration time (docs/service.md, "High availability")
    try:
        advert = json.dumps(list(cache_fps)).encode() if cache_fps else b''
    except Exception:  # noqa: BLE001 - placement is advisory
        count_swallowed('worker-cache-advert')
        advert = b''
    # fleet cache tier: the FULL set of decoded entries this host holds
    # rides REGISTER (one more additive frame), so the dispatcher's peer
    # directory is complete before the first WORK lands — a restarted
    # worker's startup scan re-advertises everything it kept on disk
    peer_advert = b''
    if peer_server is not None:
        try:
            peer_advert = proto.dump_json_params(peer_server.full_advert())
        except Exception:  # noqa: BLE001 - adverts are advisory
            count_swallowed('worker-peer-advert')
            peer_advert = b''
    frames_out = [proto.MSG_REGISTER, b'%d' % os.getpid()]
    if peer_advert:
        # frame order is positional: the placement advert must occupy
        # frame 3 (possibly empty) so the peer advert lands at frame 4
        frames_out.extend([advert, peer_advert])
    elif advert:
        frames_out.append(advert)
    while True:
        # the trailing pid frame is ADVISORY and additive (an old
        # dispatcher ignores extra REGISTER frames): it lets a standing
        # daemon's supervisor tell a worker that is merely between jobs
        # (re-registering, not yet heartbeating) from a wedged one
        sock.send_multipart(frames_out)
        poll_deadline = time.monotonic() + backoff_s
        while time.monotonic() < poll_deadline:
            if term_event is not None and term_event.is_set():
                logger.info('SIGTERM during registration; exiting')
                return None, None
            if sock.poll(_POLL_INTERVAL_MS):
                frames = sock.recv_multipart()
                if frames[0] == proto.MSG_SPEC:
                    return frames[1], (frames[2] if len(frames) > 2
                                       else None)
                # STOP/stray frames during registration are meaningless
                continue
            now = time.monotonic()
            if now - last_parent_check > 1.0:
                last_parent_check = now
                if _parent_died(parent_pid):
                    logger.info('Parent %s died; exiting', parent_pid)
                    return None, None
            if deadline is not None and now > deadline:
                logger.error('No dispatcher answered REGISTER within %.1fs',
                             register_timeout_s)
                return None, None
        backoff_s = min(backoff_s * 2, _REGISTER_BACKOFF_MAX_S)


def _reroot_decoded_cache(worker_args):
    """Point a job's materialized decoded-row-group cache at THIS host's
    shared directory (``--cache-dir`` / ``PETASTORM_TPU_DECODED_CACHE_DIR``).

    The cache object travels inside the job spec with whatever directory
    the *client* configured — meaningless on a remote decode host. With
    the override set, every job a standing worker-server fleet serves
    lands on one local tier, so N jobs over one dataset decode each
    row-group once per HOST, not once per job (the tf.data-service
    "decode once, serve many" shape). Without it, the spec's directory is
    kept (localhost fleets share the client's directory naturally)."""
    cache_dir = knobs.get_str('PETASTORM_TPU_DECODED_CACHE_DIR')
    if not cache_dir or not isinstance(worker_args, dict):
        return
    from petastorm_tpu.materialized_cache import MaterializedRowGroupCache
    cache = worker_args.get('cache')
    if isinstance(cache, MaterializedRowGroupCache) \
            and cache.path != cache_dir:
        logger.info('Rerooting decoded cache %s -> %s', cache.path,
                    cache_dir)
        cache.reroot(cache_dir)


def _run_job(sock, spec_payload, worker_id, heartbeat_interval_s,
             ack_timeout_s, parent_pid, status=None, token=None,
             term_event=None, known_fps=None, endpoint=None,
             peer_server=None, peer_live=None):
    """One job lifetime: build the worker, stream items until STOP, the
    dispatcher vanishes (ack timeout), or a DIFFERENT dispatcher
    incarnation takes the endpoint (heartbeat-ack token mismatch).
    Returns True if the server should serve again."""
    worker_class, worker_args, serializer = proto.load_job_spec(spec_payload)
    _reroot_decoded_cache(worker_args)
    # cache-aware placement: this job's decode fingerprint becomes part
    # of the host's advert set (heartbeat summaries now; a marker file
    # so future server processes advertise it from their first REGISTER)
    from petastorm_tpu.service import placement
    fingerprint = placement.placement_fingerprint(worker_args)
    if fingerprint:
        known_fps = known_fps if known_fps is not None else set()
        known_fps.add(fingerprint)
        placement.note_fingerprint(
            knobs.get_str('PETASTORM_TPU_DECODED_CACHE_DIR'), fingerprint)
    advertised = sorted(known_fps)[:placement.MAX_ADVERTISED] \
        if known_fps else None
    # per-heartbeat observability summary (docs/telemetry.md fleet view):
    # thread-free rates since the previous heartbeat, piggybacked on the
    # HEARTBEAT frame so the dispatcher's endpoint can break the fleet
    # down per worker
    summarizer = timeseries.HeartbeatSummarizer(worker_id)
    status = status if status is not None else {}

    buffer = []
    worker = worker_class(worker_id, buffer.append, worker_args)
    worker.initialize()

    # fleet cache tier (docs/service.md, "Fleet cache tier"): serve this
    # job's decoded cache to peers and fetch what peers already decoded.
    # All best-effort — a failure here costs wire-priced hits, never the
    # job.
    peer_client = None
    peer_cached = None
    if endpoint and not knobs.is_disabled('PETASTORM_TPU_PEER_CACHE'):
        from petastorm_tpu.materialized_cache import (
            MaterializedRowGroupCache,
        )
        cache = worker_args.get('cache') \
            if isinstance(worker_args, dict) else None
        if isinstance(cache, MaterializedRowGroupCache) \
                and not cache.degraded:
            from petastorm_tpu.service import peer_cache
            try:
                if peer_server is None:
                    # no eager --cache-dir server: serve the spec's own
                    # directory for this job's lifetime
                    peer_server = peer_cache.get_server(cache.path)
                peer_client = peer_cache.PeerCacheClient(
                    endpoint, self_endpoint=peer_server.endpoint)
                cache.attach_peer_client(peer_client)
                peer_cached = cache
                if peer_live is not None:
                    peer_live['client'] = peer_client
            except Exception:  # noqa: BLE001 - the tier is advisory
                count_swallowed('peer-client-wire')
                peer_client = None

    work_queue = queue.Queue()
    out_queue = queue.Queue()
    stop_flag = threading.Event()

    def executor():
        while not stop_flag.is_set():
            try:
                item_id, payload = work_queue.get(timeout=0.05)
            except queue.Empty:
                continue
            del buffer[:]
            try:
                args, kwargs = proto.load_work_item(payload)
                # traced items carry their context inside the WORK frame's
                # kwargs; activate it so this server's stage spans + the
                # attempt event (worker id + pid track) join the item's
                # timeline — they ship back inside the DONE's delta frame
                ctx = kwargs.pop(tracing.TRACE_CTX_KEY, None)
                with tracing.attempt(ctx, 'service-%d' % worker_id):
                    worker.process(*args, **kwargs)
                # metrics delta rides the DONE (io/decode/transform spans,
                # cache counters accrued while processing this item); the
                # dispatcher merges it into the client-side registry, so
                # the whole fleet aggregates without a separate channel
                frames = ([proto.MSG_DONE, proto.pack_item_id(item_id),
                           proto.dump_metrics_delta()]
                          + [serializer.serialize(v) for v in buffer])
                status['items_done'] = status.get('items_done', 0) + 1
            except Exception as e:  # noqa: BLE001 - forwarded to consumer
                logger.debug('Worker %d forwarding exception', worker_id,
                             exc_info=True)
                frames = [proto.MSG_ERROR, proto.pack_item_id(item_id),
                          proto.dump_exception(e),
                          proto.dump_metrics_delta()]
                # errored items are NOT done: the fleet view's per-worker
                # breakdown must show a sick worker's completions stalling
                status['items_errored'] = status.get('items_errored',
                                                     0) + 1
            out_queue.put(frames)

    executor_thread = threading.Thread(target=executor, daemon=True)
    executor_thread.start()

    sock.send_multipart([proto.MSG_READY])
    now = time.monotonic()
    last_heartbeat_sent = 0.0
    last_ack = now
    last_parent_check = now
    serve_again = True
    try:
        while True:
            now = time.monotonic()
            if now - last_heartbeat_sent >= heartbeat_interval_s:
                last_heartbeat_sent = now
                if faults.ARMED and faults.fault_hit(
                        'zmq.heartbeat', key=worker_id) == 'drop':
                    pass  # injected: heartbeat lost; dispatcher will lapse
                else:
                    try:
                        summary = summarizer.summary(
                            obs_port=obs_server.server_port())
                        summary['items_done'] = status.get('items_done', 0)
                        if advertised:
                            summary['cache_fp'] = advertised
                        if peer_server is not None:
                            # bounded add/evict/touch delta since the
                            # last heartbeat (carry-over keeps any one
                            # frame small)
                            delta = peer_server.advert_delta()
                            if delta:
                                summary['peer'] = delta
                        frame = proto.dump_obs_summary(summary)
                    except Exception:  # noqa: BLE001 - advisory telemetry
                        count_swallowed('worker-obs-summary')
                        frame = b''
                    if token is not None:
                        # the token rides its OWN frame, never inside the
                        # advisory summary: the dispatcher cross-checks
                        # it to spot foreign-incarnation workers, and
                        # that correctness signal must survive the
                        # summary path degrading to b''
                        sock.send_multipart([proto.MSG_HEARTBEAT, frame,
                                             token])
                    elif frame:
                        sock.send_multipart([proto.MSG_HEARTBEAT, frame])
                    else:
                        sock.send_multipart([proto.MSG_HEARTBEAT])
            while True:
                try:
                    result_frames = out_queue.get_nowait()
                except queue.Empty:
                    break
                if faults.ARMED and faults.fault_hit(
                        'zmq.done', key=result_frames[1]) == 'drop':
                    continue  # injected: completion lost in flight
                sock.send_multipart(result_frames)
            if sock.poll(_POLL_INTERVAL_MS):
                frames = sock.recv_multipart()
                msg = frames[0]
                if msg == proto.MSG_WORK:
                    work_queue.put((proto.unpack_item_id(frames[1]),
                                    frames[2]))
                    if peer_client is not None and len(frames) > 3 \
                            and frames[3]:
                        # piggybacked fleet-directory delta: holders of
                        # recently advertised entries, no DIRGET needed
                        peer_client.update_directory(
                            proto.load_json_params(frames[3]))
                elif msg == proto.MSG_STOP:
                    logger.info('Dispatcher sent STOP; job over')
                    break
                elif msg == proto.MSG_HEARTBEAT_ACK:
                    last_ack = now
                    if peer_server is not None and len(frames) > 2 \
                            and frames[2]:
                        # advisory global-eviction hints (additive
                        # trailing frame); the server re-checks local
                        # atime before dropping anything
                        try:
                            hints = proto.load_json_params(
                                frames[2]).get('evict')
                            if hints:
                                peer_server.apply_evict_hints(hints)
                        except Exception:  # noqa: BLE001 - advisory
                            count_swallowed('peer-evict-hint')
                    if token is not None and len(frames) > 1 \
                            and frames[1] != token:
                        # a NEW dispatcher incarnation answered on this
                        # endpoint: our job spec and item-id space are
                        # dead — re-register for the new job instead of
                        # decoding against the old spec or waiting out
                        # the full ack timeout
                        logger.warning(
                            'Dispatcher incarnation changed (token %r -> '
                            '%r); abandoning job to re-register',
                            token, frames[1])
                        break
                elif msg == proto.MSG_SPEC:
                    pass  # duplicate reply to a re-sent REGISTER
            if now - last_ack > ack_timeout_s:
                logger.warning('No dispatcher heartbeat ack for %.1fs; '
                               'abandoning job', ack_timeout_s)
                break
            if now - last_parent_check > 1.0:
                last_parent_check = now
                if _parent_died(parent_pid):
                    logger.info('Parent %s died; exiting', parent_pid)
                    serve_again = False
                    break
            if term_event is not None and term_event.is_set():
                # graceful release (the supervisor's scale-down path):
                # stop taking work, say BYE, exit — never a heartbeat
                # lapse, so nothing is re-ventilated for a scaling
                # decision
                logger.info('SIGTERM: finishing job and exiting')
                serve_again = False
                break
    finally:
        stop_flag.set()
        executor_thread.join(_EXECUTOR_JOIN_TIMEOUT_S)
        if peer_client is not None:
            # after the join: a live fetch must not race the close
            if peer_cached is not None:
                peer_cached.attach_peer_client(None)
            if peer_live is not None:
                peer_live.pop('client', None)
            peer_client.close()
        if executor_thread.is_alive():
            # A decode is wedged past the join budget: shutting the worker
            # down under the live process() call would close its resources
            # mid-use, and re-registering would stack a second worker on a
            # core the first still burns. Exit the process instead and let
            # the OS reclaim everything.
            logger.warning('Decode still running %.0fs after job end; '
                           'exiting instead of re-registering',
                           _EXECUTOR_JOIN_TIMEOUT_S)
            serve_again = False
        else:
            try:
                worker.shutdown()
            except Exception:  # noqa: BLE001 - best-effort shutdown
                count_swallowed('worker-shutdown')
    return serve_again


def serve(endpoint, worker_id=0, heartbeat_interval_s=1.0,
          ack_timeout_s=None, parent_pid=None, once=False,
          register_timeout_s=None):
    """Serve decode jobs from the dispatcher at ``endpoint`` until orphaned
    (``parent_pid`` died), the registration window closes, or — with
    ``once`` — the first job ends."""
    import zmq

    if ack_timeout_s is None:
        ack_timeout_s = max(10 * heartbeat_interval_s, 10.0)
    # graceful SIGTERM (the supervisor's release path, and any process
    # manager's polite stop): finish the in-flight item, send BYE, exit
    # — instead of the default instant death that reads as a lapse and
    # re-ventilates work. Signal handlers only install on the main
    # thread; an embedded serve() (tests) just skips the grace.
    term_event = threading.Event()
    try:
        signal.signal(signal.SIGTERM,
                      lambda signum, frame: term_event.set())
    except ValueError:
        pass
    # live observability plane: a worker server exposes its OWN /metrics
    # /report /health /trace when PETASTORM_TPU_OBS_PORT is set (use 0 —
    # ephemeral — for multi-worker hosts; the bound port rides every
    # heartbeat summary, so the dispatcher's fleet view says where each
    # worker's endpoint lives). Unarmed: a shared no-op handle.
    status = {'worker_id': worker_id, 'state': 'registering',
              'jobs_served': 0, 'items_done': 0, 'endpoint': endpoint}
    peer_live = {}

    def _health():
        # per-host readahead visibility in fleet mode: each decode host
        # runs its own manager (the plan rides the job spec), so the
        # hit/miss/pool numbers belong on ITS /health, not the client's
        from petastorm_tpu import readahead
        from petastorm_tpu.service import peer_cache
        out = dict(status)
        out['readahead'] = readahead.health_snapshot()
        # fleet cache tier holder view: what this host serves to peers,
        # and (while a job runs) the fetch client's hit/miss/budget
        snap = peer_cache.server_snapshot()
        if snap is not None:
            out['peer_cache'] = snap
        client = peer_live.get('client')
        if client is not None:
            out.setdefault('peer_cache', {})['client'] = client.stats()
        return out

    obs_mount = obs_server.mount('worker-server', health=_health)
    # fingerprints of decoded caches this host holds, advertised on
    # REGISTER and heartbeats: warm markers on disk plus every job this
    # process served (cache-aware placement, docs/service.md)
    from petastorm_tpu.service import placement
    known_fps = set(placement.advertised_fingerprints(
        knobs.get_str('PETASTORM_TPU_DECODED_CACHE_DIR')))
    # fleet cache tier: with a host-local cache dir configured, start the
    # peer serve socket BEFORE registering — the startup scan makes the
    # REGISTER advert carry everything this host kept across restarts,
    # so the directory is complete before the first WORK is assigned
    from petastorm_tpu.service import peer_cache
    peer_server = None
    if peer_cache.peer_cache_enabled():
        cache_dir = knobs.get_str('PETASTORM_TPU_DECODED_CACHE_DIR')
        if cache_dir:
            try:
                peer_server = peer_cache.get_server(cache_dir)
            except Exception:  # noqa: BLE001 - the tier is advisory
                count_swallowed('peer-server-start')
    try:
        while True:
            # Fresh socket (and identity) per job lifetime: a stale
            # DEALER can hold buffered frames from the previous
            # dispatcher incarnation.
            context = zmq.Context()
            sock = context.socket(zmq.DEALER)
            sock.setsockopt(zmq.IDENTITY,
                            ('worker-%d-%s'
                             % (worker_id, uuid.uuid4().hex[:8])).encode())
            sock.setsockopt(zmq.LINGER, 500)
            sock.connect(endpoint)
            try:
                status['state'] = 'registering'
                spec_payload, token = _register(
                    sock, parent_pid, register_timeout_s,
                    term_event=term_event,
                    cache_fps=sorted(known_fps)[:placement.MAX_ADVERTISED],
                    peer_server=peer_server)
                if spec_payload is None:
                    return
                status['state'] = 'serving'
                serve_again = _run_job(sock, spec_payload, worker_id,
                                       heartbeat_interval_s, ack_timeout_s,
                                       parent_pid, status=status,
                                       token=token, term_event=term_event,
                                       known_fps=known_fps,
                                       endpoint=endpoint,
                                       peer_server=peer_server,
                                       peer_live=peer_live)
                status['jobs_served'] += 1
                try:
                    sock.send_multipart([proto.MSG_BYE])
                except Exception:  # noqa: BLE001 - dispatcher may be gone
                    count_swallowed('worker-bye-send')
            finally:
                sock.close(linger=500)
                context.term()
            if once or not serve_again:
                return
    finally:
        peer_cache.close_server()
        obs_mount.close()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='petastorm_tpu decode worker server')
    parser.add_argument('--endpoint', required=True,
                        help='dispatcher tcp:// endpoint to register with')
    parser.add_argument('--worker-id', type=int, default=0)
    parser.add_argument('--heartbeat-interval', type=float, default=1.0,
                        help='seconds between heartbeats; the dispatcher '
                             'declares a worker dead after its liveness '
                             'timeout without one')
    parser.add_argument('--ack-timeout', type=float, default=None,
                        help='exit the current job after this long without '
                             'a dispatcher heartbeat ack '
                             '(default max(10*interval, 10s))')
    parser.add_argument('--parent-pid', type=int, default=None,
                        help='exit when this process dies (for locally '
                             'spawned fleets)')
    parser.add_argument('--once', action='store_true',
                        help='exit after the first job instead of '
                             're-registering')
    parser.add_argument('--register-timeout', type=float, default=None,
                        help='give up when no dispatcher answers within '
                             'this many seconds (default: retry forever)')
    parser.add_argument('--cache-dir', default=None,
                        help='host-local directory for the materialized '
                             'decoded-row-group cache: every job this '
                             'server (re-)registers for shares it, so N '
                             'jobs over one dataset decode each row-group '
                             'once per host (same as setting '
                             'PETASTORM_TPU_DECODED_CACHE_DIR)')
    parser.add_argument('--obs-port', type=int, default=None,
                        help='expose this server\'s live observability '
                             'endpoint (/metrics /report /health /trace) '
                             'on this port; 0 picks a free one (same as '
                             'setting PETASTORM_TPU_OBS_PORT; the bound '
                             'port rides the heartbeat summaries into '
                             "the dispatcher's fleet view)")
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)
    if args.cache_dir:
        knobs.set_env('PETASTORM_TPU_DECODED_CACHE_DIR', args.cache_dir)
    if args.obs_port is not None:
        knobs.set_env('PETASTORM_TPU_OBS_PORT', str(args.obs_port))
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format='%(asctime)s worker-server[%(process)d] %(message)s')
    # Decode workers must never grab the TPU chip a trainer owns — hard
    # override, exactly like exec_in_new_process: trainer hosts commonly
    # export JAX_PLATFORMS=tpu and the inherited value must not win.
    os.environ['JAX_PLATFORMS'] = 'cpu'
    serve(args.endpoint, worker_id=args.worker_id,
          heartbeat_interval_s=args.heartbeat_interval,
          ack_timeout_s=args.ack_timeout, parent_pid=args.parent_pid,
          once=args.once, register_timeout_s=args.register_timeout)
    return 0


if __name__ == '__main__':
    sys.exit(main())
