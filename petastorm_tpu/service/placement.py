"""Cache-aware placement: decode fingerprints for the service plane.

The standing service schedules many jobs onto one worker fleet. Two
jobs reading the same dataset with the same decode pipeline produce the
SAME materialized row-group cache (docs/materialized_cache.md) — so the
second job should land on the host that already decoded it, not redo
the work cold on another. The currency of that decision is the decode
fingerprint (:func:`petastorm_tpu.materialized_cache.decode_fingerprint`):

* the **client** stamps its job registration with the fingerprint of
  the job's worker args (``DaemonClientPool._register_job``);
* each **worker server** advertises the fingerprints of the caches its
  host already holds — on REGISTER (a trailing JSON frame) and in every
  heartbeat obs summary (``cache_fp``), kept fresh via marker files in
  the decoded-cache directory;
* the **dispatcher** folds the adverts into a fleet cache directory and
  prefers fingerprint-matching workers when binding (``_bind_worker``),
  counting hits and misses in telemetry.

Both sides compute the fingerprint with :func:`placement_fingerprint`
below — one function, identical inputs, identical value — so a
placement hit is a real cache hit, not a naming coincidence. Jobs whose
worker args carry no schema (stub workers, non-reader jobs) can opt in
with an explicit ``placement_group`` string in ``worker_args``; it
bypasses the schema derivation entirely and is matched verbatim.

Everything here is advisory: a wrong or missing fingerprint costs warm
starts, never correctness — so every helper swallows its own failures
(:func:`petastorm_tpu.telemetry.count_swallowed`) and degrades to
"no fingerprint".
"""

import os

from petastorm_tpu.telemetry import count_swallowed

#: cap on fingerprints a worker advertises (REGISTER frame / heartbeat
#: summary) and on marker files scanned — adverts ride the hot
#: heartbeat path and a host rarely holds more than a handful of warm
#: datasets at once
MAX_ADVERTISED = 8

_MARKER_PREFIX = '.fp_'


def placement_fingerprint(worker_args):
    """The placement identity of a job, or None when it has none.

    An explicit ``placement_group`` string in ``worker_args`` wins
    unconditionally (the user-facing escape hatch, and how schema-less
    stub jobs participate); otherwise the fingerprint derives from the
    decode-relevant args exactly like the materialized cache's own
    layout key (``loaded_schema`` + ``transform_spec`` + ``ngram``).
    """
    if not isinstance(worker_args, dict):
        return None
    try:
        group = worker_args.get('placement_group')
        if group:
            return str(group)
        loaded_schema = worker_args.get('loaded_schema')
        if loaded_schema is None:
            return None
        from petastorm_tpu.materialized_cache import decode_fingerprint
        return decode_fingerprint(loaded_schema,
                                  worker_args.get('transform_spec'),
                                  ngram=worker_args.get('ngram'))
    except Exception:  # noqa: BLE001 - placement is advisory
        count_swallowed('placement-fingerprint')
        return None


def note_fingerprint(cache_dir, fingerprint):
    """Drop a marker file so FUTURE worker servers on this host advertise
    ``fingerprint`` from their first REGISTER (the in-process set covers
    the current server's lifetime; the marker survives it)."""
    if not cache_dir or not fingerprint:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        path = os.path.join(cache_dir, _MARKER_PREFIX + str(fingerprint))
        with open(path, 'a'):
            pass
    except Exception:  # noqa: BLE001 - placement is advisory
        count_swallowed('placement-marker')


def purge_stale_markers(cache_dir):
    """Remove every ``.fp_`` marker from a cache directory that holds no
    real entries — a re-rooted or cleaned-up cache must not keep
    advertising a fingerprint it no longer backs (the marker would steer
    placement at a cold host forever). Returns the number removed: 0
    when any real entry still exists (the markers are earned), or on any
    failure — advisory like everything here."""
    try:
        if not cache_dir or not os.path.isdir(cache_dir):
            return 0
        from petastorm_tpu.cache import is_tmp_entry
        markers = []
        for root, _, files in os.walk(cache_dir):
            for name in files:
                if name.startswith(_MARKER_PREFIX):
                    markers.append(os.path.join(root, name))
                elif not is_tmp_entry(name):
                    return 0  # a real entry: the markers are earned
        removed = 0
        for path in markers:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
        return removed
    except Exception:  # noqa: BLE001 - placement is advisory
        count_swallowed('placement-marker-purge')
        return 0


def advertised_fingerprints(cache_dir, extra=()):
    """The fingerprints a worker server should advertise: marker files
    under ``cache_dir`` plus the in-process ``extra`` set, sorted and
    capped at :data:`MAX_ADVERTISED`."""
    found = set(str(fp) for fp in extra if fp)
    try:
        if cache_dir and os.path.isdir(cache_dir):
            for name in os.listdir(cache_dir):
                if name.startswith(_MARKER_PREFIX):
                    found.add(name[len(_MARKER_PREFIX):])
    except Exception:  # noqa: BLE001 - placement is advisory
        count_swallowed('placement-scan')
    return sorted(found)[:MAX_ADVERTISED]
