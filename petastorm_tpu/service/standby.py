"""Warm-standby daemon: dispatcher failover for the standing service.

PR 13's daemonized dispatcher made the decode fleet outlive any reader,
but the daemon itself stayed a single point of failure: kill it and
every registered job waits for an operator. This module is the HA half
(docs/service.md, "High availability"):

    python -m petastorm_tpu.service --standby --endpoint tcp://...:7777

A :class:`StandbyDaemon` watches the PRIMARY daemon on the endpoint it
will inherit. It is one more DEALER peer on the primary's ROUTER socket
— it periodically pulls a registry snapshot (``SSYNC`` →
``SSTATE``: job specs, client keys, leases, delivery-credit and QoS
params, the item-id watermark — see ``Dispatcher.standby_snapshot``)
and keeps the latest good copy plus a replication-lag clock. When the
primary goes silent past the lapse window, the standby **promotes**:
it builds a full :class:`~petastorm_tpu.service.daemon.ServiceDaemon`
seeded with the snapshot and binds the SAME endpoint the primary held.

What makes the takeover correct rather than merely fast:

* the promoted dispatcher mints a FRESH incarnation token, so every
  worker and every :class:`DaemonClientPool` client discovers the
  change through the existing re-registration machinery (PR 11/13) —
  clients re-bind to their seeded job by idempotency key and re-submit
  exactly the items their own accounting says were never markered;
* in-flight items are deliberately NOT replicated — they re-ventilate
  through that client re-submission, and the seeded item-id watermark
  keeps the new incarnation's id space collision-free so stale frames
  dedup away (the ``_item_owners`` gate);
* binding retries through the dead primary's lingering port, so a
  promotion that raced the kernel's socket teardown converges instead
  of failing; while the PRIMARY IS STILL ALIVE the bind simply keeps
  failing and the standby falls back to watching — a false-positive
  lapse (network blip) can never yield two live heads on one endpoint.

Degradation: with the replication stream severed (the
``zmq.replicate`` drop faultpoint, or a primary too old to speak
SSYNC) the snapshot stays empty and promotion is **cold** — no seeded
registry, clients re-register from scratch via the JOB_EXPIRED path —
slower to re-admit, still multiset-exact (``tests/test_failover.py``).
The ``service.promote`` faultpoint injects promotion failures, which
retry with backoff inside the promote window.
"""

import logging
import threading
import time

from petastorm_tpu import faults
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.telemetry import (
    count_swallowed, get_registry, knobs, metrics_disabled, tracing,
)
from petastorm_tpu.telemetry.timeseries import record_anomaly

logger = logging.getLogger(__name__)

_NET_POLL_MS = 50
_PROMOTE_BACKOFF_S = 0.2

#: HA metric names (docs/telemetry.md): promotions this process
#: performed, and how stale the standby's replicated snapshot is
SERVICE_FAILOVERS = 'petastorm_tpu_service_failovers_total'
SERVICE_REPLICATION_LAG = 'petastorm_tpu_service_replication_lag_seconds'


class StandbyDaemon:
    """Warm standby for a :class:`ServiceDaemon` on ``endpoint``.

    :param endpoint: the PRIMARY's ``tcp://host:port`` — the address
        this standby mirrors and, on promotion, takes over. A concrete
        port is required (port 0 would promote somewhere the workers
        and clients never look).
    :param sync_interval_s: seconds between replication pulls (default:
        the ``PETASTORM_TPU_SERVICE_STANDBY_SYNC_S`` knob, 1s).
    :param lapse_s: primary silence after which promotion begins
        (default: the ``PETASTORM_TPU_SERVICE_STANDBY_LAPSE_S`` knob,
        5s).
    :param promote_timeout_s: per-promotion bind window; an expired
        window (the primary still holds the endpoint — false-positive
        lapse) returns the standby to watching.

    Remaining keyword arguments are forwarded to the promoted
    :class:`ServiceDaemon` (fleet sizing, supervision, lease policy).
    """

    def __init__(self, endpoint, sync_interval_s=None, lapse_s=None,
                 promote_timeout_s=30.0, **daemon_kwargs):
        if endpoint.endswith(':0'):
            raise ValueError('A standby needs the primary\'s concrete '
                             'endpoint, not a random port: %r' % endpoint)
        self.endpoint = endpoint
        self._sync_interval_s = (
            sync_interval_s if sync_interval_s is not None
            else knobs.get_float('PETASTORM_TPU_SERVICE_STANDBY_SYNC_S',
                                 1.0, floor=0.05))
        self._lapse_s = (
            lapse_s if lapse_s is not None
            else knobs.get_float('PETASTORM_TPU_SERVICE_STANDBY_LAPSE_S',
                                 5.0, floor=0.1))
        self._promote_timeout_s = promote_timeout_s
        self._daemon_kwargs = daemon_kwargs
        #: 'standby' → 'promoting' → 'primary' (the /health role field)
        self.role = 'standby'
        #: the promoted ServiceDaemon once role == 'primary'
        self.daemon = None
        self._snapshot = None
        self._snapshot_at = None
        self._last_good = None
        self._syncs_ok = 0
        self._promotions = 0
        self._stop_event = threading.Event()
        self._promoted = threading.Event()
        self._thread = None
        self._obs_mount = None
        self._error = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            raise RuntimeError('StandbyDaemon already started')
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name='service-standby')
        self._thread.start()
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount('service-standby',
                                           health=self.health)
        logger.info('Standby watching %s (sync %.2fs, lapse %.2fs)',
                    self.endpoint, self._sync_interval_s, self._lapse_s)

    def wait_promoted(self, timeout):
        """Block until this standby became the primary (True) or the
        timeout passed (False)."""
        return self._promoted.wait(timeout)

    def health(self):
        """The standby's /health: HA role and replication freshness;
        once promoted, the full primary health document with the
        standby's failover history folded in."""
        now = time.monotonic()
        ha = {
            'role': self.role,
            'primary_endpoint': self.endpoint,
            'replication_lag_s': (round(now - self._last_good, 3)
                                  if self._last_good is not None else None),
            'snapshot_jobs': (len(self._snapshot.get('jobs', ()))
                              if self._snapshot else 0),
            'syncs_ok': self._syncs_ok,
            'promotions': self._promotions,
            'sync_interval_s': self._sync_interval_s,
            'lapse_s': self._lapse_s,
        }
        daemon = self.daemon
        if daemon is not None:
            doc = daemon.health()
            doc.update(ha)
            doc['role'] = self.role
            return doc
        return ha

    def stop(self):
        self._stop_event.set()
        if self._obs_mount is not None:
            self._obs_mount.close()
            self._obs_mount = None
        if self._thread is not None:
            self._thread.join(10.0)
            self._thread = None
        if self.daemon is not None:
            self.daemon.stop()

    def run_forever(self, install_signals=True, drain_poll_s=0.2):
        """CLI body: watch until promoted (or signalled), then serve as
        the primary until drained."""
        import signal
        if install_signals:
            handler = lambda signum, frame: self._stop_event.set()  # noqa: E731
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        self.start()
        try:
            while not self._stop_event.is_set():
                if self._error is not None:
                    raise self._error
                if self._promoted.wait(drain_poll_s):
                    # hand the main thread to the promoted daemon (its
                    # own drain-on-SIGTERM semantics take over)
                    self.daemon.run_forever(install_signals=install_signals,
                                            drain_poll_s=drain_poll_s)
                    return
        finally:
            self.stop()

    # -- the monitor thread --------------------------------------------------

    def _monitor(self):
        try:
            while not self._stop_event.is_set():
                verdict = self._sync_session()
                if verdict != 'promote':
                    return
                if self._promote():
                    return
                # promote window closed (endpoint still held — a
                # false-positive lapse): back to watching
                self.role = 'standby'
                logger.warning('Promotion window closed with %s still '
                               'bound; returning to standby', self.endpoint)
        except Exception as e:  # noqa: BLE001 - surfaced via run_forever
            logger.exception('Standby monitor died')
            self._error = e

    def _sync_session(self):
        """One replication session on a fresh DEALER socket: pull
        snapshots until the primary lapses ('promote') or we stop."""
        import zmq
        context = zmq.Context()
        sock = context.socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(self.endpoint)
        next_sync = 0.0
        # the lapse clock arms at session start: a primary that NEVER
        # answers (not yet started, or a pre-SSYNC build) is
        # indistinguishable from a dead one and promotion proceeds —
        # cold if no snapshot was ever replicated
        self._last_good = time.monotonic()
        try:
            while not self._stop_event.is_set():
                now = time.monotonic()
                if now - self._last_good > self._lapse_s:
                    return 'promote'
                if now >= next_sync:
                    sock.send_multipart([proto.MSG_STANDBY_SYNC])
                    next_sync = now + self._sync_interval_s
                if not metrics_disabled():
                    get_registry().gauge(SERVICE_REPLICATION_LAG).set(
                        now - self._last_good)
                if not sock.poll(_NET_POLL_MS):
                    continue
                while True:
                    try:
                        frames = sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    if frames[0] != proto.MSG_STANDBY_STATE:
                        continue  # stale/foreign traffic
                    if faults.ARMED and faults.fault_hit(
                            'zmq.replicate', key=b'recv') == 'drop':
                        continue  # injected: snapshot lost in flight
                    state = proto.load_standby_state(
                        frames[2] if len(frames) > 2 else b'')
                    if state is not None:
                        self._snapshot = state
                        self._snapshot_at = time.monotonic()
                    self._last_good = time.monotonic()
                    self._syncs_ok += 1
            return 'stop'
        finally:
            sock.close(linger=0)
            context.term()

    def _promote(self):
        """Take over the endpoint: build a ServiceDaemon seeded with the
        replicated snapshot and bind where the primary was. Retries
        through the dead primary's lingering port (and through injected
        ``service.promote`` failures) until the window closes. True once
        serving as primary."""
        from petastorm_tpu.service.daemon import ServiceDaemon
        self.role = 'promoting'
        snapshot = self._snapshot
        warm = bool(snapshot and snapshot.get('jobs'))
        lag_s = (round(time.monotonic() - self._last_good, 3)
                 if self._last_good is not None else None)
        record_anomaly('dispatcher_failover', detail={
            'endpoint': self.endpoint,
            'warm': warm,
            'snapshot_jobs': len(snapshot.get('jobs', ()))
            if snapshot else 0,
            'replication_lag_s': lag_s})
        tracing.record_instant('standby_promote',
                               tracing.mint(0), 'daemon',
                               endpoint=self.endpoint, warm=warm,
                               lag_s=lag_s)
        logger.warning('Primary at %s silent past %.2fs; promoting '
                       '(%s snapshot, %d job(s))', self.endpoint,
                       self._lapse_s, 'warm' if warm else 'cold',
                       len(snapshot.get('jobs', ())) if snapshot else 0)
        deadline = time.monotonic() + self._promote_timeout_s
        while not self._stop_event.is_set() \
                and time.monotonic() < deadline:
            daemon = None
            try:
                if faults.ARMED:
                    faults.fault_hit('service.promote', key=self.endpoint)
                daemon = ServiceDaemon(self.endpoint, seed_state=snapshot,
                                       **self._daemon_kwargs)
                daemon.start()
            except Exception:  # noqa: BLE001 - retried inside the window
                count_swallowed('standby-promote-attempt')
                logger.info('Promotion attempt on %s failed; retrying',
                            self.endpoint, exc_info=True)
                if daemon is not None:
                    try:
                        daemon.stop()
                    except Exception:  # noqa: BLE001 - best-effort
                        count_swallowed('standby-promote-cleanup')
                if self._stop_event.wait(_PROMOTE_BACKOFF_S):
                    return False
                continue
            self.daemon = daemon
            self._promotions += 1
            self.role = 'primary'
            if not metrics_disabled():
                get_registry().counter(SERVICE_FAILOVERS).inc()
            tracing.record_instant('endpoint_takeover',
                                   tracing.mint(0), 'daemon',
                                   endpoint=self.endpoint, warm=warm,
                                   jobs=daemon.dispatcher.active_jobs())
            logger.warning('Standby promoted: serving as primary at %s '
                           'with %d seeded job(s)', self.endpoint,
                           daemon.dispatcher.active_jobs())
            self._promoted.set()
            return True
        return False
