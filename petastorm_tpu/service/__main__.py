"""CLI of the standing decode service daemon::

    python -m petastorm_tpu.service --endpoint tcp://0.0.0.0:7777 \\
        --workers 2 --max-workers 8 --obs-port 0

Runs the daemonized dispatcher (job registry, leases, admission
control) plus the self-healing worker supervisor until SIGTERM/SIGINT
drains the registry empty (a second signal stops hard). See
docs/service.md, "Standing service".

With ``--standby`` the process is a WARM STANDBY instead: it mirrors
the primary daemon already serving ``--endpoint`` and promotes itself
onto that same endpoint when the primary goes silent past the lapse
window (docs/service.md, "High availability")::

    python -m petastorm_tpu.service --standby \\
        --endpoint tcp://127.0.0.1:7777 --workers 2
"""

import argparse
import logging
import os
import sys

from petastorm_tpu.service.daemon import ServiceDaemon
from petastorm_tpu.telemetry import knobs


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.service',
        description='petastorm_tpu standing decode-service daemon')
    parser.add_argument('--endpoint', default='tcp://127.0.0.1:0',
                        help='tcp://host:port to bind (port 0 = random; '
                             'the resolved endpoint is logged)')
    parser.add_argument('--workers', type=int, default=1,
                        help='initial supervised worker-server fleet size')
    parser.add_argument('--min-workers', type=int, default=None,
                        help='release floor (default '
                             'PETASTORM_TPU_SERVICE_MIN_WORKERS)')
    parser.add_argument('--max-workers', type=int, default=None,
                        help='recruitment ceiling (default '
                             'PETASTORM_TPU_SERVICE_MAX_WORKERS)')
    parser.add_argument('--no-supervisor', action='store_true',
                        help='serve an externally-managed fleet: no '
                             'worker processes are spawned, replaced or '
                             'released by this daemon')
    parser.add_argument('--heartbeat-interval', type=float, default=1.0)
    parser.add_argument('--liveness-timeout', type=float, default=None,
                        help='heartbeat silence after which a worker is '
                             'declared dead (default 4 intervals)')
    parser.add_argument('--max-jobs', type=int, default=None,
                        help='admission ceiling (default '
                             'PETASTORM_TPU_SERVICE_MAX_JOBS)')
    parser.add_argument('--lease', type=float, default=None,
                        help='default job lease seconds (default '
                             'PETASTORM_TPU_SERVICE_LEASE_S)')
    parser.add_argument('--obs-port', type=int, default=None,
                        help='serve /metrics /report /health /trace on '
                             'this port (0 = ephemeral; same as setting '
                             'PETASTORM_TPU_OBS_PORT)')
    parser.add_argument('--standby', action='store_true',
                        help='run as a warm standby for the PRIMARY '
                             'daemon at --endpoint: mirror its registry '
                             'and promote onto that endpoint when it '
                             'lapses (requires a concrete port)')
    parser.add_argument('--standby-sync-interval', type=float,
                        default=None,
                        help='seconds between replication pulls (default '
                             'PETASTORM_TPU_SERVICE_STANDBY_SYNC_S)')
    parser.add_argument('--standby-lapse', type=float, default=None,
                        help='primary silence before promotion (default '
                             'PETASTORM_TPU_SERVICE_STANDBY_LAPSE_S)')
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)
    if args.obs_port is not None:
        knobs.set_env('PETASTORM_TPU_OBS_PORT', str(args.obs_port))
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format='%(asctime)s service-daemon[%(process)d] %(message)s')
    # the daemon itself must never touch an accelerator; its supervised
    # workers re-pin themselves the same way (exec_in_new_process)
    os.environ['JAX_PLATFORMS'] = 'cpu'
    daemon_kwargs = dict(
        initial_workers=args.workers,
        min_workers=args.min_workers, max_workers=args.max_workers,
        heartbeat_interval_s=args.heartbeat_interval,
        liveness_timeout_s=args.liveness_timeout,
        max_jobs=args.max_jobs, lease_s=args.lease,
        supervise=not args.no_supervisor)
    if args.standby:
        from petastorm_tpu.service.standby import StandbyDaemon
        standby = StandbyDaemon(
            args.endpoint, sync_interval_s=args.standby_sync_interval,
            lapse_s=args.standby_lapse, **daemon_kwargs)
        standby.run_forever()
        return 0
    daemon = ServiceDaemon(args.endpoint, **daemon_kwargs)
    daemon.start()
    daemon.run_forever()
    return 0


if __name__ == '__main__':
    sys.exit(main())
