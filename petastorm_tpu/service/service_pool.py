"""ServicePool: the disaggregated service's client-side pool.

Implements the exact pool contract of
:class:`~petastorm_tpu.workers.thread_pool.ThreadPool` /
:class:`~petastorm_tpu.workers.process_pool.ProcessPool`
(``start / ventilate / get_results / stop / join / diagnostics``), so
``Reader(..., reader_pool_type='service')`` and ``make_jax_loader(...)``
work unchanged — the decode fleet just lives on other hosts.

Two deployment modes:

* **External fleet** (production): pass ``endpoint='tcp://0.0.0.0:7777'``
  (or set ``PETASTORM_TPU_SERVICE_DISPATCHER``); the pool hosts the
  dispatcher at that address and worker servers started anywhere
  (``python -m petastorm_tpu.service.worker_server --endpoint ...``)
  register with it, with retry/backoff, before or after the pool starts.
* **Local fleet** (tests, benchmarks, single-host use): pass
  ``spawn_local_workers=N``; the pool binds a random loopback port and
  spawns N worker-server processes itself (spawn-not-fork, pinned to
  ``JAX_PLATFORMS=cpu`` like the process pool's workers), reaping them on
  ``join()``.

Back-pressure is layered: the consumer-facing results queue is bounded
(``results_queue_size``), the dispatcher stops reading completions when it
is full, and each worker server holds at most ``max_inflight_per_worker``
assigned items — so a stalled consumer quiesces the whole remote fleet
instead of buffering unboundedly.
"""

import logging
import os
import queue
import threading
import time

from petastorm_tpu.errors import RowGroupPoisonedError, ServiceWedgedError
from petastorm_tpu.serializers import PickleSerializer
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.service.dispatcher import Dispatcher
from petastorm_tpu.telemetry import knobs, tracing
from petastorm_tpu.workers import (
    EmptyResultError, TimeoutWaitingForResultError,
)

logger = logging.getLogger(__name__)

_POLL_INTERVAL_S = 0.05
_BIND_TIMEOUT_S = 10.0
_JOIN_TIMEOUT_S = 10.0
# Ventilator-sizing hint before any worker has registered (external fleets
# announce themselves only at runtime).
_WORKERS_COUNT_HINT = 4


def apply_poison_policy(pool, info, health_owner):
    """Shared consumer-side handling of one quarantined-item delivery —
    the ONE implementation of ``poison_policy`` semantics for both
    service pool flavors (embedded :class:`ServicePool` and the standing
    service's :class:`~petastorm_tpu.service.daemon.DaemonClientPool`),
    so the policy can never drift between topologies.

    ``'skip'`` records the descriptor on ``pool.poisoned_items`` and
    reads on (the item's marker keeps the accounting exact, so the
    epoch ends with the loss reported, not wedged); ``'raise'``
    surfaces the poison — the original worker exception when the
    failures carried one, else :class:`RowGroupPoisonedError` — after
    stopping the pool. ``health_owner`` names where the operator finds
    the quarantine ledger (the error message's pointer)."""
    descriptor = {k: (repr(v) if k == 'error' and v is not None else v)
                  for k, v in info.items()}
    pool.poisoned_items.append(descriptor)
    if pool.poison_policy == 'skip':
        logger.warning(
            'Skipping quarantined item %s after %s attempt(s) (%s) — '
            "poison_policy='skip'", info.get('item_id'),
            info.get('attempts'), info.get('reason'))
        return
    error = info.get('error')
    if error is None:
        error = RowGroupPoisonedError(
            'Service work item %s was quarantined after %s failed '
            'attempt(s) (%s). Its workers died without reporting an '
            'exception; see %s `poisoned` list. '
            "Pass poison_policy='skip' to read past quarantined "
            'row-groups.' % (info.get('item_id'), info.get('attempts'),
                             info.get('reason'), health_owner),
            info=descriptor)
    pool._error = error
    pool.stop()
    pool.join()
    raise pool._error


def consume_results(pool, timeout, lock, check_fatal=None, on_marker=None,
                    wedge_error=None):
    """The ONE consumer read loop shared by :class:`ServicePool` and the
    standing service's :class:`~petastorm_tpu.service.daemon
    .DaemonClientPool` — factored the way :func:`apply_poison_policy`
    already is, so the wedge clock, the no-progress deadline and the
    marker/poison/error handling can never drift between the two
    topologies (they were deliberate near-copies before).

    ``pool`` provides the shared surface: ``_error``, ``_results_queue``,
    ``_stop_event``, ``_ventilated_items``/``_processed_items`` (guarded
    by ``lock``), ``_ventilator``, ``_serializer``, ``_last_progress``,
    ``_read_deadline_s``, ``_note_poisoned`` and ``stop``/``join``.
    ``check_fatal()`` (optional) runs on every empty poll and returns an
    exception to surface, or None — the embedded pool's dispatcher-death
    / dead-local-fleet probe. ``on_marker()`` (optional) runs UNDER
    ``lock`` together with the processed-item increment — the daemon
    client's ack credit. ``wedge_error(waited_s, inflight)`` builds the
    topology-specific :class:`~petastorm_tpu.errors.ServiceWedgedError`
    when the no-progress deadline trips.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    # the wedge clock measures time blocked INSIDE this call: a consumer
    # pausing between calls (recompile, checkpoint save) is not service
    # starvation and must not trip the deadline on re-entry
    pool._last_progress = time.monotonic()
    while True:
        if pool._error is not None:
            raise pool._error
        try:
            kind, payload = pool._results_queue.get(
                timeout=_POLL_INTERVAL_S)
        except queue.Empty:
            if pool._stop_event.is_set():
                raise EmptyResultError()
            fatal = check_fatal() if check_fatal is not None else None
            if fatal is not None:
                pool._error = fatal
                pool.stop()
                pool.join()
                raise pool._error
            with lock:
                all_done = (pool._ventilated_items
                            == pool._processed_items)
            if all_done and (pool._ventilator is None
                             or pool._ventilator.completed()):
                raise EmptyResultError()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutWaitingForResultError()
            if not all_done:
                _check_no_progress(pool, lock, wedge_error)
            continue
        pool._last_progress = time.monotonic()
        if kind == 'marker':
            with lock:
                pool._processed_items += 1
                if on_marker is not None:
                    on_marker()
            if pool._ventilator is not None:
                pool._ventilator.processed_item()
            continue
        if kind == 'poisoned':
            pool._note_poisoned(payload)
            continue
        if kind == 'error':
            pool._error = payload
            pool.stop()
            pool.join()
            raise pool._error
        return pool._serializer.deserialize(payload)


def _check_no_progress(pool, lock, wedge_error):
    """Raise the diagnosable wedge error when no entry reached this
    consumer for ``read_deadline_s`` with work outstanding — instead of
    a silent hang (lost WORK frame, dead-but-undetected workers, network
    partition, dead daemon)."""
    if not pool._read_deadline_s or wedge_error is None:
        return
    waited = time.monotonic() - pool._last_progress
    if waited <= pool._read_deadline_s:
        return
    with lock:
        inflight = pool._ventilated_items - pool._processed_items
    error = wedge_error(waited, inflight)
    pool._error = error
    pool.stop()
    pool.join()
    raise error


class ServicePool:
    """Client pool backed by remote worker servers over ``tcp://``."""

    def __init__(self, endpoint=None, expected_workers=None,
                 spawn_local_workers=None, results_queue_size=50,
                 serializer=None, heartbeat_interval_s=1.0,
                 liveness_timeout_s=None, connect_timeout_s=30.0,
                 no_workers_timeout_s=30.0, max_inflight_per_worker=2,
                 worker_ack_timeout_s=None, max_retries=None,
                 retry_backoff_s=None, poison_policy='raise',
                 read_deadline_s=None):
        """
        :param endpoint: ``tcp://host:port`` the dispatcher binds (port 0 =
            random). Default: random loopback port (local fleet mode).
        :param expected_workers: block ``start()`` until this many worker
            servers registered (default: the spawned count, else 1).
        :param spawn_local_workers: spawn this many localhost worker-server
            processes owned by this pool.
        :param liveness_timeout_s: heartbeat silence after which a worker is
            declared dead and its items re-ventilated (default 4 heartbeat
            intervals).
        :param connect_timeout_s: how long ``start()`` waits for the
            expected registrations before failing.
        :param no_workers_timeout_s: runtime failure threshold — work
            outstanding but zero live workers for this long.
        :param worker_ack_timeout_s: spawned-fleet only — how long a
            worker server tolerates missing dispatcher heartbeat acks
            before abandoning the job (default: the server's own
            ``max(10 * heartbeat_interval, 10s)``).
        :param max_retries: per-item retry budget, total attempts
            (default ``PETASTORM_TPU_SERVICE_MAX_RETRIES``); an item
            exhausting it is quarantined, not crash-looped.
        :param retry_backoff_s: base of the exponential retry backoff
            (default ``PETASTORM_TPU_SERVICE_RETRY_BACKOFF_S``).
        :param poison_policy: what a quarantined item does to this
            consumer: ``'raise'`` (default — surface the poison; the
            original worker exception when one exists, else
            :class:`~petastorm_tpu.errors.RowGroupPoisonedError`) or
            ``'skip'`` (drop the quarantined item's rows, record it in
            :attr:`poisoned_items`, keep reading — degrade, don't die).
        :param read_deadline_s: ``get_results`` no-progress deadline with
            work outstanding, after which
            :class:`~petastorm_tpu.errors.ServiceWedgedError` (carrying
            the live fleet view) is raised instead of wedging forever
            (default ``PETASTORM_TPU_SERVICE_READ_DEADLINE_S``; 0
            disables).
        """
        if poison_policy not in ('raise', 'skip'):
            raise ValueError("poison_policy must be 'raise' or 'skip'; "
                             'got %r' % (poison_policy,))
        self._endpoint_requested = endpoint or 'tcp://127.0.0.1:0'
        self._expected_workers = expected_workers
        self._spawn_local_workers = spawn_local_workers
        self._results_queue_size = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._heartbeat_interval_s = heartbeat_interval_s
        self._liveness_timeout_s = (liveness_timeout_s
                                    if liveness_timeout_s is not None
                                    else 4.0 * heartbeat_interval_s)
        self._connect_timeout_s = connect_timeout_s
        self._no_workers_timeout_s = no_workers_timeout_s
        self._max_inflight_per_worker = max_inflight_per_worker
        self._worker_ack_timeout_s = worker_ack_timeout_s
        self._max_retries = max_retries
        self._retry_backoff_s = retry_backoff_s
        self.poison_policy = poison_policy
        self._read_deadline_s = (read_deadline_s
                                 if read_deadline_s is not None
                                 else knobs.get_float(
                                     'PETASTORM_TPU_SERVICE_READ'
                                     '_DEADLINE_S', 300.0, floor=0.0))
        #: quarantine descriptors seen by THIS consumer (poison_policy=
        #: 'skip' keeps reading past them; the reader's report and the
        #: dispatcher /health carry the same records)
        self.poisoned_items = []
        self._last_progress = None

        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._stop_event = threading.Event()
        self._counter_lock = threading.Lock()
        self._ventilated_items = 0
        self._processed_items = 0
        self._ventilator = None
        self._dispatcher = None
        self._dispatcher_thread = None
        self._local_procs = []
        self._error = None
        self._joined = False
        self._obs_mount = None

    @property
    def workers_count(self):
        """Live fleet size (never below the configured floor). The reader's
        ventilator re-reads this for its in-flight bound, so worker servers
        joining a RUNNING job genuinely raise parallelism — the scale-out
        path autotune_report advises."""
        base = self._spawn_local_workers or self._expected_workers or 0
        registered = (self._dispatcher.registered_workers()
                      if self._dispatcher is not None else 0)
        return max(base, registered) or _WORKERS_COUNT_HINT

    @property
    def dispatcher_endpoint(self):
        """The resolved ``tcp://`` endpoint (after a random-port bind)."""
        return self._dispatcher.endpoint if self._dispatcher else None

    # -- lifecycle ----------------------------------------------------------

    def start(self, worker_class, worker_args=None, ventilator=None,
              start_ventilator=True):
        if self._dispatcher is not None:
            raise RuntimeError('ServicePool already started')
        job_spec = proto.dump_job_spec(worker_class, worker_args,
                                       self._serializer)
        self._dispatcher = Dispatcher(
            self._endpoint_requested, job_spec, self._deliver,
            self._stop_event,
            heartbeat_interval_s=self._heartbeat_interval_s,
            liveness_timeout_s=self._liveness_timeout_s,
            max_inflight_per_worker=self._max_inflight_per_worker,
            no_workers_timeout_s=self._no_workers_timeout_s,
            max_retries=self._max_retries,
            retry_backoff_s=self._retry_backoff_s)
        self._dispatcher_thread = threading.Thread(
            target=self._dispatcher.run, daemon=True,
            name='service-dispatcher')
        self._dispatcher_thread.start()
        self._dispatcher.wait_bound(_BIND_TIMEOUT_S)

        if self._spawn_local_workers:
            self._spawn_workers()
        self._await_registrations()

        # live observability plane: the dispatcher runs in THIS (consumer)
        # process, so its fleet view — per-worker heartbeat summaries on
        # top of the registry's already-merged fleet aggregate — mounts on
        # the same endpoint the Reader/JaxLoader use (docs/service.md)
        from petastorm_tpu.telemetry import obs_server
        self._obs_mount = obs_server.mount(
            'service-dispatcher', health=self._dispatcher.health,
            report=self._fleet_report)

        self._ventilator = ventilator
        if ventilator is not None and start_ventilator:
            ventilator.start()

    def _fleet_report(self):
        return {'fleet': self._dispatcher.fleet_view()}

    def _spawn_workers(self):
        from petastorm_tpu.service.worker_server import serve
        from petastorm_tpu.workers.exec_in_new_process import (
            exec_in_new_process,
        )

        for worker_id in range(self._spawn_local_workers):
            proc = exec_in_new_process(
                serve, self._dispatcher.endpoint, worker_id=worker_id,
                heartbeat_interval_s=self._heartbeat_interval_s,
                ack_timeout_s=self._worker_ack_timeout_s,
                parent_pid=os.getpid(), once=True,
                register_timeout_s=self._connect_timeout_s)
            self._local_procs.append(proc)

    def _await_registrations(self):
        need = (self._expected_workers or self._spawn_local_workers or 1)
        deadline = time.monotonic() + self._connect_timeout_s
        while self._dispatcher.registered_workers() < need:
            if self._dispatcher.fatal_error is not None:
                self._abort_startup()
                raise self._dispatcher.fatal_error
            # ANY exit before registration is fatal here — including a
            # clean one (registration window closed, parent-death check):
            # the fleet will never reach the expected size.
            dead = [p.pid for p in self._local_procs if p.poll() is not None]
            if dead:
                self._abort_startup()
                raise RuntimeError(
                    'Service worker server process(es) %s exited during '
                    'startup — see their stderr for the reason' % dead)
            if time.monotonic() > deadline:
                registered = self._dispatcher.registered_workers()
                self._abort_startup()
                raise RuntimeError(
                    'Only %d of %d worker servers registered with the '
                    'dispatcher at %s within %.1fs (workers retry with '
                    'backoff — check endpoint reachability and that the '
                    'servers are running)'
                    % (registered, need, self._dispatcher.endpoint,
                       self._connect_timeout_s))
            time.sleep(_POLL_INTERVAL_S)

    def _abort_startup(self):
        self._stop_event.set()
        if self._dispatcher_thread is not None:
            self._dispatcher_thread.join(_JOIN_TIMEOUT_S)
        self._reap_local_procs()

    # -- data path ----------------------------------------------------------

    def ventilate(self, *args, **kwargs):
        with self._counter_lock:
            self._ventilated_items += 1
        # a traced item's context rides INSIDE the opaque work payload to
        # the worker server; the dispatcher additionally needs it BY item
        # id to stamp its lifecycle instants (dispatch/reventilate/done)
        self._dispatcher.submit(proto.dump_work_item(args, kwargs),
                                trace_ctx=kwargs.get(tracing.TRACE_CTX_KEY))

    def _deliver(self, entry):
        """Dispatcher-thread side of the results queue: NON-BLOCKING put.
        False = momentarily full (the dispatcher backlogs and retries);
        the dispatcher thread must stay free to ack worker heartbeats, so
        a stalled consumer quiesces the fleet instead of starving its
        liveness protocol. On stop the entry is dropped (True): accounting
        no longer matters and the backlog must not pin shutdown."""
        if self._stop_event.is_set():
            return True
        try:
            self._results_queue.put_nowait(entry)
            return True
        except queue.Full:
            return False

    def get_results(self, timeout=None):
        return consume_results(self, timeout, self._counter_lock,
                               check_fatal=self._check_fatal,
                               wedge_error=self._wedge_error)

    def _check_fatal(self):
        """Per-empty-poll fatal probe (shared loop hook): a dispatcher
        fatal error, or every spawned local worker dead with work still
        outstanding."""
        fatal = (self._dispatcher.fatal_error
                 if self._dispatcher else None)
        if fatal is None and self._local_procs and \
                all(p.poll() is not None for p in self._local_procs):
            with self._counter_lock:
                outstanding = (self._ventilated_items
                               != self._processed_items)
            if outstanding:
                fatal = RuntimeError(
                    'All spawned service worker servers died '
                    'unexpectedly: %s'
                    % [p.pid for p in self._local_procs])
        return fatal

    def _note_poisoned(self, info):
        """One quarantined item reached this consumer: apply the
        ``poison_policy`` (shared semantics: :func:`apply_poison_policy`)."""
        apply_poison_policy(self, info, "the dispatcher's /health")

    def _wedge_error(self, waited, inflight):
        """The embedded pool's wedge diagnosis — carrying the live fleet
        view, so the operator sees WHICH failure domain wedged."""
        fleet = {}
        try:
            fleet = self._dispatcher.fleet_view()
        except Exception:  # noqa: BLE001 - diagnosis must not mask itself
            pass
        return ServiceWedgedError(
            'Service read made no progress for %.1fs with %d item(s) '
            'outstanding (deadline PETASTORM_TPU_SERVICE_READ_DEADLINE_S'
            '=%.1fs). Live fleet view: %r'
            % (waited, inflight, self._read_deadline_s, fleet),
            fleet=fleet)

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('Must call stop() before join()')
        if self._joined:
            return
        self._joined = True
        if self._obs_mount is not None:
            self._obs_mount.close()
        if self._dispatcher_thread is not None:
            # run() broadcasts STOP to every registered worker on its way out
            self._dispatcher_thread.join(_JOIN_TIMEOUT_S)
        self._reap_local_procs()

    def _reap_local_procs(self):
        deadline = time.monotonic() + _JOIN_TIMEOUT_S
        for proc in self._local_procs:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                proc.wait(timeout=remaining)
            except Exception:  # noqa: BLE001 - ignored stop; escalate
                proc.terminate()
                try:
                    proc.wait(timeout=2)
                except Exception:  # noqa: BLE001
                    proc.kill()
                    proc.wait()
        self._local_procs = []

    # -- observability ------------------------------------------------------

    @property
    def diagnostics(self):
        with self._counter_lock:
            ventilated = self._ventilated_items
            processed = self._processed_items
        diag = {
            'items_ventilated': ventilated,
            'items_processed': processed,
            'items_inflight': ventilated - processed,
            'output_queue_size': self._results_queue.qsize(),
        }
        if self._dispatcher is not None:
            diag.update(self._dispatcher.stats())
        else:
            diag.update({'workers_alive': 0, 'workers_registered': 0,
                         'workers_seen': 0, 'items_assigned': 0,
                         'items_pending': 0, 'items_reventilated': 0,
                         'items_duplicate_done': 0, 'items_retried': 0,
                         'items_poisoned': 0,
                         'metrics_deltas_merged': 0})
        return diag

    @property
    def results_qsize(self):
        return self._results_queue.qsize()
