"""PyArrow-style DNF ``filters`` support for the reader factories.

Parity surface for the reference's ``filters`` kwarg
(``petastorm/reader.py:73,125``: "Standard PyArrow filters", passed to the
legacy ``pq.ParquetDataset`` where they prune partition directories only).
This implementation goes further, TPU-first in spirit — skip I/O instead of
doing it:

* **Row-group pruning before any read**: each clause is tested against hive
  partition values (exact) and the parquet footer's per-row-group column
  statistics (min/max range checks) — row-groups that provably cannot match
  are never ventilated, so their bytes are never fetched or decoded.
* **Exact row filtering on the workers**: surviving row-groups still pass
  through a columnar predicate (``do_include_batch`` masks, no per-row
  Python), so — unlike the reference — ``filters`` are exact at row level,
  not just partition level.

Filter format (the pyarrow DNF convention): a list of ``(column, op, value)``
tuples (ANDed), or a list of such lists (OR of AND-clauses). Supported ops:
``= == != < > <= >= in not in``.
"""

import numpy as np

from petastorm_tpu.predicates import PredicateBase

_OPS = ('=', '==', '!=', '<', '>', '<=', '>=', 'in', 'not in')


def _is_term(t):
    return (isinstance(t, (tuple, list)) and len(t) == 3
            and isinstance(t[0], str) and isinstance(t[1], str))


def normalize_filters(filters):
    """Validate and normalize to DNF: a list of AND-clauses (each a list of
    ``(column, op, value)`` tuples). Returns None for empty input."""
    if not filters:
        return None
    if all(_is_term(t) for t in filters):
        clauses = [list(map(tuple, filters))]
    elif all(isinstance(c, (tuple, list)) and not _is_term(c)
             for c in filters):
        clauses = []
        for clause in filters:
            if not clause:
                raise ValueError('Empty AND-clause in filters')
            bad = [t for t in clause if not _is_term(t)]
            if bad:
                raise ValueError('Filter terms must be (column, op, value) '
                                 'tuples with string column/op, got %r'
                                 % (bad[0],))
            clauses.append(list(map(tuple, clause)))
    else:
        raise ValueError(
            'filters must be a flat list of (column, op, value) tuples OR a '
            'list of such lists (DNF); got a mix: %r' % (filters,))
    for clause in clauses:
        for col, op, value in clause:
            if op not in _OPS:
                raise ValueError('Unsupported filter op %r (supported: %s)'
                                 % (op, ', '.join(_OPS)))
            if op in ('in', 'not in'):
                if isinstance(value, (str, bytes)) or not hasattr(
                        value, '__iter__'):
                    raise ValueError(
                        "%r value for %r must be a non-string collection "
                        '(got %r); for a single value use %r'
                        % (op, col, value, '=' if op == 'in' else '!='))
    return clauses


def _eval_term(op, actual, value):
    if actual is None:
        return False  # pyarrow DNF semantics: nulls never match any term
    if op in ('=', '=='):
        return actual == value
    if op == '!=':
        return actual != value
    if op == '<':
        return actual < value
    if op == '>':
        return actual > value
    if op == '<=':
        return actual <= value
    if op == '>=':
        return actual >= value
    if op == 'in':
        return actual in value
    if op == 'not in':
        return actual not in value
    raise AssertionError(op)


def _eval_term_columnar(op, col, value):
    """Vectorized term over a column; ``col`` is ndarray or list.
    Nulls (None cells in object columns) never match, per pyarrow DNF."""
    arr = col if isinstance(col, np.ndarray) else np.asarray(col, dtype=object)
    if op in ('in', 'not in'):
        if arr.dtype.kind in 'iufb':
            # same dtype-guarded np.isin fast path as predicates.in_set
            values_arr = np.asarray(list(value))
            if values_arr.dtype.kind in 'iufb':
                mask = np.isin(arr, values_arr)
                return ~mask if op == 'not in' else mask
        values = set(value)
        mask = np.fromiter(
            (v is not None and v in values for v in arr),
            dtype=bool, count=len(arr))
        if op == 'not in':
            valid = np.fromiter((v is not None for v in arr),
                                dtype=bool, count=len(arr))
            return valid & ~mask
        return mask
    if arr.dtype == object:
        return np.fromiter(
            (_eval_term(op, v, value) for v in arr), dtype=bool,
            count=len(arr))
    if op in ('=', '=='):
        return arr == value
    if op == '!=':
        return arr != value
    if op == '<':
        return arr < value
    if op == '>':
        return arr > value
    if op == '<=':
        return arr <= value
    return arr >= value


class FiltersPredicate(PredicateBase):
    """DNF filters as a composable predicate with a columnar fast path."""

    def __init__(self, filters):
        clauses = normalize_filters(filters)
        if clauses is None:
            raise ValueError('filters must be non-empty')
        self._clauses = clauses
        self._fields = {term[0] for clause in clauses for term in clause}

    @property
    def clauses(self):
        return self._clauses

    def get_fields(self):
        return set(self._fields)

    def do_include(self, values):
        return any(all(_eval_term(op, values[col], v) for col, op, v in clause)
                   for clause in self._clauses)

    def do_include_batch(self, columns):
        n = len(next(iter(columns.values())))
        mask = np.zeros(n, dtype=bool)
        for clause in self._clauses:
            clause_mask = np.ones(n, dtype=bool)
            for col, op, value in clause:
                clause_mask &= np.asarray(
                    _eval_term_columnar(op, columns[col], value), dtype=bool)
                if not clause_mask.any():
                    break
            mask |= clause_mask
            if mask.all():
                break
        return mask


# ---------------------------------------------------------------------------
# Row-group pruning
# ---------------------------------------------------------------------------

def _term_maybe_matches(term, partition_values, typed_partition):
    """Conservative per-row-group test on PARTITION evidence only: False
    only when a hive partition value proves the term can match no row.
    File-column terms always maybe-match here — the statistics pass
    (:mod:`petastorm_tpu.pushdown`) owns that half."""
    col, op, value = term
    if col not in partition_values:
        return True
    try:
        return bool(_eval_term(op, typed_partition(col), value))
    except TypeError:
        return True  # incomparable types: keep, the worker decides


def prune_row_group_indices(dataset_info, pieces, piece_indices, clauses,
                            stored_schema=None):
    """Drop row-group indices that provably cannot satisfy the filters.

    Two passes, cheapest first: hive partition values prune with zero
    I/O; the pushdown planner's footer-statistics prover
    (:func:`petastorm_tpu.pushdown.plan_rowgroup_pruning` — one footer
    read per file, in parallel, memoized process-wide) then runs over
    the survivors, and only when a filtered column actually lives in the
    files. ``PETASTORM_TPU_PUSHDOWN=0`` limits pruning to the
    partition-value pass (the statistics-pruning oracle escape hatch).
    """
    from petastorm_tpu.arrow_worker import typed_partition_value

    def typed_for(piece):
        def typed(col):
            field = (stored_schema.fields.get(col)
                     if stored_schema is not None else None)
            return typed_partition_value(field, piece.partition_values[col])
        return typed

    def keep(piece):
        return any(
            all(_term_maybe_matches(t, piece.partition_values,
                                    typed_for(piece))
                for t in clause)
            for clause in clauses)

    # pass 1: partition values only (zero I/O)
    survivors = [i for i in piece_indices if keep(pieces[i])]

    needs_stats = any(
        t[0] not in pieces[i].partition_values
        for i in survivors for clause in clauses for t in clause)
    if not needs_stats:
        return survivors

    # pass 2: footer statistics for the survivors, through the memoized
    # planner (lazy import: pushdown imports this module at its top)
    from petastorm_tpu import pushdown
    if not pushdown.pushdown_enabled():
        return survivors
    plan = pushdown.plan_rowgroup_pruning(dataset_info, pieces, survivors,
                                          clauses=clauses,
                                          stored_schema=stored_schema)
    return plan.kept


def describe_clauses(clauses):
    """Human-readable filter rendering for error messages."""
    return ' OR '.join(
        '(' + ' AND '.join('%s %s %r' % t for t in clause) + ')'
        for clause in clauses)


__all__ = ['FiltersPredicate', 'normalize_filters',
           'prune_row_group_indices', 'describe_clauses']
