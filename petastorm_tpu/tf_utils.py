"""TensorFlow bridge: petastorm_tpu readers → ``tf.data.Dataset``.

Re-design of ``petastorm/tf_utils.py`` for TF2: the primary API is
:func:`make_petastorm_dataset` building a ``tf.data.Dataset`` from a reader
with a typed ``output_signature`` (static shapes restored from the Unischema,
wildcard dims → ``None``), instead of the reference's TF1
``tf.py_func``/``RandomShuffleQueue`` graph plumbing (``tf_utils.py:270-327``
— retained only as the thin :func:`tf_tensors` compat shim).

dtype mapping parity (``tf_utils.py:27-44``): uint16→int32, uint32→int64,
Decimal/str/bytes→string, datetime64→int64 (nanoseconds since epoch),
bool→bool.
"""

import datetime
from decimal import Decimal

import numpy as np

_NP_TO_TF_KIND = {
    'uint16': 'int32',
    'uint32': 'int64',
    'uint64': 'int64',
}


def _import_tf():
    import tensorflow as tf
    return tf


def _tf_dtype(tf, field):
    """TF dtype for a Unischema field (reference map, ``tf_utils.py:27-44``)."""
    np_dtype = field.numpy_dtype
    if np_dtype in (np.str_, np.bytes_, str, bytes, Decimal):
        return tf.string
    dt = np.dtype(np_dtype)
    if dt.kind == 'M':  # datetime64 → ns-from-epoch int64
        return tf.int64
    name = _NP_TO_TF_KIND.get(dt.name, dt.name)
    return tf.as_dtype(name)


def _sanitize_field_tf_types(value):
    """Convert values TF cannot ingest (reference: ``tf_utils.py:58-100``)."""
    if value is None:
        raise RuntimeError('Null values in fields are not compatible with '
                           'the TF bridge; fill or filter them first')
    if isinstance(value, Decimal):
        return str(value)
    if isinstance(value, (datetime.date, datetime.datetime)):
        return np.datetime64(value).astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.datetime64):
        return value.astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.ndarray):
        if value.dtype.kind == 'M':
            return value.astype('datetime64[ns]').astype(np.int64)
        if value.dtype == object and value.size and \
                isinstance(value.flat[0], Decimal):
            return value.astype(str)
    return value


def _guard_not_exhausted(reader):
    """No-repeat guard (reference: ``tf_utils.py:367-373``): re-invoking the
    generator on an exhausted reader would silently yield an empty pass —
    ``dataset.repeat()`` would then spin forever."""
    if getattr(reader, 'last_row_consumed', False):
        raise RuntimeError(
            'Multiple iterations over make_petastorm_dataset are not '
            'supported: the underlying reader is exhausted. Use '
            'num_epochs=None (or a larger num_epochs) on the reader instead '
            'of dataset.repeat()/re-iteration.')


def _row_generator(reader, field_names):
    _guard_not_exhausted(reader)
    for row in reader:
        row_dict = row._asdict()
        yield tuple(_sanitize_field_tf_types(row_dict[name])
                    for name in field_names)


def _batch_generator(reader, field_names):
    _guard_not_exhausted(reader)
    for batch in reader:
        columns = batch._asdict()
        out = []
        for name in field_names:
            col = columns[name]
            if col.dtype == object or col.dtype.kind == 'M':
                cells = [_sanitize_field_tf_types(v) for v in col]
                shapes = {np.shape(c) for c in cells}
                if len(shapes) > 1:
                    # pre-empt numpy's opaque 'setting an array element
                    # with a sequence' (surfacing as an
                    # InvalidArgumentError mid-iteration inside tf.data)
                    from petastorm_tpu.ragged import RAGGED_MESSAGE
                    raise TypeError(RAGGED_MESSAGE % name)
                col = np.asarray(cells)
            out.append(col)
        yield tuple(out)


def _field_shape(field, batched):
    shape = tuple(dim if dim is not None else None for dim in field.shape)
    return ((None,) + shape) if batched else shape


def make_petastorm_dataset(reader):
    """``tf.data.Dataset`` over a reader.

    * Row readers (``make_reader``) yield one element per row.
    * Batch readers (``make_batch_reader``) yield one element per row-group
      (re-batch with ``.unbatch().batch(n)``).
    * Elements are namedtuple-shaped (the schema's namedtuple type).

    Reference: ``tf_utils.py:329-412``; no-repeat guard per ``:367-373`` —
    use ``num_epochs=None`` on the reader instead of ``dataset.repeat()``.
    """
    tf = _import_tf()
    if getattr(reader, 'ngram', None) is not None:
        return _make_ngram_dataset(tf, reader)

    schema = reader.schema
    fields = [schema.fields[name] for name in schema.fields]
    field_names = [f.name for f in fields]
    batched = reader.batched_output

    signature = tuple(
        tf.TensorSpec(shape=_field_shape(f, batched), dtype=_tf_dtype(tf, f))
        for f in fields)
    gen = _batch_generator if batched else _row_generator

    dataset = tf.data.Dataset.from_generator(
        lambda: gen(reader, field_names), output_signature=signature)
    nt = schema.namedtuple
    return dataset.map(lambda *args: nt(*args),
                       num_parallel_calls=tf.data.AUTOTUNE)


def _make_ngram_dataset(tf, reader):
    """NGram readers: elements are ``{timestep: namedtuple}`` dicts; flatten
    to a tuple for the generator boundary, rebuild in a map (reference:
    ``tf_utils.py:141-183,402-412``)."""
    ngram = reader.ngram
    schema = reader.schema
    timesteps = sorted(ngram.fields)
    ts_schemas = {k: ngram.get_schema_at_timestep(schema, k)
                  for k in timesteps}
    flat_fields = [(k, ts_schemas[k].fields[name])
                   for k in timesteps for name in ts_schemas[k].fields]

    signature = tuple(
        tf.TensorSpec(shape=_field_shape(f, False), dtype=_tf_dtype(tf, f))
        for _, f in flat_fields)

    def gen():
        _guard_not_exhausted(reader)
        for window in reader:
            out = []
            for k, field in flat_fields:
                value = getattr(window[k], field.name)
                out.append(_sanitize_field_tf_types(value))
            yield tuple(out)

    dataset = tf.data.Dataset.from_generator(gen, output_signature=signature)

    def rebuild(*args):
        window = {}
        i = 0
        for k in timesteps:
            names = list(ts_schemas[k].fields)
            nt = ts_schemas[k].namedtuple
            window[k] = nt(*args[i:i + len(names)])
            i += len(names)
        return window

    return dataset.map(rebuild, num_parallel_calls=tf.data.AUTOTUNE)


_TF_TENSOR_ITERATORS = None

#: well-known op name monitoring tools grep for
#: (reference: ``petastorm/tf_utils.py:46-48``)
RANDOM_SHUFFLING_QUEUE_SIZE = 'random_shuffling_queue_size'


def shuffling_queue_size_tensor(reader):
    """A scalar int64 tensor named ``random_shuffling_queue_size`` reporting
    how many decoded ITEMS (row-group result batches — not individual
    rows) are buffered or in flight ahead of the consumer right now.

    TF2 re-design of the reference's well-known queue-size op
    (``petastorm/tf_utils.py:46-48``: its TF1 ``RandomShuffleQueue`` exposed
    ``.size()`` under that name for TensorBoard fill-level monitoring; TF2's
    ``dataset.shuffle`` hides its buffer). The value comes from the reader's
    own :attr:`diagnostics` gauges: explicit queue depths where the pool
    reports them (thread pool, JaxLoader staging), otherwise
    ventilated-minus-processed in-flight items (process pool) — evaluate it
    in a summary callback each step::

        tf.summary.scalar('shuffling_queue_size',
                          shuffling_queue_size_tensor(reader))

    A shrinking value means the consumer outruns the input pipeline (add
    workers); a steadily full gauge means the input side is not the
    bottleneck.
    """
    tf = _import_tf()

    def _size():
        return np.int64(_buffered_item_count(
            getattr(reader, 'diagnostics', None) or {}))

    return tf.py_function(_size, [], tf.int64,
                          name=RANDOM_SHUFFLING_QUEUE_SIZE)


def _buffered_item_count(diag):
    """Decoded items buffered/in flight per the diagnostics gauges."""
    total = 0
    found = False
    for key in ('stage_queue_depth', 'output_queue_size'):
        value = diag.get(key)
        if isinstance(value, (int, float)):
            total += int(value)
            found = True
    if not found:
        ventilated = diag.get('items_ventilated')
        processed = diag.get('items_processed')
        if isinstance(ventilated, (int, float)) \
                and isinstance(processed, (int, float)):
            total = max(0, int(ventilated) - int(processed))
    return total


def tf_tensors(reader, shuffling_queue_capacity=0, min_after_dequeue=0):
    """TF1-style compat shim: each call yields the reader's next row as eager
    tensors (reference: ``tf_utils.py:270-327``). Prefer
    :func:`make_petastorm_dataset`.

    The underlying dataset iterator is cached per reader — rebuilding it per
    call would discard its prefetched rows and silently skip data.
    """
    global _TF_TENSOR_ITERATORS
    if _TF_TENSOR_ITERATORS is None:
        import weakref
        _TF_TENSOR_ITERATORS = weakref.WeakKeyDictionary()
    if reader not in _TF_TENSOR_ITERATORS:
        dataset = make_petastorm_dataset(reader)
        if shuffling_queue_capacity > 0:
            dataset = dataset.shuffle(shuffling_queue_capacity,
                                      reshuffle_each_iteration=True)
            del min_after_dequeue  # folded into dataset.shuffle semantics
        _TF_TENSOR_ITERATORS[reader] = (iter(dataset),
                                        shuffling_queue_capacity)
    iterator, cached_capacity = _TF_TENSOR_ITERATORS[reader]
    if cached_capacity != shuffling_queue_capacity:
        raise ValueError(
            'tf_tensors was already called on this reader with '
            'shuffling_queue_capacity=%d; later calls cannot change it'
            % cached_capacity)
    try:
        return next(iterator)
    except StopIteration:
        raise RuntimeError(
            'tf_tensors: the underlying reader is exhausted (num_epochs '
            'reached); use num_epochs=None for an endless stream') from None
