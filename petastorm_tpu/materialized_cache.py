"""Materialized decoded-row-group cache: decode once, serve many.

``LocalDiskCache`` caches *raw* pickled reads per process; nothing caches
*decoded* output, so every epoch and every co-trained job re-pays the
io→decode→filter→transform pipeline — the stage BENCH_r05 measures at 71%
of the read path (``jax_io_decode_share`` 0.711) and that both the tf.data
service paper (arxiv 2210.14826) and the tabular-preprocessing study
(arxiv 2409.14912) identify as the dominant, cacheable cost.

:class:`MaterializedRowGroupCache` stores the *finished* columnar batch —
post decode, filter and TransformSpec — as an **Arrow IPC file** per
row-group, keyed by ``(dataset fingerprint, row-group, TransformSpec/
codec/schema fingerprint)`` (the fingerprints live here too, see
:func:`decode_fingerprint`). Entries are written via the atomic
tmp + ``os.replace`` discipline, so concurrent readers — including the
whole service fleet pointing ``PETASTORM_TPU_DECODED_CACHE_DIR`` at one
shared directory — never observe a partial entry.

On a hit the batch is **memory-mapped back zero-copy**: numeric/str
columns become ``np.frombuffer`` views over the IPC file's mmap'd buffers
(no pickle, no decode spans — the hit path records only the
``cache_hit_read`` stage), so epoch 2+ is cache-bound instead of
decode-bound. Ragged/object columns fall back to an embedded pickle cell
(counted separately as copy reads). A bounded in-memory tier
(``mem_limit_bytes``) sits over the size-bounded disk tier so the hottest
row-groups skip the filesystem entirely.
"""

import errno
import hashlib
import json
import logging
import os
import pickle
import re
import tempfile
import threading
import time
import types
from collections import OrderedDict

import numpy as np

from petastorm_tpu import faults, sanitizer
from petastorm_tpu.cache import (
    CacheBase, attach_scan, evict_lru, publish_entry,
)
from petastorm_tpu.telemetry import span
from petastorm_tpu.telemetry.registry import get_registry
from petastorm_tpu.telemetry.timeseries import record_anomaly

logger = logging.getLogger(__name__)

# telemetry counter names (read back by telemetry.export's decoded-cache
# section); a worker process's increments ride the pool delta channels
DECODED_CACHE_HITS = 'petastorm_tpu_decoded_cache_hits_total'
DECODED_CACHE_MISSES = 'petastorm_tpu_decoded_cache_misses_total'
DECODED_CACHE_MEM_HITS = 'petastorm_tpu_decoded_cache_mem_hits_total'
DECODED_CACHE_EVICTIONS = 'petastorm_tpu_decoded_cache_evictions_total'
DECODED_CACHE_BYTES_WRITTEN = \
    'petastorm_tpu_decoded_cache_bytes_written_total'
DECODED_CACHE_BYTES_READ = 'petastorm_tpu_decoded_cache_bytes_read_total'
DECODED_CACHE_MMAP_READS = 'petastorm_tpu_decoded_cache_mmap_reads_total'
DECODED_CACHE_COPY_READS = 'petastorm_tpu_decoded_cache_copy_reads_total'
DECODED_CACHE_SIZE_BYTES = 'petastorm_tpu_decoded_cache_size_bytes'
DECODED_CACHE_DISK_FAILURES = \
    'petastorm_tpu_decoded_cache_disk_failures_total'
DECODED_CACHE_DEGRADED = 'petastorm_tpu_decoded_cache_degraded'
DECODED_CACHE_SKIPPED = 'petastorm_tpu_decoded_cache_skipped_total'


def count_cache_skip(reason):
    """One reader left uncached by the decoded-cache arming logic, by
    reason (today: ``predicate`` — an arbitrary predicate has no stable
    cache identity, so ``PETASTORM_TPU_DECODED_CACHE=1`` reads it
    uncached; ``FiltersPredicate`` readers DO cache, their clause digest
    joins the key). Documented in docs/telemetry.md — a silently
    uncached fleet knob was previously invisible."""
    from petastorm_tpu.telemetry.spans import metrics_disabled
    if not metrics_disabled():
        get_registry().counter(DECODED_CACHE_SKIPPED, reason=reason).inc()

#: errnos that mean the MEDIUM (or the directory) is the problem, not
#: one entry, when a STORE fails: disk full, quota, read-only remount,
#: directory permissions, I/O error. One of these degrades the disk
#: tier immediately — retrying per-row-group would fail the same way
#: and bill every row-group an fsync-deep error path.
_STORE_FAULT_ERRNOS = frozenset(
    e for e in (errno.ENOSPC, getattr(errno, 'EDQUOT', None), errno.EROFS,
                errno.EACCES, errno.EPERM, errno.EIO) if e is not None)

#: on a READ only EIO indicts the medium. EACCES/EPERM are frequently
#: ENTRY-shaped there — one foreign-UID file in a shared per-host
#: directory must not disarm the tier for every other readable entry —
#: so they ride the consecutive-failure ramp instead.
_READ_FAULT_ERRNOS = frozenset((errno.EIO,))

#: entry-shaped failures (serialization oddities, transient weirdness)
#: tolerate this many CONSECUTIVE occurrences before degrading anyway —
#: a tier failing every single store is not caching, just burning time
_CONSECUTIVE_FAILURE_LIMIT = 5

#: memory-tier hits LRU-touch their backing disk entry at most this
#: often per entry: the disk LRU only needs coarse freshness, and a hot
#: in-memory loop must not pay one utime syscall per hit
_UTIME_INTERVAL_S = 5.0
_UTIME_TRACKED_CAP = 4096


# -- publish notifications ----------------------------------------------------
# The peer-cache serve plane (service/peer_cache.py) advertises entries
# the moment THIS process publishes them instead of waiting out its
# directory rescan; module-level so one hook covers every cache object.

_PUBLISH_LISTENERS = []


def add_publish_listener(listener):
    """Register ``listener(entry_path, size)`` to run after every
    successful disk-tier publish in this process."""
    _PUBLISH_LISTENERS.append(listener)


def remove_publish_listener(listener):
    try:
        _PUBLISH_LISTENERS.remove(listener)
    except ValueError:
        pass


def _notify_published(entry, size):
    for listener in list(_PUBLISH_LISTENERS):
        try:
            listener(entry, size)
        except Exception:  # noqa: BLE001 - adverts are advisory
            from petastorm_tpu.telemetry import count_swallowed
            count_swallowed('cache-publish-listener')

#: dtype kinds whose flat buffer round-trips through np.frombuffer —
#: these columns mmap back zero-copy; everything else ('O' object arrays:
#: ragged rows, None-bearing nullables, Decimals) embeds a pickle cell
_RAW_KINDS = 'biufcmMSU'

_LENGTH_META = b'petastorm_tpu.length'
_VERSION_META = b'petastorm_tpu.version'
_FORMAT_VERSION = b'1'


def default_cache_dir():
    """Shared-by-default location: every process (and every locally
    spawned worker fleet) on the host resolves the same directory, so the
    decode-once-serve-many property holds without configuration."""
    return os.path.join(tempfile.gettempdir(),
                        'petastorm-tpu-decoded-cache')


# -- fingerprints ------------------------------------------------------------
#
# The cache key must change whenever the *content* of a decoded batch
# could: a different TransformSpec (code, closure, or schema edits), a
# different codec configuration, a different loaded column set, or a
# rewritten dataset file. Serving a stale decoded batch is silent data
# corruption, so every fingerprint errs toward over-invalidation.


def _sha1(*parts):
    h = hashlib.sha1()
    for part in parts:
        h.update(part if isinstance(part, bytes) else str(part).encode())
        h.update(b'\x00')
    return h.hexdigest()


#: CPython's default object repr embeds the allocation address — useless
#: (and actively harmful) as a cross-process identity, so it is scrubbed
#: from every repr-based fallback digest below
_ADDR_RE = re.compile(r' at 0x[0-9a-f]+')


def _value_digest(value, depth=0):
    """Deterministic-across-processes digest of a Python value.

    ``repr`` is NOT enough for two reasons this function exists to fix:
    numpy truncates large arrays (two different 10k-element lookup tables
    repr identically — a collision would serve stale decoded rows), and
    nested code objects / default object reprs embed memory addresses
    (a new address every process — the shared cache would never hit).
    """
    if depth > 8:  # deep/self-referential structures: coarse but stable
        return _sha1('deep', _ADDR_RE.sub('', repr(value)))
    if value is None or isinstance(value, (bool, int, float, complex,
                                           str, bytes)):
        return repr(value)
    if isinstance(value, np.ndarray):
        return _sha1('nd', value.dtype.str, value.shape,
                     np.ascontiguousarray(value).tobytes())
    if isinstance(value, types.CodeType):
        return _code_digest(value, depth + 1)
    if isinstance(value, (tuple, list)):
        return _sha1(type(value).__name__,
                     *[_value_digest(v, depth + 1) for v in value])
    if isinstance(value, (set, frozenset)):
        return _sha1('set', *sorted(_value_digest(v, depth + 1)
                                    for v in value))
    if isinstance(value, dict):
        return _sha1('dict', *sorted(
            '%s:%s' % (_value_digest(k, depth + 1),
                       _value_digest(v, depth + 1))
            for k, v in value.items()))
    if callable(value):
        return callable_fingerprint(value, depth + 1)
    try:
        return _sha1('pkl', pickle.dumps(value, protocol=4))
    except Exception:  # noqa: BLE001 - unpicklable: scrubbed-repr fallback
        return _sha1('repr', _ADDR_RE.sub('', repr(value)))


def _code_digest(code, depth=0):
    """Digest of a code object, recursing into nested code consts (a
    lambda/inner def inside a transform) instead of repr'ing them —
    ``repr(code)`` carries the object's address and would differ every
    process."""
    return _sha1(code.co_code,
                 *[_value_digest(c, depth) for c in code.co_consts],
                 repr(code.co_names), repr(code.co_varnames))


def callable_fingerprint(func, _depth=0):
    """Deterministic-across-processes identity of a transform callable:
    code bytes + consts + defaults + closure cell contents. Two processes
    importing the same function agree; editing the function body, its
    constants, or the values it closes over (``seq_len`` in a
    packing-transform factory, a numpy lookup table of any size) changes
    the fingerprint."""
    if func is None:
        return 'none'
    code = getattr(func, '__code__', None)
    if code is None:
        # partials / callable objects: best-effort over their visible state
        inner = getattr(func, 'func', None)
        if inner is not None and callable(inner):
            return _sha1('partial', callable_fingerprint(inner, _depth + 1),
                         _value_digest(getattr(func, 'args', ()), _depth),
                         _value_digest(getattr(func, 'keywords', None),
                                       _depth))
        state = vars(func) if hasattr(func, '__dict__') else {}
        return _sha1(type(func).__module__, type(func).__qualname__,
                     _value_digest(state, _depth))
    cells = []
    for cell in func.__closure__ or ():
        try:
            cells.append(_value_digest(cell.cell_contents, _depth + 1))
        except ValueError:  # empty cell
            cells.append('<empty>')
    return _sha1(_code_digest(code, _depth),
                 _value_digest(getattr(func, '__defaults__', None), _depth),
                 *cells)


def transform_fingerprint(spec):
    """Identity of a TransformSpec: the callable plus its declarative
    schema edits. None (no transform) has the stable identity 'none'."""
    if spec is None:
        return 'none'
    fields = [(f.name, repr(f.numpy_dtype), f.shape, f.nullable)
              for f in getattr(spec, 'edit_fields', ())]
    return _sha1(callable_fingerprint(getattr(spec, 'func', None)),
                 repr(fields), repr(getattr(spec, 'removed_fields', None)),
                 repr(getattr(spec, 'selected_fields', None)))


def _codec_fingerprint(codec):
    if codec is None:
        return 'plain'
    return _sha1(type(codec).__module__, type(codec).__qualname__,
                 repr(sorted(vars(codec).items())))


def schema_fingerprint(schema):
    """Identity of the loaded schema view: field names, dtypes, shapes
    and full codec configuration (quality, image format, …) — a codec
    parameter change decodes different bytes and must miss."""
    parts = []
    for name in sorted(schema.fields):
        f = schema.fields[name]
        parts.append('%s|%r|%r|%r|%s' % (f.name, f.numpy_dtype, f.shape,
                                         f.nullable,
                                         _codec_fingerprint(f.codec)))
    return _sha1(*parts)


def ngram_fingerprint(ngram):
    """Identity of an NGram configuration. It belongs in the key because
    the ngram's *length* changes the cached rows themselves: with
    ``shuffle_row_drop_partitions > 1`` each partition borrows
    ``length - 1`` overlap rows from the next (see
    ``arrow_worker._apply_row_drop``), so two jobs sharing a cache
    directory with different ngram shapes must not serve each other."""
    if ngram is None:
        return 'none'
    fields = {k: sorted(getattr(f, 'name', f) for f in v)
              for k, v in ngram.fields.items()}
    ts = ngram.timestamp_field
    return _sha1(repr(sorted(fields.items())),
                 repr(getattr(ts, 'name', ts)),
                 repr(ngram.delta_threshold),
                 repr(getattr(ngram, 'timestamp_overlap', None)))


def decode_fingerprint(loaded_schema, transform_spec, ngram=None):
    """The decode-identity half of a cache key: what was read+decoded
    (schema view incl. codecs), what transformed it, and the ngram shape
    (which leaks into the rows via the row-drop overlap)."""
    return _sha1(schema_fingerprint(loaded_schema),
                 transform_fingerprint(transform_spec),
                 ngram_fingerprint(ngram))


def dataset_file_fingerprint(dataset_info, path):
    """Identity of one parquet file's bytes (size + mtime when the
    filesystem provides them): rewriting the dataset in place invalidates
    its cached decoded row-groups."""
    try:
        info = dataset_info.fs.info(path)
        size = info.get('size')
        mtime = info.get('mtime') or info.get('LastModified')
        return '%s-%s' % (size, mtime)
    except Exception:  # noqa: BLE001 - exotic fs: fall back to path-only
        # counted: the path-only fallback weakens invalidation (a
        # rewritten file could serve stale rows), so it must be visible
        from petastorm_tpu.telemetry import count_swallowed
        count_swallowed('cache-fingerprint-stat')
        return 'nostat'


# -- Arrow IPC entry format --------------------------------------------------
#
# One IPC file per entry, holding ONE record batch with one large_binary
# column per decoded column (each a single cell: the column's raw flat
# bytes, or a pickle for object columns). Field metadata carries the
# numpy dtype + shape so the read path can np.frombuffer the cell's data
# buffer straight off the memory map — the arrays alias the mmap (their
# base chain holds the pyarrow Buffer), no allocation, no pickle.


def _column_payload(col):
    """``(kind, flat uint8 view-or-bytes, meta)`` for one decoded column."""
    if (isinstance(col, np.ndarray) and col.dtype.kind in _RAW_KINDS
            and col.dtype.itemsize):
        raw = np.ascontiguousarray(col)
        return ('raw', raw.view(np.uint8).reshape(-1),
                {b'kind': b'raw', b'dtype': col.dtype.str.encode(),
                 b'shape': json.dumps(list(col.shape)).encode()})
    payload = pickle.dumps(col, protocol=pickle.HIGHEST_PROTOCOL)
    # The view's .base holds the freshly pickled bytes (this frame's
    # only reference), so the caller owns the memory.  # pipesan: owns
    return ('pickle', np.frombuffer(payload, dtype=np.uint8),
            {b'kind': b'pickle'})


def write_entry(path, columns, length):
    """Serialize a decoded batch to ``path`` as one Arrow IPC file.
    Returns the file's size in bytes. Not atomic by itself — callers
    write to a tmp name and ``os.replace`` (see :meth:`~
    MaterializedRowGroupCache.get`)."""
    import pyarrow as pa
    fields, arrays = [], []
    for name, col in columns.items():
        _, data, meta = _column_payload(col)
        offsets = np.array([0, data.nbytes], dtype=np.int64)
        arrays.append(pa.Array.from_buffers(
            pa.large_binary(), 1,
            [None, pa.py_buffer(offsets), pa.py_buffer(data)]))
        fields.append(pa.field(name, pa.large_binary(), metadata=meta))
    schema = pa.schema(fields, metadata={
        _LENGTH_META: str(int(length)).encode(),
        _VERSION_META: _FORMAT_VERSION,
    })
    with pa.OSFile(path, 'wb') as sink:
        with pa.ipc.new_file(sink, schema) as writer:
            writer.write_batch(pa.RecordBatch.from_arrays(arrays,
                                                          schema=schema))
    return os.stat(path).st_size


def read_entry(path):
    """``(columns, length, mmap_columns, copy_columns)`` from an entry.

    EVERY returned column arrives ``writeable=False``: raw columns are
    ``np.frombuffer`` views whose base chain holds the IPC file's
    read-only memory-map buffer (zero-copy; the mmap stays alive exactly
    as long as any returned array), and pickle-fallback columns — fresh
    allocations that would otherwise come back writable — are explicitly
    frozen, because the same array objects are shared through the memory
    tier with every later hit: a consumer's in-place write must raise
    (``ValueError: assignment destination is read-only``, see
    docs/troubleshoot.md) instead of silently corrupting the shared
    entry. Raises on a malformed/truncated file — callers treat that as
    a miss and re-fill."""
    import pyarrow as pa
    source = pa.memory_map(path, 'r')
    reader = pa.ipc.open_file(source)
    meta = reader.schema.metadata or {}
    if meta.get(_VERSION_META) != _FORMAT_VERSION:
        raise ValueError('decoded-cache entry %s: unknown format version'
                         % path)
    length = int(meta[_LENGTH_META])
    batch = reader.get_batch(0)
    columns = {}
    mmap_columns = copy_columns = 0
    for i, field in enumerate(reader.schema):
        fmeta = field.metadata or {}
        cell = batch.column(i)
        if fmeta.get(b'kind') == b'raw':
            dtype = np.dtype(fmeta[b'dtype'].decode())
            shape = tuple(json.loads(fmeta[b'shape'].decode()))
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            # read-only by construction: the mmap buffer is immutable
            columns[field.name] = np.frombuffer(
                cell.buffers()[2], dtype=dtype, count=count).reshape(shape)
            mmap_columns += 1
        else:
            col = pickle.loads(cell[0].as_py())
            if isinstance(col, np.ndarray):
                col.flags.writeable = False
            columns[field.name] = col
            copy_columns += 1
    return columns, length, mmap_columns, copy_columns


class MaterializedRowGroupCache(CacheBase):
    """Decoded row-group cache: bounded memory tier over a size-bounded
    Arrow-IPC disk tier.

    The ``get`` contract stores/returns decoded
    :class:`~petastorm_tpu.arrow_worker.ColumnBatch` values (or None for
    row-groups the filter emptied — cached as a zero-length tombstone so
    warm epochs skip the re-read too). Safe across threads (internal
    lock) and across processes (atomic rename; pickling drops the lock
    and memory tier, so each pool worker gets a private hot tier over the
    one shared directory).

    :param path: cache directory (created if needed; stale tmp files of
        dead writers are purged at init).
    :param disk_limit_bytes: soft cap on the directory; least-recently-
        accessed entries are evicted when exceeded.
    :param mem_limit_bytes: cap of the in-memory tier (0 disables it).
    :param cleanup: remove the directory on :meth:`cleanup`.
    :param implicit_upgrade: True when this cache came from the
        fleet-wide ``PETASTORM_TPU_DECODED_CACHE=1`` upgrade rather than
        an explicit ``cache_type='decoded'``: the worker then refuses to
        cache TransformSpecs that never declared ``cacheable=True`` (the
        knob must not silently freeze an unmarked — possibly stochastic —
        transform's output).
    """

    def __init__(self, path, disk_limit_bytes, mem_limit_bytes=0,
                 cleanup=False, implicit_upgrade=False, **_unused):
        self._disk_limit = disk_limit_bytes
        self._mem_limit = mem_limit_bytes
        self._cleanup_on_exit = cleanup
        self.implicit_upgrade = implicit_upgrade
        self._lock = threading.Lock()
        self._mem = OrderedDict()   # key -> (columns, length, nbytes)
        self._mem_bytes = 0
        # degrade-don't-die state (docs/troubleshoot.md, "The decoded
        # cache degraded to decode-through"): a disk-fault errno (or a
        # run of consecutive store failures) disarms the DISK tier for
        # the rest of this process — reads decode through, the memory
        # tier keeps serving — instead of failing the epoch or paying a
        # failing syscall per row-group forever.
        self._degraded = False
        self._consecutive_failures = 0
        # fleet peer-fetch hook (service/peer_cache.py), armed only by
        # the service worker wiring; None = plain host-local cache
        self._peer = None
        self._utime_at = {}  # entry path -> monotonic time of last touch
        self._attach(path)

    def _attach(self, path):
        self._path = path
        os.makedirs(path, exist_ok=True)
        # one walk: purge dead writers' tmp files + total the entries
        self._total = attach_scan(path)

    @property
    def degraded(self):
        """True when the disk tier disarmed itself after disk faults."""
        return self._degraded

    def reroot(self, path):
        """Re-point the cache at a different directory (the service
        worker server's ``PETASTORM_TPU_DECODED_CACHE_DIR`` override, so
        every job landing on a host shares that host's local-SSD tier
        regardless of what directory the client baked into the spec).
        Re-arms a degraded tier: the fault belonged to the OLD medium."""
        with self._lock:
            self._mem.clear()
            self._mem_bytes = 0
        if self._degraded:
            # clear the stale telemetry too: a re-armed tier must not
            # keep reporting degraded=1 to /metrics and the fleet view
            self._registry().gauge(DECODED_CACHE_DEGRADED,
                                   pid=str(os.getpid())).set(0)
        self._degraded = False
        self._consecutive_failures = 0
        with self._lock:
            self._utime_at.clear()
        self._attach(path)
        # a re-rooted dir that holds no real entries must not keep
        # advertising placement fingerprints it no longer backs
        from petastorm_tpu.service.placement import purge_stale_markers
        purge_stale_markers(path)

    def __getstate__(self):
        # Crosses the process-pool/service spawn boundary: the lock can't
        # travel and the memory tier shouldn't (each worker builds its own
        # hot set; the disk directory is the shared tier).
        state = self.__dict__.copy()
        del state['_lock']
        state['_mem'] = OrderedDict()
        state['_mem_bytes'] = 0
        state['_peer'] = None    # the fetch client owns sockets
        state['_utime_at'] = {}
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        # entries pickled by a pre-fleet-tier build
        self.__dict__.setdefault('_peer', None)
        self.__dict__.setdefault('_utime_at', {})

    @property
    def path(self):
        return self._path

    def _entry_path(self, key):
        digest = hashlib.sha1(str(key).encode('utf-8')).hexdigest()
        return os.path.join(self._path, digest[:2], digest + '.arrow')

    @staticmethod
    def _registry():
        return get_registry()

    def _size_gauge(self):
        # per-process series over the ONE shared directory; aggregated
        # with max, never sum (see telemetry.export's cache sections)
        return self._registry().gauge(DECODED_CACHE_SIZE_BYTES,
                                      pid=str(os.getpid()))

    # -- memory tier ---------------------------------------------------------

    @staticmethod
    def _columns_nbytes(columns):
        return sum(col.nbytes for col in columns.values()
                   if isinstance(col, np.ndarray))

    def _mem_get(self, key):
        if not self._mem_limit:
            return None
        with self._lock:
            entry = self._mem.get(key)
            if entry is not None:
                self._mem.move_to_end(key)
            return entry

    def _mem_put(self, key, columns, length):
        if not self._mem_limit:
            return
        nbytes = self._columns_nbytes(columns)
        if nbytes > self._mem_limit:
            return  # a single oversized batch would just thrash the tier
        if sanitizer.sanitize_enabled():
            # the tier SHARES these array objects with every later hit
            # (and, on the fill path, with the batch just returned to the
            # consumer) — armed mode freezes them so an in-place write
            # raises at the write site instead of corrupting the entry.
            # AFTER the oversized bail-out: a batch the tier never stores
            # stays the consumer's own writable memory.
            sanitizer.guard_payload(columns)
        with self._lock:
            old = self._mem.pop(key, None)
            if old is not None:
                self._mem_bytes -= old[2]
            self._mem[key] = (columns, length, nbytes)
            self._mem_bytes += nbytes
            while self._mem_bytes > self._mem_limit and self._mem:
                _, (_, _, evicted) = self._mem.popitem(last=False)
                self._mem_bytes -= evicted

    # -- the cache contract --------------------------------------------------

    def get(self, key, fill_cache_func):
        from petastorm_tpu.arrow_worker import ColumnBatch
        registry = self._registry()
        entry = self._entry_path(key)
        hit = self._mem_get(key)
        if hit is not None:
            registry.counter(DECODED_CACHE_HITS).inc()
            registry.counter(DECODED_CACHE_MEM_HITS).inc()
            # LRU touch even on memory-tier hits: the backing disk
            # entry's atime is what eviction sorts by, and without it
            # the disk LRU would evict exactly the hot working set —
            # invisible to THIS process, devastating to every fresh
            # pool worker and co-trained job sharing the directory.
            # Rate-limited: coarse freshness is all the LRU needs.
            self._touch_entry(entry)
            columns, length, _ = hit
            return ColumnBatch(dict(columns), length) if length else None
        if not self._degraded:
            try:
                if faults.ARMED:
                    faults.fault_hit('cache.read', key=entry)
                # stat BEFORE the span: a plain miss must not record a
                # cache_hit_read call or bill its failed open as hit time
                # (that would inflate the hit_side term the cache-phase
                # verdict weighs decode time against)
                size = os.stat(entry).st_size
                with span('cache_hit_read'):
                    columns, length, mmaped, copied = read_entry(entry)
                os.utime(entry)  # LRU touch
                registry.counter(DECODED_CACHE_HITS).inc()
                registry.counter(DECODED_CACHE_BYTES_READ).inc(size)
                registry.counter(DECODED_CACHE_MMAP_READS).inc(mmaped)
                registry.counter(DECODED_CACHE_COPY_READS).inc(copied)
                self._consecutive_failures = 0
                self._mem_put(key, columns, length)
                # a fresh wrapper per hit: workers stamp item_index/epoch
                # on the returned batch, and concurrent hits of one key
                # (two epochs in flight on a thread pool) must not race
                # that write
                return (ColumnBatch(dict(columns), length) if length
                        else None)
            except OSError as e:
                # ENOENT is the plain miss; anything else is the MEDIUM
                # failing (EIO, EACCES after a remount, ...) — counted,
                # and disk-fault errnos degrade the tier
                if e.errno not in (None, errno.ENOENT):
                    self._note_disk_failure('read', e)
            except Exception:  # noqa: BLE001 - truncated/corrupt entry
                logger.warning('decoded cache entry %s unreadable; '
                               'refilling', entry, exc_info=True)
                registry.counter(DECODED_CACHE_DISK_FAILURES,
                                 op='corrupt').inc()
                self._remove_entry(entry)
        registry.counter(DECODED_CACHE_MISSES).inc()
        if self._peer is not None and not self._degraded:
            # fleet tier (docs/service.md, "Fleet cache tier"): a known
            # holder serves the finished entry at wire price before we
            # pay the decode; ANY failure returns None and the local
            # fill below proceeds — degraded is never wrong
            served = self._peer.fetch(key, entry, self)
            if served is not None:
                columns, length = served
                self._mem_put(key, columns, length)
                return (ColumnBatch(dict(columns), length) if length
                        else None)
        batch = fill_cache_func()
        columns = dict(batch.columns) if batch is not None else {}
        length = batch.length if batch is not None else 0
        if self._degraded:
            # decode-through: the memory tier still serves repeats, the
            # broken disk is never touched again this process
            self._mem_put(key, columns, length)
            return batch
        try:
            with span('cache_fill'):
                if faults.ARMED:
                    faults.fault_hit('cache.write', key=entry)
                size, replaced = publish_entry(
                    entry, lambda tmp: write_entry(tmp, columns, length))
            registry.counter(DECODED_CACHE_BYTES_WRITTEN).inc(size)
            with self._lock:
                self._total += size - replaced
                over_limit = self._total > self._disk_limit
            self._size_gauge().set(self._total)
            self._consecutive_failures = 0
            self._mem_put(key, columns, length)
            _notify_published(entry, size)
            if over_limit:
                self._maybe_evict()
        except (OSError, ValueError, pickle.PicklingError) as e:
            logger.warning('decoded cache failed to store %r', key,
                           exc_info=True)
            self._note_disk_failure('store', e)
        return batch

    def _note_disk_failure(self, op, exc):
        """Count one swallowed disk-tier failure; degrade to
        decode-through on medium-indicting errnos (immediately — the set
        depends on the operation, see the errno-set comments above) or a
        run of consecutive failures of any shape. Every swallow is
        visible: the counter carries the op, the anomaly event carries
        the cause."""
        self._registry().counter(DECODED_CACHE_DISK_FAILURES, op=op).inc()
        self._consecutive_failures += 1
        errno_ = getattr(exc, 'errno', None)
        immediate = (_STORE_FAULT_ERRNOS if op == 'store'
                     else _READ_FAULT_ERRNOS)
        if errno_ in immediate:
            self._degrade('%s failed with %s (%s)'
                          % (op, errno.errorcode.get(errno_, errno_), exc))
        elif self._consecutive_failures >= _CONSECUTIVE_FAILURE_LIMIT:
            self._degrade('%d consecutive disk-tier failures (last: %s)'
                          % (self._consecutive_failures, exc))

    def _degrade(self, reason):
        """Disarm the disk tier for the rest of this process and say so
        loudly ONCE: gauge, ``cache_degraded`` anomaly event (with its
        runbook), log. Reads decode through from here on — an epoch on a
        full disk finishes slower, it does not fail."""
        if self._degraded:
            return
        self._degraded = True
        self._registry().gauge(DECODED_CACHE_DEGRADED,
                               pid=str(os.getpid())).set(1)
        record_anomaly('cache_degraded',
                       detail={'path': self._path, 'reason': reason})
        logger.warning('Decoded cache at %s degraded to decode-through: '
                       '%s', self._path, reason)

    def _touch_entry(self, entry):
        """Rate-limited LRU touch of a disk entry backing a memory-tier
        hit (at most once per entry per :data:`_UTIME_INTERVAL_S`)."""
        now = time.monotonic()
        with self._lock:
            last = self._utime_at.get(entry)
            if last is not None and now - last < _UTIME_INTERVAL_S:
                return
            if len(self._utime_at) > _UTIME_TRACKED_CAP:
                self._utime_at.clear()  # worst case: one extra utime each
            self._utime_at[entry] = now
        try:
            os.utime(entry)
        except OSError:
            pass

    # -- the fleet peer tier -------------------------------------------------

    def attach_peer_client(self, client):
        """Arm the fleet peer-fetch path (service worker wiring): on a
        local disk miss, ``client.fetch`` is tried before the decode."""
        self._peer = client

    def publish_fetched(self, entry, write_func):
        """Publish a peer-fetched entry into the disk tier with the same
        atomic tmp+rename discipline and size/eviction accounting as a
        local fill — on disk the peer path must be indistinguishable.
        Returns the published size; raises on failure (the fetch path
        degrades to local decode)."""
        size, replaced = publish_entry(entry, write_func)
        self._registry().counter(DECODED_CACHE_BYTES_WRITTEN).inc(size)
        with self._lock:
            self._total += size - replaced
            over_limit = self._total > self._disk_limit
        self._size_gauge().set(self._total)
        _notify_published(entry, size)
        if over_limit:
            self._maybe_evict()
        return size

    def _remove_entry(self, entry):
        try:
            size = os.stat(entry).st_size
            os.remove(entry)
            with self._lock:
                self._total -= size
        except OSError:
            pass

    def _maybe_evict(self):
        # shared LRU walk, OUTSIDE the lock: _mem_get/_mem_put take the
        # same lock on every get, and an eviction pass over a large tier
        # must not stall pure memory-tier hits behind disk I/O. Removal
        # under a live mmap is safe (POSIX keeps the pages mapped).
        with self._lock:
            before = self._total
        total, evictions, _ = evict_lru(self._path, self._disk_limit)
        with self._lock:
            # merge, don't assign (see LocalDiskCache._maybe_evict): a
            # concurrent publish during the walk must not be lost —
            # over-counting only costs an extra self-correcting walk
            self._total = total + (self._total - before)
        if evictions:
            self._registry().counter(DECODED_CACHE_EVICTIONS).inc(evictions)
        self._size_gauge().set(self._total)

    def cleanup(self):
        if self._cleanup_on_exit:
            import shutil
            shutil.rmtree(self._path, ignore_errors=True)
            return
        # a kept directory must not advertise placement fingerprints for
        # entries that no longer exist (stale `.fp_` markers)
        from petastorm_tpu.service.placement import purge_stale_markers
        purge_stale_markers(self._path)
