"""pipesan runtime half: ASan-style guards for the zero-copy boundaries.

The static ``buffer-escape``/``buffer-write`` pass
(:mod:`petastorm_tpu.analysis.pass_buffers`) proves the *source* honors
the borrow contracts registered in ``analysis/contracts.py``; this module
catches what static analysis can't — a consumer (user transform, training
loop, third-party callback) mutating or outliving a borrowed view at
runtime. ``PETASTORM_TPU_SANITIZE=1`` (docs/env_knobs.md) arms three
guards at the three zero-copy boundaries:

* **Staging arena** (``jax/staging.py``): slot slabs are allocated with
  poisoned *red zones* (canary bytes) before and after the visible
  array — verified before every refill and re-poisoned on recycle, so a
  wild write through an escaped view is detected at the next cycle
  instead of silently corrupting a neighbor batch. A **weakref census**
  of the views handed to the dispatch records every consumer that still
  holds one when the slot comes up for recycling; the recycle is
  *aborted* (the slot gets fresh buffers, the escaped holder keeps the
  old memory — quarantine, like ASan's) and the escape is reported.
* **Decoded cache** (``materialized_cache.py``): memory-tier columns are
  forced ``writeable=False`` before they are shared, so an in-place
  consumer write raises ``ValueError: assignment destination is
  read-only`` at the write site instead of corrupting every later hit
  (disk-tier mmap columns are born read-only regardless of the knob).
* **ZMQ results channel** (``workers/process_pool.py`` +
  ``serializers.py``): receive frames are exposed as read-only
  memoryviews and the reconstructed out-of-band arrays are forced
  ``writeable=False`` — a consumer mutating a wire-buffer view raises
  instead of scribbling on ZMQ's receive buffers.

Violations the guards detect directly (canary trample, use-after-recycle)
are recorded in a bounded in-process ring, surfaced as the ``pipesan``
section of ``pipeline_report()`` and counted in the
``petastorm_tpu_sanitizer_*`` metrics; violations the guards *convert*
into exceptions (read-only writes) fail loudly in the consumer's own
stack, which is the point. Off (the default) every guard is a cheap
cached-boolean check resolved at engine/serializer construction — the
hot path pays nothing (the ``perf``-marked guard in
``tests/test_sanitizer.py`` holds this).
"""

import logging
import threading
import time
import weakref

import numpy as np

from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, register_refresh,
)

logger = logging.getLogger(__name__)

#: registry counters (docs/telemetry.md metric reference)
SANITIZER_VIOLATIONS = 'petastorm_tpu_sanitizer_violations_total'
SANITIZER_VIEWS_GUARDED = 'petastorm_tpu_sanitizer_views_guarded_total'
SANITIZER_CANARY_CHECKS = 'petastorm_tpu_sanitizer_canary_checks_total'

#: red-zone size on each side of a guarded slab. 64 bytes keeps any
#: numpy dtype's alignment intact for the visible region and is wide
#: enough that an off-by-one-row write cannot jump the zone.
CANARY_BYTES = 64

#: the poison pattern (0xA5 = alternating bits, unlikely fill value)
CANARY_BYTE = 0xA5

#: violation-ring bound: keeps the newest entries (oldest drop off), so
#: the ``recent`` slice of ``pipeline_report()['pipesan']`` stays recent
#: in a long soak; the per-kind counters carry the full totals
_RING_LIMIT = 50

# cached knob (refresh_sanitizer/telemetry.refresh re-reads)
_enabled = None

_lock = threading.Lock()
_violations = []


def sanitize_enabled():
    """True when ``PETASTORM_TPU_SANITIZE`` carries an enable spelling
    (off by default — the guards cost real per-batch work)."""
    global _enabled
    if _enabled is None:
        _enabled = knobs.is_enabled('PETASTORM_TPU_SANITIZE')
    return _enabled


def refresh_sanitizer():
    """Re-read the knob (tests, long-lived processes); engines resolve it
    at construction, so the next reader/loader pass sees the new value."""
    global _enabled
    _enabled = None


register_refresh(refresh_sanitizer)


def record_violation(kind, detail):
    """One sanitizer finding: counted per ``kind``, kept in the bounded
    ring for ``pipeline_report()['pipesan']``, and logged — never raised
    (a false positive must not kill a training job; the guards that CAN
    be precise raise in the consumer's stack instead)."""
    with _lock:
        _violations.append({'kind': kind, 'detail': detail,
                            'ts': time.time()})
        if len(_violations) > _RING_LIMIT:
            del _violations[:len(_violations) - _RING_LIMIT]
    if not metrics_disabled():
        get_registry().counter(SANITIZER_VIOLATIONS, kind=kind).inc()
    logger.warning('pipesan violation [%s]: %s', kind, detail)


def violations():
    """Snapshot of the recorded violations (oldest first)."""
    with _lock:
        return [dict(v) for v in _violations]


def reset_for_tests():
    """Clear the violation ring and the cached knob (test isolation; the
    metric counters live in the registry and reset with it)."""
    global _enabled
    with _lock:
        del _violations[:]
    _enabled = None


# -- read-only view guards ----------------------------------------------------


def guard_readonly(arr):
    """Force ``writeable=False`` on an ndarray; returns 1 when the flag
    was flipped (0 for non-arrays, already-read-only views, and the rare
    base that refuses)."""
    if isinstance(arr, np.ndarray) and arr.flags.writeable:
        try:
            arr.flags.writeable = False
            return 1
        except ValueError:
            return 0
    return 0


def guard_payload(value):
    """Force every reachable top-level ndarray in a result payload
    read-only: plain arrays, dicts/lists/tuples of them, and
    ``ColumnBatch``-shaped objects (a ``columns`` dict attribute).
    Returns the number of arrays guarded (also counted in the
    ``views_guarded`` metric)."""
    guarded = _guard_value(value, depth=0)
    if guarded and not metrics_disabled():
        get_registry().counter(SANITIZER_VIEWS_GUARDED).inc(guarded)
    return guarded


def _guard_value(value, depth):
    if depth > 3:  # payloads are shallow; never chase arbitrary graphs
        return 0
    if isinstance(value, np.ndarray):
        return guard_readonly(value)
    guarded = 0
    if isinstance(value, dict):
        for v in value.values():
            guarded += _guard_value(v, depth + 1)
        return guarded
    if isinstance(value, (list, tuple)):
        for v in value:
            guarded += _guard_value(v, depth + 1)
        return guarded
    columns = getattr(value, 'columns', None)
    if isinstance(columns, dict):
        return _guard_value(columns, depth + 1)
    return guarded


# -- red-zone (canary) slabs --------------------------------------------------


def allocate_guarded(shape, dtype):
    """An ``np.empty(shape, dtype)`` equivalent whose memory sits between
    two poisoned red zones inside one flat uint8 slab. The visible array
    is a view into the slab's middle; :func:`check_canaries` walks the
    ``.base`` chain back to the slab to verify the zones."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    slab = np.empty(nbytes + 2 * CANARY_BYTES, np.uint8)
    _poison(slab, nbytes)
    view = slab[CANARY_BYTES:CANARY_BYTES + nbytes].view(dtype)
    return view.reshape(shape)


def _poison(slab, nbytes):
    slab[:CANARY_BYTES] = CANARY_BYTE
    slab[CANARY_BYTES + nbytes:] = CANARY_BYTE


def _slab_of(arr):
    """The root uint8 slab of a guarded array (None when the array was
    not built by :func:`allocate_guarded`)."""
    root = arr
    while isinstance(getattr(root, 'base', None), np.ndarray):
        root = root.base
    if isinstance(root, np.ndarray) and root.dtype == np.uint8 \
            and root.ndim == 1 and root.nbytes >= 2 * CANARY_BYTES:
        return root
    return None


def check_canaries(arr, repoison=True):
    """True when both red zones around a guarded array still carry the
    poison pattern; trampled zones are re-poisoned (so the NEXT trample
    is caught too) when ``repoison``. Counted per check."""
    slab = _slab_of(arr)
    if slab is None:
        return True  # not a guarded slab (plain np.empty): nothing to say
    if not metrics_disabled():
        get_registry().counter(SANITIZER_CANARY_CHECKS).inc()
    nbytes = slab.nbytes - 2 * CANARY_BYTES
    intact = bool((slab[:CANARY_BYTES] == CANARY_BYTE).all()
                  and (slab[CANARY_BYTES + nbytes:] == CANARY_BYTE).all())
    if not intact and repoison:
        _poison(slab, nbytes)
    return intact


# -- escaped-view census ------------------------------------------------------


class ViewCensus:
    """Weakrefs of the views an arena slot handed out on its last
    dispatch. At recycle time, any ref still resolving means a consumer
    kept the view past the slot's documented lifetime — the classic
    use-after-recycle. Single-threaded like the staging engine itself."""

    __slots__ = ('_refs',)

    def __init__(self):
        self._refs = []

    def register(self, arrays):
        """Record this dispatch's outbound views (replaces the previous
        dispatch's refs — those were checked at the recycle gate)."""
        refs = []
        for arr in arrays:
            try:
                refs.append(weakref.ref(arr))
            except TypeError:  # non-weakref-able stand-in (tests, scalars)
                pass
        self._refs = refs

    def escaped(self):
        """How many of the registered views are still alive."""
        return sum(1 for ref in self._refs if ref() is not None)
