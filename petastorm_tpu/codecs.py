"""Field codecs: (de)serialize rich field values into Parquet-storable cells.

From-scratch re-design of ``petastorm/codecs.py`` with the same on-disk byte
formats (so datasets written by the reference and by this framework interop):

* :class:`CompressedImageCodec` — png/jpeg bytes as produced by OpenCV
  (``codecs.py:58-130``), RGB channel order at the API boundary.
* :class:`NdarrayCodec` — the ``np.save`` .npy byte stream (``codecs.py:133-171``).
* :class:`CompressedNdarrayCodec` — ``np.savez_compressed`` bytes (``codecs.py:174-212``).
* :class:`ScalarCodec` — plain typed parquet cells (``codecs.py:215-271``).

Differences from the reference, deliberately:

* Codecs declare an **arrow** storage type (:meth:`arrow_type`); Spark types
  are derived from arrow only when pyspark is installed.
* Every codec also implements :meth:`decode_batch`, a vectorized batch decode
  used by the TPU host pipeline (the reference decodes strictly row-by-row via
  ``utils.decode_row``). This is the seam where native/Pallas batched decoders
  plug in.
* Codecs are JSON-describable (``codec_to_json``/``codec_from_json``) for the
  versioned footer format, instead of being pickled with the schema.
"""

import logging
import os
import threading
from abc import ABCMeta, abstractmethod
from decimal import Decimal
from io import BytesIO

import numpy as np
import pyarrow as pa

from petastorm_tpu import faults
from petastorm_tpu.telemetry import knobs
from petastorm_tpu.unischema import numpy_to_arrow_type

logger = logging.getLogger(__name__)

_IMAGE_POOL = None
_IMAGE_POOL_DISABLED = object()
_IMAGE_POOL_LOCK = threading.Lock()

# decode_fn -> bool: does this native build accept the trailing `threads`
# argument? Probed once per function with a zero-length call (only a stale
# .so predating the argument can raise TypeError there).
_NATIVE_THREADS_SUPPORT = {}

# In-process override of the PETASTORM_TPU_IMAGE_DECODER_THREADS parse
# (None = the knob rules). The staging autotuner's adjustment seam
# (jax/autotune.py): it must retune THIS process without mutating
# os.environ — child processes inherit the environment, and a mid-run
# mutation would silently retarget every later reader in this process
# and poison A/B comparisons against the knob's documented value.
_DECODER_THREADS_OVERRIDE = None


def set_image_decoder_threads_override(value):
    """Set (int) or clear (None) the in-process decoder-thread override
    consumed by :func:`image_decoder_threads`. Owned by the staging
    autotuner; the loader clears it at stop so a tuned-down width never
    outlives the loader that learned it."""
    global _DECODER_THREADS_OVERRIDE
    _DECODER_THREADS_OVERRIDE = (None if value is None
                                 else max(0, int(value)))

# Calibrated jpeg chroma-upsampling mode (1 fancy / 0 merged), or None until
# the first sizeable batch decides it; see _jpeg_upsampling_mode.
_JPEG_FANCY_MODE = None
_JPEG_FANCY_LOCK = threading.Lock()
_JPEG_FANCY_ATTEMPTS = 0
_JPEG_FANCY_MAX_ATTEMPTS = 5


def _jpeg_mode_cache_path(decode_fn):
    """Per-host cache file for the calibrated mode, keyed by the native
    jpeg module build (path+size+mtime): the winner depends only on the
    libjpeg build linked into that .so, so caching it makes the pick
    stable run-to-run on a host instead of re-flipping on machine noise
    (advisor r4). Returns None when the build can't be identified."""
    import hashlib
    import sys
    import tempfile
    module = sys.modules.get(getattr(decode_fn, '__module__', None))
    so_path = getattr(module, '__file__', None)
    if not so_path:
        return None
    try:
        st = os.stat(so_path)
    except OSError:
        return None
    key = hashlib.md5(('%s:%d:%d' % (so_path, st.st_size, st.st_mtime_ns))
                      .encode('utf-8')).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(),
                        'petastorm_tpu_jpeg_fancy_%d_%s'
                        % (os.getuid(), key))


def _jpeg_upsampling_mode(decode_fn, cells, image_shape):
    """Pick the faster libjpeg chroma-upsampling mode for THIS host.

    Which of libjpeg's two 4:2:0 paths wins depends on the host's libjpeg
    build (turbo SIMD-vectorizes the fancy upsampler; its merged RGB path
    is scalar on some configurations — see ``native/jpeg_batch.c``), so
    instead of hardcoding a loser, time both modes once per process on the
    first real batch and cache the winner. Shared boxes drift by 2x over
    seconds, so the timing is INTERLEAVED (mode order alternating within
    each round, median per mode) — back-to-back per-mode loops would just
    measure which mode ran during the quiet period. A set (non-empty)
    ``PETASTORM_TPU_JPEG_FANCY`` disables calibration and defers to the C
    module's env parse (returns -1), preserving the bit-exactness escape
    hatch (=1 is bit-identical to cv2).

    Cost: ~8 x min(n, 8) single-image decodes, once per process. A wrong
    pick on pathological timing costs only decode rate, never correctness
    — both modes are faithful decodes of the same bytes.
    """
    global _JPEG_FANCY_MODE, _JPEG_FANCY_ATTEMPTS
    if knobs.raw('PETASTORM_TPU_JPEG_FANCY'):
        return -1
    if _JPEG_FANCY_MODE is not None:
        return _JPEG_FANCY_MODE
    if len(cells) < 4:
        return -1  # too small to time; env default, keep calibration open
    with _JPEG_FANCY_LOCK:
        if _JPEG_FANCY_MODE is not None:
            return _JPEG_FANCY_MODE
        cache_path = _jpeg_mode_cache_path(decode_fn)
        if cache_path is not None:
            try:
                with open(cache_path) as f:
                    cached = f.read().strip()
                if cached in ('0', '1'):
                    _JPEG_FANCY_MODE = int(cached)
                    logger.info(
                        'jpeg upsampling mode: %s (host cache %s)',
                        'fancy' if _JPEG_FANCY_MODE else 'merged',
                        cache_path)
                    return _JPEG_FANCY_MODE
            except OSError:
                pass
        import statistics
        import time
        sample = cells[:8]
        scratch = np.empty((len(sample),) + tuple(image_shape), np.uint8)
        try:
            # zero-length signature probe: ONLY a stale .so predating the
            # mode argument can raise TypeError here (oddball cells are
            # prefix-skipped by the C loop, never raised)
            decode_fn([], scratch[:0], 0)
        except TypeError:
            _JPEG_FANCY_MODE = -1  # env default forever
            return -1
        timings = {0: [], 1: []}
        for mode in (0, 1):
            decode_fn(sample, scratch, mode)  # warm (page-in, caches)
        for round_idx in range(3):
            order = (0, 1) if round_idx % 2 == 0 else (1, 0)
            for mode in order:
                start = time.perf_counter()
                done = decode_fn(sample, scratch, mode)
                timings[mode].append(time.perf_counter() - start)
                if done != len(sample):
                    # non-jpeg/oddball cells: timing would compare
                    # different work; env default for now, and retry on a
                    # later batch — but only a bounded number of times
                    # (a dataset whose every batch leads with an oddball
                    # must not pay a calibration attempt per batch)
                    _JPEG_FANCY_ATTEMPTS += 1
                    if _JPEG_FANCY_ATTEMPTS >= _JPEG_FANCY_MAX_ATTEMPTS:
                        _JPEG_FANCY_MODE = -1
                    return -1
        medians = {m: statistics.median(t) for m, t in timings.items()}
        _JPEG_FANCY_MODE = min(medians, key=medians.get)
        # INFO so run artifacts record which mode produced the pixels
        # (the two modes are both faithful decodes but not bit-identical)
        logger.info(
            'jpeg upsampling calibrated: %s (merged %.1f img/s, fancy '
            '%.1f img/s)', 'fancy' if _JPEG_FANCY_MODE else 'merged',
            len(sample) / medians[0], len(sample) / medians[1])
        if cache_path is not None:
            try:
                tmp_path = cache_path + '.%d' % os.getpid()
                with open(tmp_path, 'w') as f:
                    f.write(str(_JPEG_FANCY_MODE))
                os.replace(tmp_path, cache_path)
            except OSError:
                pass  # stability cache only; calibration already decided
        return _JPEG_FANCY_MODE


def _native_supports_threads(decode_fn, out, prefix_args):
    """True when this native build's batch decoder accepts the trailing
    ``threads`` argument (probed once per function with a zero-length
    call; a stale ``.so`` predating the argument raises TypeError and is
    routed to the Python-side chunking fallback)."""
    ok = _NATIVE_THREADS_SUPPORT.get(decode_fn)
    if ok is None:
        try:
            decode_fn([], out[:0], *(tuple(prefix_args) + (1,)))
            ok = True
        except TypeError:
            ok = False
        _NATIVE_THREADS_SUPPORT[decode_fn] = ok
    return ok


def image_decoder_threads():
    """Decode-parallelism width from ``PETASTORM_TPU_IMAGE_DECODER_THREADS``
    (0/1 = serial; default min(4, cpu_count)) — the ONE owner of the
    parse. The SAME number sizes whichever pool actually runs a given
    batch: the native batch decoders' internal C-level pthread pool (one
    native call per row-group column, GIL released) when the C extensions
    are live, or the Python-side cv2 executor
    (:func:`_image_decode_pool`) on the fallback path. The two pools
    never stack on ONE batch (no threads × threads within a decode);
    concurrent reader workers each get their own width, so process-wide
    decode threads scale as workers × knob — sizing guidance in
    docs/env_knobs.md. The staging autotuner may override the parsed
    value in-process (:func:`set_image_decoder_threads_override`)."""
    if _DECODER_THREADS_OVERRIDE is not None:
        return _DECODER_THREADS_OVERRIDE
    return image_decoder_threads_from_knob()


def image_decoder_threads_from_knob():
    """The knob's own parsed value, ignoring any in-process override —
    the autotuner's restore ceiling (a tuner constructed while another
    loader's override is live must not mistake the tuned-down width for
    the configured baseline)."""
    raw = knobs.raw('PETASTORM_TPU_IMAGE_DECODER_THREADS')
    if raw is None:
        return min(4, os.cpu_count() or 1)
    try:
        return max(0, int(raw))
    except ValueError:
        logger.warning(
            'PETASTORM_TPU_IMAGE_DECODER_THREADS=%r is not an '
            'integer; threaded image decode disabled', raw)
        return 0


def _image_decode_pool():
    """Shared small thread pool for batched cv2 image decode, or None.

    cv2 releases the GIL, so a few threads give real parallelism on top of
    the reader's own worker parallelism without oversubscribing. Sized by
    :func:`image_decoder_threads`; only the cv2 fallback path uses it —
    the native decoders parallelize inside the C call instead.
    """
    global _IMAGE_POOL
    if _IMAGE_POOL is _IMAGE_POOL_DISABLED:
        return None
    if _IMAGE_POOL is None:
        with _IMAGE_POOL_LOCK:
            if _IMAGE_POOL is _IMAGE_POOL_DISABLED:
                return None
            if _IMAGE_POOL is None:
                workers = image_decoder_threads()
                if workers <= 1:
                    _IMAGE_POOL = _IMAGE_POOL_DISABLED
                    return None
                from concurrent.futures import ThreadPoolExecutor
                _IMAGE_POOL = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix='img-decode')
    return _IMAGE_POOL


class DataframeColumnCodec(metaclass=ABCMeta):
    """Abstract codec contract (reference: ``petastorm/codecs.py:36-55``)."""

    @abstractmethod
    def encode(self, unischema_field, value):
        """Encode a single value into its parquet-storable form."""

    @abstractmethod
    def decode(self, unischema_field, encoded):
        """Decode a single stored cell back into its numpy form."""

    def decode_batch(self, unischema_field, encoded_iterable, out=None):
        """Decode many cells; default is a python loop, codecs may vectorize.

        ``out=`` (the fused-decode destination API, docs/telemetry.md):
        a preallocated ``(n,) + shape`` array the decoded rows land in —
        page-aligned column slabs from the row-group worker, or staging-
        arena slot views from the JAX loader's fused fill. When given it
        is filled IN PLACE and returned; a cell whose decoded shape/dtype
        cannot land in its row raises instead of silently degrading to a
        list (callers gate ``out=`` on fixed-shape fields).
        """
        values = [self.decode(unischema_field, v) for v in encoded_iterable]
        if out is None:
            return values
        for i, value in enumerate(values):
            _assign_row(out, i, value, unischema_field)
        return out

    @abstractmethod
    def arrow_type(self, unischema_field):
        """The arrow DataType of the stored column."""

    def spark_dtype(self, unischema_field):
        """Spark type of the stored column (requires pyspark)."""
        return arrow_to_spark_type(self.arrow_type(unischema_field))

    # JSON description for the versioned footer
    def to_json_dict(self):
        return {'type': type(self).__name__}


def _check_out_destination(unischema_field, out, n):
    """The ONE validation of a ``decode_batch(out=)`` destination: the
    field must be fixed-shape and ``out`` must be exactly ``(n,) + shape``
    in the field's dtype (both vectorizing codecs share this — the fused
    contract must not fork between them)."""
    shape = unischema_field.shape
    if not shape or any(d is None for d in shape):
        raise ValueError(
            'decode_batch(out=) requires a fixed-shape field; %r '
            'has shape %r' % (unischema_field.name, shape))
    expected = (n,) + tuple(shape)
    dtype = np.dtype(unischema_field.numpy_dtype)
    if out.shape != expected or out.dtype != dtype:
        raise ValueError(
            'decode_batch(out=): destination %s %s does not match '
            'the declared %s %s' % (out.shape, out.dtype, expected, dtype))


def _assign_row(out, i, value, unischema_field):
    """One decoded cell into row ``i`` of a caller-owned destination.
    The shape must match EXACTLY — plain ``out[i] = value`` would happily
    numpy-BROADCAST a smaller cell across the row (silent replicated
    data, where the no-``out`` path preserved the true shape and
    surfaced the raggedness downstream); dtype follows numpy assignment
    casting, same as the codecs' own ``astype`` to the declared dtype."""
    value = np.asarray(value)
    if value.shape != out.shape[1:]:
        raise ValueError(
            'decode_batch(out=): field %r cell decoded to shape %s, not '
            'the declared %s' % (unischema_field.name, value.shape,
                                 out.shape[1:]))
    out[i] = value


def decode_batch_with_nulls(unischema_field, values, out=None):
    """Batch-decode a column whose cells may be None (nullable fields): null
    cells bypass the codec and stay None, non-null cells go through the
    codec's vectorized ``decode_batch``. Positions are preserved.

    Returns either a list (one entry per cell, None preserved) or — on the
    all-non-null fast path — whatever the codec's ``decode_batch`` returned,
    which may be a contiguous ``(n,)+shape`` ndarray.

    ``out=`` (fused-decode destination): rows decode straight into the
    caller's preallocated ``(n,) + shape`` slab — contiguous runs of
    non-null cells each go through ONE vectorized ``decode_batch(out=)``
    call on the matching slab slice, and null positions are explicitly
    ZERO-FILLED (never left as uninitialized or previous-slot bytes —
    the slab may be a recycled staging-arena slot whose stale pixels
    would otherwise leak into "null" rows). Returns ``out``.
    """
    if faults.ARMED:
        # the one decode seam every path shares — native batch, cv2
        # fallback, fused-into-slot — so an injected "poisoned cell"
        # exercises whichever decoder actually runs
        faults.fault_hit('decode.batch',
                         key=getattr(unischema_field, 'name', None))
    if out is not None:
        codec = unischema_field.codec
        n = len(values)
        i = 0
        while i < n:
            if values[i] is None:
                j = i
                while j < n and values[j] is None:
                    j += 1
                out[i:j] = 0
                i = j
            else:
                j = i
                while j < n and values[j] is not None:
                    j += 1
                codec.decode_batch(unischema_field, values[i:j],
                                   out=out[i:j])
                i = j
        return out
    non_null_idx = [i for i, v in enumerate(values) if v is not None]
    if len(non_null_idx) == len(values):
        return unischema_field.codec.decode_batch(unischema_field, values)
    decoded = unischema_field.codec.decode_batch(
        unischema_field, [values[i] for i in non_null_idx])
    result = [None] * len(values)
    for slot, i in enumerate(non_null_idx):
        result[i] = decoded[slot]
    return result


class CompressedImageCodec(DataframeColumnCodec):
    """Store uint8/uint16 images as png or jpeg bytes.

    Byte-compatible with the reference codec (``petastorm/codecs.py:58-130``):
    images are RGB at the API boundary and channel-swapped to OpenCV's BGR for
    encode/decode of 3-channel images.

    .. note:: **jpeg decode determinism.** ``decode_batch`` prefers the
       first-party native decoder, whose DEFAULT chroma-upsampling mode
       (merged vs fancy) is auto-calibrated once per process to whichever
       this host decodes faster (see ``_jpeg_upsampling_mode``); per-cell
       ``decode`` and any fallback rows go through cv2, which always uses
       fancy upsampling. The two modes differ by small
       chroma-interpolation deltas (quality vs source within 0.2 dB
       PSNR), so in the default mode decoded pixels can vary with the
       path taken — across hosts (native build present or not, and which
       mode calibration picked) and across rows of one batch
       (oddball-cell fallback). Pipelines that need bit-identical decode
       everywhere should set env ``PETASTORM_TPU_JPEG_FANCY=1``, which
       forces fancy upsampling and makes the native path bit-identical to
       cv2 (provided the DCT method stays at its ``islow`` default —
       ``PETASTORM_TPU_JPEG_DCT=ifast`` trades that bit-identity away).
       png decode is lossless and path-independent either way.
    """

    def __init__(self, image_codec='png', quality=80):
        if image_codec not in ('png', 'jpeg', 'jpg'):
            raise ValueError('Unsupported image codec: %r' % image_codec)
        self._image_codec = '.' + image_codec
        self._quality = quality

    @property
    def image_codec(self):
        return self._image_codec[1:]

    def encode(self, unischema_field, value):
        import cv2
        if unischema_field.numpy_dtype != value.dtype:
            raise ValueError('Field %r dtype %s != value dtype %s'
                             % (unischema_field.name, unischema_field.numpy_dtype, value.dtype))
        if not unischema_field.is_shape_compliant(value.shape):
            raise ValueError('Field %r: image shape %s does not match %s'
                             % (unischema_field.name, value.shape, unischema_field.shape))
        if value.ndim == 3 and value.shape[2] not in (3, 4):
            raise ValueError('Field %r: images must be 2-d, HxWx3 or HxWx4; got shape %s'
                             % (unischema_field.name, value.shape))
        if value.ndim == 3:
            # cv2.cvtColor is SIMD-vectorized; numpy fancy-index channel
            # swaps cost ~25% of total decode throughput (measured).
            code = (cv2.COLOR_RGB2BGR if value.shape[2] == 3
                    else cv2.COLOR_RGBA2BGRA)
            bgr = cv2.cvtColor(np.ascontiguousarray(value), code)
        else:
            bgr = value
        params = ([int(cv2.IMWRITE_JPEG_QUALITY), self._quality]
                  if self._image_codec in ('.jpeg', '.jpg') else [])
        ok, encoded = cv2.imencode(self._image_codec, bgr, params)
        if not ok:
            raise RuntimeError('cv2.imencode failed for field %r' % unischema_field.name)
        return bytearray(encoded)

    @staticmethod
    def _as_uint8(encoded):
        """Cell bytes as a uint8 array, zero-copy for buffer views."""
        if isinstance(encoded, np.ndarray) and encoded.dtype == np.uint8:
            return encoded
        return np.frombuffer(bytes(encoded), dtype=np.uint8)

    @staticmethod
    def _is_3_channel(raw):
        """Header sniff: True only when the stored image provably has 3
        color components. Guards the direct-RGB decode fast path — forcing
        RGB onto a grayscale cell would silently colorize it instead of
        surfacing the shape mismatch."""
        if len(raw) < 26:
            return False
        head = raw[:4].tobytes()
        if head.startswith(b'\x89PNG'):
            # IHDR color type 2 = RGB triple; bit depth must be 8 — 16-bit
            # PNGs downscale by >>8 under forced-RGB decode but cast mod-256
            # under decode(), a silent value divergence
            return raw[25] == 2 and raw[24] == 8
        if head.startswith(b'\xff\xd8'):  # JPEG: scan for an SOF marker
            i = 2
            n = len(raw)
            while i + 9 < n:
                if raw[i] != 0xFF:
                    return False
                marker = int(raw[i + 1])
                if marker in (0xD8, 0x01) or 0xD0 <= marker <= 0xD7:
                    i += 2
                    continue
                if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
                    # precision must be 8 bits; component count 3
                    return int(raw[i + 4]) == 8 and int(raw[i + 9]) == 3
                seg_len = (int(raw[i + 2]) << 8) | int(raw[i + 3])
                if seg_len < 2:
                    return False
                i += 2 + seg_len
        return False

    def decode(self, unischema_field, encoded):
        import cv2
        image = cv2.imdecode(self._as_uint8(encoded), cv2.IMREAD_UNCHANGED)
        if image is None:
            raise ValueError('cv2.imdecode failed for field %r' % unischema_field.name)
        if image.ndim == 3 and image.shape[2] in (3, 4):
            code = (cv2.COLOR_BGR2RGB if image.shape[2] == 3
                    else cv2.COLOR_BGRA2RGBA)
            image = cv2.cvtColor(image, code)
        # Every branch above leaves `image` a buffer cv2 freshly
        # allocated for THIS call (imdecode or cvtColor); when no cast is
        # needed astype(copy=False) returns that same owned array, so
        # ownership transfers cleanly to the caller.  # pipesan: owns
        return image.astype(unischema_field.numpy_dtype, copy=False)

    def _decode_into(self, unischema_field, encoded, dst):
        """Decode one cell directly into a row of a preallocated batch:
        cvtColor writes into ``dst`` (no intermediate copy). Raises on any
        shape/decode surprise so the caller can fall back."""
        import cv2
        raw = self._as_uint8(encoded)
        if (dst.ndim == 3 and dst.shape[2] == 3 and dst.dtype == np.uint8
                and hasattr(cv2, 'IMREAD_COLOR_RGB')
                and self._is_3_channel(raw)):
            # decode straight to RGB: saves the whole-image BGR→RGB pass
            # (bit-identical; the flag exists since OpenCV 4.10). EXIF
            # orientation must be ignored — decode()'s IMREAD_UNCHANGED
            # never applies it, and a silently rotated batch would diverge.
            image = cv2.imdecode(
                raw, cv2.IMREAD_COLOR_RGB | cv2.IMREAD_IGNORE_ORIENTATION)
            if image is None:
                raise ValueError('cv2.imdecode failed for field %r'
                                 % unischema_field.name)
            if image.shape != dst.shape:
                raise ValueError('decoded shape %s != declared %s'
                                 % (image.shape, dst.shape))
            dst[...] = image
            return
        image = cv2.imdecode(raw, cv2.IMREAD_UNCHANGED)
        if image is None:
            raise ValueError('cv2.imdecode failed for field %r' % unischema_field.name)
        if image.shape != dst.shape:
            raise ValueError('decoded shape %s != declared %s'
                             % (image.shape, dst.shape))
        if image.ndim == 3 and image.shape[2] in (3, 4):
            code = (cv2.COLOR_BGR2RGB if image.shape[2] == 3
                    else cv2.COLOR_BGRA2RGBA)
            if dst.dtype == image.dtype:
                cv2.cvtColor(image, code, dst=dst)
            else:
                dst[...] = cv2.cvtColor(image, code)
        else:
            dst[...] = image

    def decode_batch(self, unischema_field, encoded_iterable, out=None):
        """Batched decode with a threaded cv2 fan-out for fixed-shape fields.

        cv2.imdecode releases the GIL, so decoding cells on a small shared
        thread pool runs truly in parallel; results land directly in one
        preallocated contiguous ``(n,)+shape`` array (no per-cell ndarray
        retained + no later np.stack copy — downstream collation passes the
        dense batch through). Wildcard-shaped fields and any decode surprise
        (bad bytes, shape mismatch) fall back to the sequential per-cell
        path, which preserves reference semantics exactly.

        ``out=`` selects the fused-decode destination contract: the rows
        decode straight into the caller's buffer (a page-aligned column
        slab or a staging-arena slot view), it must be ``(n,) + shape`` in
        the field's dtype, and decode surprises RAISE instead of falling
        back to a list — the caller owns the buffer's lifecycle and a
        silent shape change would corrupt it.

        SURVEY §7.3 calls jpeg/png decode throughput the place the
        north-star input rate is won or lost; this is the corresponding
        hot-loop (reference equivalent: ``petastorm/codecs.py:102-130``,
        one cv2 call per row with no batch seam at all).
        """
        cells = encoded_iterable if isinstance(encoded_iterable, list) \
            else list(encoded_iterable)
        shape = unischema_field.shape
        n = len(cells)
        if out is not None:
            _check_out_destination(unischema_field, out, n)
            self._decode_dense(unischema_field, cells, out)
            return out
        if n >= 4 and shape and not any(d is None for d in shape):
            try:
                dense = np.empty((n,) + tuple(shape),
                                 dtype=unischema_field.numpy_dtype)
                self._decode_dense(unischema_field, cells, dense)
                return dense
            except Exception:  # noqa: BLE001 - dense path is an accelerator
                logger.debug('Dense batched image decode failed; falling back '
                             'to the per-cell path', exc_info=True)
        return [self.decode(unischema_field, v) for v in cells]

    def _decode_dense(self, unischema_field, cells, out):
        """Decode every cell into its row of ``out``; raises on any decode
        surprise (the no-``out`` caller catches and falls back). The
        Python-side cv2 executor is consulted only AFTER the native path
        declined — on the native one-call path it is never even created
        (the one-pool contract of docs/env_knobs.md)."""
        if self._native_image_batch(unischema_field, cells, out):
            return
        pool = _image_decode_pool()
        if pool is None:
            for i in range(len(cells)):
                self._decode_into(unischema_field, cells[i], out[i])
        else:
            list(pool.map(
                lambda i: self._decode_into(unischema_field,
                                            cells[i], out[i]),
                range(len(cells))))

    def _native_image_batch(self, unischema_field, cells, out):
        """Decode an image batch with the first-party native loops
        (``native/jpeg_batch.c`` / ``native/png_batch.c``); True when
        ``out`` is fully populated.

        One C call decodes the whole batch RGB-direct into ``out`` with the
        GIL released, without per-cell Python dispatch or Mat allocation.
        png is bit-identical to the cv2 path (PNG stores RGB natively).
        jpeg chroma upsampling is auto-calibrated per process — merged vs
        fancy, whichever THIS host decodes faster (the winner is
        host-dependent; see ``_jpeg_upsampling_mode``); the two differ
        only in chroma interpolation (within 0.2 dB PSNR vs the source).
        Set env ``PETASTORM_TPU_JPEG_FANCY=1`` to force fancy, which is
        bit-identical-to-cv2 output (both ride libjpeg-turbo; see
        ``native/jpeg_batch.c``; requires the default ``islow`` DCT — not
        ``PETASTORM_TPU_JPEG_DCT=ifast``), or ``=0`` to force merged.

        Parallelism is ONE pool, never two (docs/env_knobs.md): with
        ``PETASTORM_TPU_IMAGE_DECODER_THREADS`` > 1 and a current native
        build, the whole column goes down in a SINGLE native call whose
        internal C-level pthread pool fans the cells out (no Python task
        churn, no GIL round trips between chunks); only a stale ``.so``
        predating the ``threads`` argument falls back to chunking the
        batch across the shared Python executor. Cells the native loop
        rejects (not a 3-component 8-bit image of the declared shape)
        finish through ``_decode_into``, whose failures propagate to the
        caller's sequential fallback.
        """
        if out.dtype != np.uint8 or out.ndim != 4 or out.shape[3] != 3:
            return False
        if self._image_codec in ('.jpeg', '.jpg'):
            from petastorm_tpu.native import get_jpeg_module
            native_mod = get_jpeg_module()
            decode_fn = getattr(native_mod, 'decode_jpeg_batch', None)
            if decode_fn is None:
                return False
            mode = _jpeg_upsampling_mode(decode_fn, cells, out.shape[1:])
            # the jpeg threads argument is positional AFTER the mode, so
            # the threaded call always names the mode explicitly (-1 =
            # the C env-default contract); the chunked fallback keeps the
            # historical arity for stale builds
            threaded_prefix = (mode,)
            decode_args = (mode,) if mode >= 0 else ()
        elif self._image_codec == '.png':
            from petastorm_tpu.native import get_png_module
            native_mod = get_png_module()
            decode_fn = getattr(native_mod, 'decode_png_batch', None)
            if decode_fn is None:
                return False
            threaded_prefix = ()
            decode_args = ()
        else:
            return False

        def run(lo, hi, call_args):
            # prefix-count contract: decode natively, route ONLY the
            # rejected cell through the generic path, then re-enter the
            # native loop on the tail (one oddball must not demote the
            # whole remaining chunk to per-cell decode)
            while lo < hi:
                done = decode_fn(cells[lo:hi], out[lo:hi], *call_args)
                lo += done
                if lo < hi:
                    self._decode_into(unischema_field, cells[lo], out[lo])
                    lo += 1

        n = len(cells)
        threads = image_decoder_threads()
        if threads > 1 and _native_supports_threads(decode_fn, out,
                                                    threaded_prefix):
            # ONE native call: the C pool fans the whole row-group column
            # out with the GIL released. The Python executor is NOT also
            # engaged (nor created) — the knob sizes exactly one pool per
            # batch.
            run(0, n, threaded_prefix + (threads,))
            return True
        # only the chunked fallback (stale .so / serial knob) consults
        # the Python-side executor into existence
        pool = _image_decode_pool()
        workers = getattr(pool, '_max_workers', 0) if pool is not None else 0
        if workers > 1 and n >= 2 * workers:
            chunk = -(-n // workers)
            bounds = [(lo, min(lo + chunk, n))
                      for lo in range(0, n, chunk)]
            list(pool.map(lambda b: run(b[0], b[1], decode_args), bounds))
        else:
            run(0, n, decode_args)
        return True

    def arrow_type(self, unischema_field):
        return pa.binary()

    def to_json_dict(self):
        return {'type': 'CompressedImageCodec',
                'image_codec': self.image_codec, 'quality': self._quality}


class NdarrayCodec(DataframeColumnCodec):
    """Store any numpy ndarray as .npy bytes (``petastorm/codecs.py:133-171``)."""

    def encode(self, unischema_field, value):
        _check_ndarray(unischema_field, value)
        buf = BytesIO()
        np.save(buf, value, allow_pickle=False)
        return bytearray(buf.getvalue())

    def decode(self, unischema_field, encoded):
        arr = np.load(BytesIO(bytes(encoded)), allow_pickle=False)
        return arr

    def decode_batch(self, unischema_field, encoded_iterable, out=None):
        """Fixed-shape numeric fields take the native batched decoder (one C
        call parsing all headers then memcpy-ing every payload with the GIL
        released — fanned across the internal pthread pool when
        ``PETASTORM_TPU_IMAGE_DECODER_THREADS`` > 1); anything else —
        wildcard dims, strings, or cells the native parser rejects — flows
        through the per-cell Python path. ``out=`` decodes into the
        caller's preallocated slab (fused-decode destination contract:
        fixed-shape fields only; surprises raise)."""
        cells = list(encoded_iterable)
        shape = unischema_field.shape
        if out is not None and not cells:
            return out
        fixed = bool(cells) and bool(shape) \
            and not any(d is None for d in shape)
        try:
            dtype = np.dtype(unischema_field.numpy_dtype)
        except TypeError:
            dtype = None
        if not fixed or dtype is None or dtype.kind not in 'iufb':
            if out is not None:
                raise ValueError(
                    'decode_batch(out=) requires a fixed-shape numeric '
                    'field; %r has shape %r' % (unischema_field.name, shape))
            return super().decode_batch(unischema_field, cells)
        dense = out
        if dense is not None:
            _check_out_destination(unischema_field, dense, len(cells))
        from petastorm_tpu.native import get_native_module
        native = get_native_module()
        if native is None:
            if dense is not None:
                return super().decode_batch(unischema_field, cells,
                                            out=dense)
            return super().decode_batch(unischema_field, cells)
        if dense is None:
            dense = np.empty((len(cells),) + shape, dtype=dtype)
        # numpy's header writer emits the shape tuple with canonical repr
        # spacing ("'shape': (2, 3)"), so an exact substring match rejects
        # any cell whose true shape differs from the declared one even when
        # the byte counts coincide (e.g. (3,2) vs (2,3)); rejected cells
        # fall back to the Python path, which preserves the true shape.
        shape_str = "'shape': %r" % (tuple(int(d) for d in shape),)
        threads = image_decoder_threads()
        try:
            done = native.decode_npy_batch(cells, dense, dtype.str,
                                           shape_str, threads)
        except TypeError:  # stale .so predating the threads argument
            done = native.decode_npy_batch(cells, dense, dtype.str,
                                           shape_str)
        if done == len(cells):
            # Return the contiguous batch itself: downstream collation
            # (arrow_worker._stack) passes it through, avoiding a second
            # full-batch copy via np.stack.
            return dense
        if out is not None:
            # fused destination: the rejected tail decodes per-cell into
            # its rows; a true-shape mismatch raises (the caller owns the
            # buffer and a silent broadcast would corrupt it)
            for i in range(done, len(cells)):
                _assign_row(out, i, self.decode(unischema_field, cells[i]),
                            unischema_field)
            return out
        rows = list(dense[:done])
        rows.extend(self.decode(unischema_field, c) for c in cells[done:])
        return rows

    def arrow_type(self, unischema_field):
        return pa.binary()


class CompressedNdarrayCodec(DataframeColumnCodec):
    """Store a numpy ndarray zlib-compressed (``petastorm/codecs.py:174-212``)."""

    def encode(self, unischema_field, value):
        _check_ndarray(unischema_field, value)
        buf = BytesIO()
        np.savez_compressed(buf, arr=value)
        return bytearray(buf.getvalue())

    def decode(self, unischema_field, encoded):
        with np.load(BytesIO(bytes(encoded)), allow_pickle=False) as npz:
            return npz['arr']

    def arrow_type(self, unischema_field):
        return pa.binary()


class ScalarCodec(DataframeColumnCodec):
    """Store a scalar as a typed parquet cell (``petastorm/codecs.py:215-271``).

    The reference parameterizes this codec with a Spark type; here it is
    parameterized with an **arrow** type (a numpy dtype or a Spark type are
    also accepted and converted), keeping Spark optional.
    """

    def __init__(self, storage_type):
        self._arrow_type = _as_arrow_type(storage_type)

    def encode(self, unischema_field, value):
        at = self._arrow_type
        if pa.types.is_integer(at):
            return int(value)
        if pa.types.is_floating(at):
            return float(value)
        if pa.types.is_boolean(at):
            return bool(value)
        if pa.types.is_string(at) or pa.types.is_large_string(at):
            if isinstance(value, Decimal):
                return str(value)
            if isinstance(value, bytes):
                return value.decode('utf-8')
            return str(value)
        if pa.types.is_binary(at) or pa.types.is_large_binary(at):
            return bytes(value)
        if pa.types.is_decimal(at):
            return Decimal(str(value))
        if pa.types.is_timestamp(at) or pa.types.is_date(at):
            return value
        raise ValueError('ScalarCodec: unsupported storage type %s' % at)

    def decode(self, unischema_field, encoded):
        if unischema_field.numpy_dtype is Decimal:
            return Decimal(encoded)
        return unischema_field.numpy_dtype(encoded)

    def decode_batch(self, unischema_field, encoded_iterable):
        if unischema_field.numpy_dtype is Decimal:
            return [Decimal(v) for v in encoded_iterable]
        return np.asarray(list(encoded_iterable)).astype(unischema_field.numpy_dtype)

    def arrow_type(self, unischema_field):
        return self._arrow_type

    def to_json_dict(self):
        return {'type': 'ScalarCodec', 'arrow_type': str(self._arrow_type)}


def _check_ndarray(unischema_field, value):
    if not isinstance(value, np.ndarray):
        raise ValueError('Field %r: expected ndarray, got %s'
                         % (unischema_field.name, type(value)))
    want = np.dtype(unischema_field.numpy_dtype)
    # Flexible dtypes (str/bytes) carry an item length; compare by kind only.
    matches = (want.kind == value.dtype.kind if want.kind in 'SU'
               else want == value.dtype)
    if not matches:
        raise ValueError('Field %r dtype %s != value dtype %s'
                         % (unischema_field.name, unischema_field.numpy_dtype, value.dtype))
    if not unischema_field.is_shape_compliant(value.shape):
        raise ValueError('Field %r: shape %s does not match %s'
                         % (unischema_field.name, value.shape, unischema_field.shape))


# ---------------------------------------------------------------------------
# storage-type conversions
# ---------------------------------------------------------------------------

_ARROW_TYPE_PARSERS = {
    'bool': pa.bool_, 'int8': pa.int8, 'uint8': pa.uint8, 'int16': pa.int16,
    'uint16': pa.uint16, 'int32': pa.int32, 'uint32': pa.uint32,
    'int64': pa.int64, 'uint64': pa.uint64, 'halffloat': pa.float16,
    'float': pa.float32, 'double': pa.float64, 'string': pa.string,
    'large_string': pa.large_string, 'binary': pa.binary,
    'large_binary': pa.large_binary,
}


def _parse_arrow_type(type_str):
    if type_str in _ARROW_TYPE_PARSERS:
        return _ARROW_TYPE_PARSERS[type_str]()
    if type_str.startswith('timestamp'):
        inner = type_str[type_str.index('[') + 1:type_str.index(']')]
        if ',' in inner:  # e.g. 'timestamp[us, tz=UTC]'
            unit, tz_part = (s.strip() for s in inner.split(',', 1))
            tz = tz_part.split('=', 1)[1] if '=' in tz_part else None
            return pa.timestamp(unit, tz)
        return pa.timestamp(inner)
    if type_str.startswith('date32'):
        return pa.date32()
    if type_str.startswith('date64'):
        return pa.date64()
    if type_str.startswith('decimal'):
        inner = type_str[type_str.index('(') + 1:type_str.index(')')]
        precision, scale = (int(x) for x in inner.split(','))
        return pa.decimal128(precision, scale)
    raise ValueError('Cannot parse arrow type string %r' % type_str)


def _as_arrow_type(storage_type):
    """Accept an arrow DataType, a numpy dtype, or a Spark DataType."""
    if isinstance(storage_type, pa.DataType):
        return storage_type
    if isinstance(storage_type, str):
        return _parse_arrow_type(storage_type)
    try:
        return numpy_to_arrow_type(storage_type)
    except (ValueError, TypeError):
        pass
    # Possibly a Spark type instance; map via its simpleString.
    simple = getattr(storage_type, 'simpleString', None)
    if callable(simple):
        return _spark_simple_string_to_arrow(simple())
    raise ValueError('Cannot interpret %r as a storage type' % (storage_type,))


_SPARK_SIMPLE_TO_ARROW = {
    'boolean': pa.bool_(), 'tinyint': pa.int8(), 'smallint': pa.int16(),
    'int': pa.int32(), 'bigint': pa.int64(), 'float': pa.float32(),
    'double': pa.float64(), 'string': pa.string(), 'binary': pa.binary(),
    'timestamp': pa.timestamp('us'), 'date': pa.date32(),
}


def _spark_simple_string_to_arrow(simple):
    if simple in _SPARK_SIMPLE_TO_ARROW:
        return _SPARK_SIMPLE_TO_ARROW[simple]
    if simple.startswith('decimal'):
        inner = simple[simple.index('(') + 1:simple.index(')')]
        precision, scale = (int(x) for x in inner.split(','))
        return pa.decimal128(precision, scale)
    raise ValueError('Cannot map spark type %r to arrow' % simple)


#: arrow type (by ``str()``) → pyspark type class name. The single source of
#: truth for the arrow↔spark bridge: used by :func:`arrow_to_spark_type`
#: (live pyspark instances) and by the footer's reference-compatible schema
#: export (class names only, no pyspark needed) in ``etl/legacy.py``.
ARROW_TO_SPARK_TYPE_NAME = {
    'bool': 'BooleanType', 'int8': 'ByteType', 'int16': 'ShortType',
    'int32': 'IntegerType', 'int64': 'LongType',
    'uint8': 'ShortType', 'uint16': 'IntegerType', 'uint32': 'LongType',
    'halffloat': 'FloatType', 'float': 'FloatType', 'double': 'DoubleType',
    'string': 'StringType', 'large_string': 'StringType',
    'binary': 'BinaryType', 'large_binary': 'BinaryType',
    'date32[day]': 'DateType',
}


def arrow_to_spark_type(arrow_type):
    """Map an arrow DataType to a Spark DataType (requires pyspark)."""
    from pyspark.sql import types as T
    name = ARROW_TO_SPARK_TYPE_NAME.get(str(arrow_type))
    if name is not None:
        return getattr(T, name)()
    if pa.types.is_timestamp(arrow_type):
        return T.TimestampType()
    if pa.types.is_decimal(arrow_type):
        return T.DecimalType(arrow_type.precision, arrow_type.scale)
    if pa.types.is_list(arrow_type):
        return T.ArrayType(arrow_to_spark_type(arrow_type.value_type))
    raise ValueError('Cannot map arrow type %s to spark' % arrow_type)


# ---------------------------------------------------------------------------
# JSON (de)serialization of codec descriptions
# ---------------------------------------------------------------------------

def codec_to_json(codec):
    if codec is None:
        return None
    return codec.to_json_dict()


def codec_from_json(d):
    if d is None:
        return None
    kind = d['type']
    if kind == 'CompressedImageCodec':
        return CompressedImageCodec(d['image_codec'], d['quality'])
    if kind == 'NdarrayCodec':
        return NdarrayCodec()
    if kind == 'CompressedNdarrayCodec':
        return CompressedNdarrayCodec()
    if kind == 'ScalarCodec':
        return ScalarCodec(_parse_arrow_type(d['arrow_type']))
    raise ValueError('Unknown codec type in schema JSON: %r' % kind)
