"""Result-payload serializers for the process pool boundary.

Parity with ``petastorm/reader_impl/pickle_serializer.py`` and
``arrow_table_serializer.py``: a serializer turns a worker result into bytes
for the ZMQ hop and back. :class:`PickleSerializer` (protocol 5) is the
default — :class:`~petastorm_tpu.arrow_worker.ColumnBatch` payloads are
dicts of numpy arrays, which the **multipart frame API** ships with their
ndarray payloads as pickle-5 *out-of-band buffers*, one ZMQ frame each:
the pickle stream carries only metadata, serialization is a single memcpy
per array into its frame, and receive-side deserialization is **zero-copy**
(the reconstructed arrays view the received frames directly —
``pickle.loads(..., buffers=frames)``; with ``recv_multipart(copy=False)``
nothing is copied between the wire and the consumer's numpy arrays).
:class:`ArrowTableSerializer` streams a ``pyarrow.Table`` as a RecordBatch
stream for consumers that stay in Arrow.

The single-payload ``serialize``/``deserialize`` pair remains the
one-frame contract for channels that cannot carry multipart payloads (the
service protocol's framed messages); ``serialize_frames`` /
``deserialize_frames`` default to delegating to it, so custom serializers
keep working unchanged on the multipart process-pool channel.
"""

import pickle
from abc import ABCMeta, abstractmethod

import pyarrow as pa

from petastorm_tpu import sanitizer


class SerializerBase(metaclass=ABCMeta):
    @abstractmethod
    def serialize(self, value):
        """value → bytes-like."""

    @abstractmethod
    def deserialize(self, payload):
        """bytes-like → value."""

    def serialize_frames(self, value):
        """value → non-empty list of bytes-likes, each shipped as its own
        ZMQ frame. Default: one frame via :meth:`serialize`."""
        return [self.serialize(value)]

    def deserialize_frames(self, frames):
        """Inverse of :meth:`serialize_frames`; ``frames`` may be
        memoryviews over receive buffers (zero-copy receive)."""
        if len(frames) != 1:
            raise ValueError(
                '%s expects a single payload frame; got %d (was the result '
                'produced by a different serializer?)'
                % (type(self).__name__, len(frames)))
        return self.deserialize(frames[0])


class PickleSerializer(SerializerBase):
    """Default payload codec (reference: ``pickle_serializer.py:17-23``)."""

    def serialize(self, value):
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, payload):
        return pickle.loads(payload)

    def serialize_frames(self, value):
        """Pickle-5 out-of-band: frame 0 is the pickle stream (metadata +
        small objects), every buffer-exporting payload (ndarrays, Arrow
        buffers) follows as its own raw frame — no copy into the stream."""
        buffers = []
        head = pickle.dumps(value, protocol=5,
                            buffer_callback=buffers.append)
        return [head] + [b.raw() for b in buffers]

    def deserialize_frames(self, frames):
        """Zero-copy reconstruction: out-of-band arrays are rebuilt as
        views over ``frames[1:]`` (read-only when the receive buffers
        are). Decode paths never mutate result columns in place, so
        read-only views are safe batch payloads. Under
        ``PETASTORM_TPU_SANITIZE=1`` the reconstructed arrays are forced
        ``writeable=False`` regardless of the buffers' mutability, so a
        consumer scribbling on a wire buffer raises at the write site."""
        value = pickle.loads(frames[0], buffers=frames[1:])
        if sanitizer.sanitize_enabled():
            sanitizer.guard_payload(value)
        return value


class ArrowTableSerializer(SerializerBase):
    """``pyarrow.Table`` ↔ RecordBatch-stream bytes
    (reference: ``arrow_table_serializer.py:18-33``)."""

    def serialize(self, table):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def deserialize(self, payload):
        with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
            return reader.read_all()
