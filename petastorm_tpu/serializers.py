"""Result-payload serializers for the process pool boundary.

Parity with ``petastorm/reader_impl/pickle_serializer.py`` and
``arrow_table_serializer.py``: a serializer turns a worker result into bytes
for the ZMQ hop and back. :class:`PickleSerializer` (protocol 5, out-of-band
buffers capable) is the default — :class:`~petastorm_tpu.arrow_worker.ColumnBatch`
payloads are dicts of numpy arrays, which pickle ships with a single memcpy.
:class:`ArrowTableSerializer` streams a ``pyarrow.Table`` as a RecordBatch
stream for consumers that stay in Arrow.
"""

import pickle
from abc import ABCMeta, abstractmethod

import pyarrow as pa


class SerializerBase(metaclass=ABCMeta):
    @abstractmethod
    def serialize(self, value):
        """value → bytes-like."""

    @abstractmethod
    def deserialize(self, payload):
        """bytes-like → value."""


class PickleSerializer(SerializerBase):
    """Default payload codec (reference: ``pickle_serializer.py:17-23``)."""

    def serialize(self, value):
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, payload):
        return pickle.loads(payload)


class ArrowTableSerializer(SerializerBase):
    """``pyarrow.Table`` ↔ RecordBatch-stream bytes
    (reference: ``arrow_table_serializer.py:18-33``)."""

    def serialize(self, table):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def deserialize(self, payload):
        with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
            return reader.read_all()
