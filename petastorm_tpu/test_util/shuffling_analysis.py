"""Shuffle-quality analysis (reference:
``petastorm/test_util/shuffling_analysis.py:30-85``): quantify how well a
reader decorrelates row order by correlating the emitted id stream against
the unshuffled order."""

import numpy as np

from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField


def generate_shuffle_analysis_dataset(url, num_rows=1000, rowgroup_size=100):
    """Sequential-id dataset for shuffle analysis."""
    import pyarrow as pa
    schema = Unischema('ShuffleAnalysisSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    ])
    rows = [{'id': i} for i in range(num_rows)]
    write_dataset(url, schema, rows, rowgroup_size_rows=rowgroup_size,
                  num_files=max(1, num_rows // (rowgroup_size * 4)))
    return schema


def compute_correlation_distribution(url, num_runs=5, reader_factory=None,
                                     **reader_kwargs):
    """Mean |Pearson correlation| between each run's emitted id order and
    the sorted order: ~1 = unshuffled, ~0 = well shuffled."""
    from petastorm_tpu.reader import make_reader
    factory = reader_factory or make_reader
    correlations = []
    for run in range(num_runs):
        kwargs = dict(reader_kwargs)
        kwargs.setdefault('num_epochs', 1)
        kwargs['seed'] = run
        with factory(url, **kwargs) as reader:
            ids = np.asarray([row.id for row in reader])
        expected = np.arange(len(ids))
        correlations.append(abs(float(np.corrcoef(ids, expected)[0, 1])))
    return float(np.mean(correlations))
