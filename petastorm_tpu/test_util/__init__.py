"""Test/consumer utilities (reference: ``petastorm/test_util/``)."""

from petastorm_tpu.test_util.reader_mock import ReaderMock  # noqa: F401
from petastorm_tpu.test_util.generator import generate_datapoint  # noqa: F401
