"""Random datapoint generation from a Unischema
(reference: ``petastorm/generator.py:21-47``)."""

from decimal import Decimal

import numpy as np


def generate_datapoint(schema, rng=None):
    """One random row dict matching the schema (wildcard dims drawn in
    [1, 4]; nullable fields are non-null)."""
    rng = rng or np.random.RandomState()
    row = {}
    for field in schema:
        row[field.name] = _random_value(field, rng)
    return row


def _random_value(field, rng):
    np_dtype = field.numpy_dtype
    shape = tuple(d if d is not None else int(rng.randint(1, 5))
                  for d in field.shape)
    if np_dtype is Decimal:
        return Decimal('%d.%02d' % (rng.randint(0, 100), rng.randint(0, 100)))
    if np_dtype in (np.str_, str):
        if shape:
            return np.array([_rand_str(rng) for _ in range(int(np.prod(shape)))],
                            dtype=np.str_).reshape(shape)
        return _rand_str(rng)
    if np_dtype in (np.bytes_, bytes):
        if shape:
            return np.array([_rand_str(rng).encode() for _ in range(int(np.prod(shape)))],
                            dtype=np.bytes_).reshape(shape)
        return _rand_str(rng).encode()
    dtype = np.dtype(np_dtype)
    if dtype.kind == 'b':
        values = rng.randint(0, 2, shape or ()).astype(bool)
    elif dtype.kind in 'iu':
        info = np.iinfo(dtype)
        values = rng.randint(max(info.min, -1000), min(info.max, 1000),
                             shape or ()).astype(dtype)
    elif dtype.kind == 'f':
        values = rng.rand(*shape).astype(dtype) if shape \
            else dtype.type(rng.rand())
    elif dtype.kind == 'M':
        values = (np.datetime64('2020-01-01')
                  + np.timedelta64(int(rng.randint(0, 1000)), 'D'))
    else:
        raise ValueError('Cannot generate a value for dtype %r' % dtype)
    if shape == () and isinstance(values, np.ndarray):
        return values[()]
    return values


def _rand_str(rng):
    return ''.join(chr(rng.randint(97, 123)) for _ in range(8))
