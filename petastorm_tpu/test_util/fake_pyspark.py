"""A pandas-backed fake ``pyspark`` deep enough to EXECUTE the Spark parity
surface without a JVM.

The reference tests multi-node behavior with mocks where the real system is
unavailable (mocked HDFS namenodes, ``hdfs/tests/test_hdfs_namenode.py:41-53``;
``ReaderMock`` as a fake source). This module applies the same strategy to
pyspark: :func:`install` registers fake ``pyspark`` / ``pyspark.sql`` /
``pyspark.ml.*`` modules in ``sys.modules`` so that
:func:`~petastorm_tpu.spark.make_spark_converter`,
:func:`~petastorm_tpu.spark_utils.dataset_as_rdd` and
``materialize_dataset(spark=...)`` run their REAL code paths — vector
flattening, float-precision unification, plan-fingerprint dedupe, the
Spark-side parquet write, the availability wait and size advisory, hadoop
conf save/restore, executor-side decode — against a pandas/pyarrow engine.

Only the API those paths touch is implemented; anything else raises
``AttributeError`` loudly. The emulation covers (reference file:line for the
behavior each backs):

* ``DataFrame.schema`` fields with ``dataType.typeName()`` / ``VectorUDT``
  (``spark_dataset_converter.py:546-557``),
* ``withColumn`` + ``Column.cast`` for scalar and ``array<...>`` casts
  (``:524-543``),
* ``pyspark.ml.functions.vector_to_array``,
* ``df.write.option(...).parquet(url)`` — a real pyarrow parquet write,
* ``spark.read.parquet(url).inputFiles()`` (``:700-703``),
* ``df._jdf.queryExecution().analyzed().toString()`` — a content
  fingerprint standing in for the logical plan (``:498-506``),
* ``spark.sparkContext.parallelize(...).flatMap/map/collect`` — local
  execution of the executor closures (``spark_utils.py:23-52``),
* ``spark.sparkContext._jsc.hadoopConfiguration()`` get/set/setInt/unset
  (``etl/dataset_metadata.py:135-178``).
"""

import glob
import hashlib
import os
import sys
import types
import uuid

import numpy as np


# -- schema types ------------------------------------------------------------

class _DataType:
    _type_name = 'void'

    def typeName(self):  # noqa: N802 - pyspark API casing
        return self._type_name

    def __repr__(self):
        return type(self).__name__


class DoubleType(_DataType):
    _type_name = 'double'


class FloatType(_DataType):
    _type_name = 'float'


class LongType(_DataType):
    _type_name = 'bigint'


class StringType(_DataType):
    _type_name = 'string'


class ArrayType(_DataType):
    _type_name = 'array'

    def __init__(self, element_type):
        self.elementType = element_type

    def __repr__(self):
        return 'ArrayType(%r)' % (self.elementType,)


class VectorUDT(_DataType):
    """Name-matched: the converter dispatches on
    ``type(f.dataType).__name__ == 'VectorUDT'``."""
    _type_name = 'udt'


class StructField:
    def __init__(self, name, data_type):
        self.name = name
        self.dataType = data_type

    def __repr__(self):
        return 'StructField(%s,%r)' % (self.name, self.dataType)


class DenseVector:
    """Stand-in for ``pyspark.ml.linalg.DenseVector``."""

    def __init__(self, values):
        self.values = np.asarray(values, np.float64)

    def toArray(self):  # noqa: N802 - pyspark API casing
        return self.values

    def __repr__(self):
        # value-based, like the real DenseVector: the plan fingerprint
        # (_JDataFrame.toString) hashes cell reprs, and an identity-based
        # default repr would break content-addressed cache dedupe for any
        # dataframe still holding vectors
        return 'DenseVector(%s)' % self.values.tolist()


# -- columns (lazy expressions applied by withColumn) ------------------------

_CAST_NUMPY = {'float': np.float32, 'double': np.float64,
               'int': np.int32, 'bigint': np.int64}
_CAST_TYPE = {'float': FloatType, 'double': DoubleType,
              'int': LongType, 'bigint': LongType}


class Column:
    def __init__(self, name, transform=None, out_type=None):
        self.name = name
        self._transform = transform           # pandas Series -> pandas Series
        self._out_type = out_type             # _DataType after the transform

    def cast(self, target):
        if target.startswith('array<') and target.endswith('>'):
            elem = target[len('array<'):-1]
            np_t, t_t = _CAST_NUMPY[elem], _CAST_TYPE[elem]

            def conv(series):
                return series.map(lambda cell: np.asarray(cell, np_t))

            return Column(self.name, conv, ArrayType(t_t()))
        np_t, t_t = _CAST_NUMPY[target], _CAST_TYPE[target]
        return Column(self.name, lambda s: s.astype(np_t), t_t())

    def apply(self, series):
        return self._transform(series) if self._transform else series


def vector_to_array(col, dtype='float64'):
    """``pyspark.ml.functions.vector_to_array`` equivalent."""
    np_t = _CAST_NUMPY[{'float32': 'float', 'float64': 'double'}
                       .get(dtype, dtype)]
    t_t = FloatType if np_t is np.float32 else DoubleType

    def conv(series):
        return series.map(lambda vec: np.asarray(
            vec.values if isinstance(vec, DenseVector) else vec, np_t))

    return Column(col.name, conv, ArrayType(t_t()))


# -- dataframe ---------------------------------------------------------------

def _infer_field(name, series):
    if series.dtype == np.float32:
        return StructField(name, FloatType())
    if series.dtype == np.float64:
        return StructField(name, DoubleType())
    if np.issubdtype(series.dtype, np.integer):
        return StructField(name, LongType())
    first = next((v for v in series if v is not None), None)
    if isinstance(first, DenseVector):
        return StructField(name, VectorUDT())
    if isinstance(first, (list, np.ndarray)):
        elem = np.asarray(first)
        inner = (FloatType() if elem.dtype == np.float32 else
                 DoubleType() if elem.dtype == np.float64 else LongType())
        return StructField(name, ArrayType(inner))
    return StructField(name, StringType())


class DataFrame:
    def __init__(self, pdf, session, fields=None):
        self._pdf = pdf.reset_index(drop=True)
        self.sparkSession = session
        self.schema = (list(fields) if fields is not None
                       else [_infer_field(c, pdf[c]) for c in pdf.columns])
        # the logical-plan handle the converter fingerprints (':498-506');
        # content-addressed so "same dataframe" -> same plan string
        self._jdf = _JDataFrame(self)

    def __getitem__(self, name):
        return Column(name)

    def withColumn(self, name, col):  # noqa: N802 - pyspark API casing
        pdf = self._pdf.copy()
        pdf[name] = col.apply(pdf[col.name]).values
        out_type = col._out_type or next(
            f.dataType for f in self.schema if f.name == col.name)
        if any(f.name == name for f in self.schema):
            fields = [StructField(name, out_type) if f.name == name else f
                      for f in self.schema]
        else:  # like real pyspark: a new name APPENDS a column
            fields = list(self.schema) + [StructField(name, out_type)]
        return DataFrame(pdf, self.sparkSession, fields)

    def count(self):
        return len(self._pdf)

    def collect(self):
        import collections
        row_cls = collections.namedtuple('Row', list(self._pdf.columns))
        return [row_cls(**rec) for rec in self._pdf.to_dict('records')]

    @property
    def write(self):
        return _Writer(self)

    def toPandas(self):  # noqa: N802 - pyspark API casing
        return self._pdf.copy()


class _JDataFrame:
    def __init__(self, df):
        self._df = df

    def queryExecution(self):  # noqa: N802 - pyspark API casing
        return self

    def analyzed(self):
        return self

    def toString(self):  # noqa: N802 - pyspark API casing
        h = hashlib.sha1()
        h.update(repr([(f.name, repr(f.dataType))
                       for f in self._df.schema]).encode())
        for name in self._df._pdf.columns:
            for cell in self._df._pdf[name]:
                h.update(repr(np.asarray(cell).tolist()
                              if isinstance(cell, (list, np.ndarray))
                              else cell).encode())
        return 'FakeLogicalPlan(%s)' % h.hexdigest()


def _arrow_table(df):
    import pyarrow as pa
    arrays, names = [], []
    for field in df.schema:
        series = df._pdf[field.name]
        t = field.dataType
        if isinstance(t, ArrayType):
            np_t = _CAST_NUMPY[t.elementType.typeName()]
            pa_t = pa.list_(pa.from_numpy_dtype(np_t))
            arrays.append(pa.array(
                [np.asarray(v, np_t) for v in series], pa_t))
        elif isinstance(t, VectorUDT):
            raise ValueError('VectorUDT column %r cannot be written to '
                             'parquet; flatten it first (the converter '
                             'does this via vector_to_array)' % field.name)
        elif isinstance(t, FloatType):
            arrays.append(pa.array(series.astype(np.float32), pa.float32()))
        else:
            arrays.append(pa.array(series))
        names.append(field.name)
    return pa.table(dict(zip(names, arrays)))


class _Writer:
    def __init__(self, df):
        self._df = df
        self._options = {}

    def option(self, key, value):
        self._options[key] = value
        return self

    def parquet(self, url):
        import pyarrow.parquet as pq
        path = url[len('file://'):] if url.startswith('file://') else url
        os.makedirs(path, exist_ok=True)
        table = _arrow_table(self._df)
        # two part files (when rows allow), like a 2-partition write: the
        # availability wait and the median-size advisory then exercise
        # their multi-file paths
        n = table.num_rows
        splits = [table] if n < 2 else [table.slice(0, n // 2),
                                        table.slice(n // 2)]
        for i, part in enumerate(splits):
            name = 'part-%05d-%s.snappy.parquet' % (i, uuid.uuid4().hex[:12])
            pq.write_table(part, os.path.join(path, name),
                           compression=self._options.get('compression',
                                                         'snappy'))
        with open(os.path.join(path, '_SUCCESS'), 'w'):
            pass


class _LazyParquetFrame:
    """Lazy read result, like real Spark's: ``inputFiles()`` answers from
    the file listing alone; data materializes only when a DataFrame method
    actually needs it (``_await_and_advise`` only lists files — eager
    decode there would be pure waste AND less faithful)."""

    def __init__(self, parts, session):
        self._parts = parts
        self._session = session
        self._df = None

    def inputFiles(self):  # noqa: N802 - pyspark API casing
        return ['file://' + p for p in self._parts]

    def _materialize(self):
        if self._df is None:
            import pyarrow.parquet as pq
            pdf = pq.ParquetDataset(self._parts).read().to_pandas()
            self._df = DataFrame(pdf, self._session)
        return self._df

    def __getattr__(self, name):
        return getattr(self._materialize(), name)


class _Reader:
    def __init__(self, session):
        self._session = session

    def parquet(self, url):
        path = url[len('file://'):] if url.startswith('file://') else url
        parts = sorted(glob.glob(os.path.join(path, '*.parquet')))
        if not parts:
            raise FileNotFoundError('no parquet files under %s' % path)
        return _LazyParquetFrame(parts, self._session)


# -- context / session -------------------------------------------------------

class _HadoopConf:
    def __init__(self):
        self._conf = {}

    def get(self, key, default=None):
        return self._conf.get(key, default)

    def set(self, key, value):
        self._conf[key] = value

    def setInt(self, key, value):  # noqa: N802 - py4j API casing
        self._conf[key] = int(value)

    def unset(self, key):
        self._conf.pop(key, None)


class _JSparkContext:
    def __init__(self):
        self._hadoop_conf = _HadoopConf()

    def hadoopConfiguration(self):  # noqa: N802 - py4j API casing
        return self._hadoop_conf


class RDD:
    """Local, eager stand-in: transformations compose; collect() runs the
    closures in-process — the executor-side decode of ``dataset_as_rdd``
    really executes, just not remotely."""

    def __init__(self, items):
        self._items = list(items)

    def map(self, fn):
        return RDD([fn(item) for item in self._items])

    def flatMap(self, fn):  # noqa: N802 - pyspark API casing
        return RDD([out for item in self._items for out in fn(item)])

    def collect(self):
        return list(self._items)

    def count(self):
        return len(self._items)


class SparkContext:
    def __init__(self):
        self._jsc = _JSparkContext()

    def parallelize(self, items, num_slices=None):
        return RDD(items)


class _RuntimeConf:
    def __init__(self):
        self._conf = {}

    def get(self, key, default=None):
        return self._conf.get(key, default)

    def set(self, key, value):
        self._conf[key] = value


class SparkSession:
    def __init__(self):
        self.sparkContext = SparkContext()
        self.conf = _RuntimeConf()

    def range(self, n):
        import pandas as pd
        return DataFrame(pd.DataFrame({'id': np.arange(n, dtype=np.int64)}),
                         self)

    def createDataFrame(self, pdf):  # noqa: N802 - pyspark API casing
        return DataFrame(pdf, self)

    @property
    def read(self):
        return _Reader(self)

    def stop(self):
        pass


# -- sys.modules installation ------------------------------------------------

_FAKE_MODULES = ('pyspark', 'pyspark.sql', 'pyspark.ml',
                 'pyspark.ml.functions', 'pyspark.ml.linalg')


def install():
    """Register the fake modules; returns the displaced ``sys.modules``
    entries for :func:`uninstall`."""
    displaced = {name: sys.modules.get(name) for name in _FAKE_MODULES}

    pyspark = types.ModuleType('pyspark')
    pyspark.__version__ = '0.0.fake'
    pyspark.SparkContext = SparkContext

    sql = types.ModuleType('pyspark.sql')
    sql.SparkSession = SparkSession
    sql.DataFrame = DataFrame

    ml = types.ModuleType('pyspark.ml')
    ml_functions = types.ModuleType('pyspark.ml.functions')
    ml_functions.vector_to_array = vector_to_array
    ml_linalg = types.ModuleType('pyspark.ml.linalg')
    ml_linalg.DenseVector = DenseVector
    ml_linalg.VectorUDT = VectorUDT

    pyspark.sql = sql
    pyspark.ml = ml
    ml.functions = ml_functions
    ml.linalg = ml_linalg

    for name, module in (('pyspark', pyspark), ('pyspark.sql', sql),
                         ('pyspark.ml', ml),
                         ('pyspark.ml.functions', ml_functions),
                         ('pyspark.ml.linalg', ml_linalg)):
        sys.modules[name] = module
    return displaced


def uninstall(displaced):
    """Restore the ``sys.modules`` entries :func:`install` displaced."""
    for name in _FAKE_MODULES:
        previous = displaced.get(name)
        if previous is None:
            sys.modules.pop(name, None)
        else:
            sys.modules[name] = previous
