"""ReaderMock: a schema-driven fake reader with no I/O
(reference: ``petastorm/test_util/reader_mock.py:19-82``). Useful for
testing consumers (loaders, bridges) without a dataset on disk."""

import numpy as np

from petastorm_tpu.test_util.generator import generate_datapoint


def schema_data_generator_example(schema, rng):
    """Default data generator: random values per field."""
    return generate_datapoint(schema, rng)


class ReaderMock:
    """Infinite iterator of synthetic rows (namedtuples) for a schema.

    :param schema: a :class:`Unischema`.
    :param schema_data_generator: ``(schema, rng) -> row_dict`` override.
    """

    def __init__(self, schema, schema_data_generator=None, seed=0,
                 batched_output=False, batch_size=16):
        self.schema = schema
        self.ngram = None
        self.batched_output = batched_output
        self.last_row_consumed = False
        self._batch_size = batch_size
        self._gen = schema_data_generator or schema_data_generator_example
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        return self

    def __next__(self):
        if not self.batched_output:
            return self.schema.make_namedtuple(**self._gen(self.schema,
                                                           self._rng))
        rows = [self._gen(self.schema, self._rng)
                for _ in range(self._batch_size)]
        columns = {}
        for name in self.schema.fields:
            values = [r[name] for r in rows]
            first = values[0]
            if isinstance(first, np.ndarray):
                columns[name] = (np.stack(values)
                                 if all(v.shape == first.shape for v in values)
                                 else _object_array(values))
            else:
                columns[name] = np.asarray(values)
        return self.schema.make_namedtuple(**columns)

    def next(self):
        return self.__next__()

    def reset(self):
        pass

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        pass


def _object_array(values):
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out
