"""Fused attention on the MXU: the Pallas flash-attention kernel.

The dense attention path materializes the full ``(B, H, S, S)`` score
tensor in HBM — at seq 1024+ that is the transformer's HBM-bandwidth
hot spot and the ceiling on single-chip MFU. This wraps jax's shipped
Pallas TPU flash-attention kernel (blockwise online-softmax; scores only
ever live in VMEM tiles) behind this framework's ``(B, S, H, D)`` layout,
causal (LM) or bidirectional (ViT/encoder) alike, with two fallbacks so
the SAME model code runs everywhere:

* real TPU → the Pallas kernel;
* any other backend → the exact dense reference (tests oracle against it;
  CPU-mesh CI never depends on kernel support).

Selected per-model via ``TransformerConfig(attn_impl='flash')``; combines
with dp/tp/pp meshes (the kernel runs per-shard under XLA's auto
partitioning) but not with ``seq_axis`` (ring/Ulysses own the sharded-S
case).

The reference framework has no model execution layer (SURVEY.md §0);
this is part of the TPU-native consumer layer, alongside
:mod:`petastorm_tpu.ops.ring_attention`.
"""

import jax
import numpy as np

from petastorm_tpu.ops.ring_attention import reference_attention

#: the kernel's default block size: sequences must be multiples of it
#: (jax's _verify_block rejects others); shorter/ragged lengths take the
#: dense path rather than shrinking blocks below MXU tiles
_FLASH_BLOCK = 128


def reference_causal_attention(q, k, v, sm_scale):
    """Dense causal attention oracle — the ONE shared dense oracle
    (:func:`petastorm_tpu.ops.ring_attention.reference_attention`), so a
    numerics change there is the single source of truth here too."""
    return reference_attention(q, k, v, causal=True, scale=sm_scale)


def _on_tpu():
    try:
        return jax.default_backend() == 'tpu'
    except Exception:  # noqa: BLE001 - uninitialized backend
        return False


def kernel_supported(seq_len):
    """Would :func:`flash_causal_attention` run the FUSED kernel (not the
    dense fallback) for this sequence length on the current backend? The
    single source of truth for callers (e.g. the bench) deciding whether
    an ``attn_impl='flash'`` config buys anything here."""
    return _on_tpu() and seq_len >= _FLASH_BLOCK \
        and seq_len % _FLASH_BLOCK == 0


def flash_attention_fused(q, k, v, causal=True, sm_scale=None,
                          force_kernel=False):
    """Self-attention, fused when the backend supports it.

    :param q, k, v: ``(B, S, H, D)`` activations (the framework layout).
    :param causal: lower-triangular mask (LM) vs full bidirectional
        attention (ViT/encoder) — both ride the same fused kernel.
    :param sm_scale: score scale; default ``1/sqrt(D)``.
    :param force_kernel: run the Pallas kernel even off-TPU (interpret
        mode — slow, for kernel-correctness tests only).
    :return: ``(B, S, H, D)`` context, same dtype as ``q``.
    """
    b, s, h, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    use_kernel = force_kernel or kernel_supported(s)
    if not use_kernel:
        return reference_attention(q, k, v, causal=causal, scale=sm_scale)

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention,
    )

    def run():
        # kernel layout is (B, H, S, D)
        bhsd = lambda t: t.transpose(0, 2, 1, 3)  # noqa: E731
        out = flash_attention(bhsd(q), bhsd(k), bhsd(v), causal=causal,
                              sm_scale=float(sm_scale))
        return out.transpose(0, 2, 1, 3).astype(q.dtype)

    if force_kernel and not _on_tpu():
        from jax.experimental.pallas import tpu as pltpu
        with pltpu.force_tpu_interpret_mode():
            return run()
    return run()


def flash_causal_attention(q, k, v, sm_scale=None, force_kernel=False):
    """Causal flash attention — :func:`flash_attention_fused` with the LM
    mask (the original public name, kept for callers)."""
    return flash_attention_fused(q, k, v, causal=True, sm_scale=sm_scale,
                                 force_kernel=force_kernel)
