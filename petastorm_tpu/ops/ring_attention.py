"""Ring attention: exact attention over sequences sharded across devices.

Long-context support: when a sequence is too long for one chip's HBM, shard
it over a mesh axis and compute attention in ``n_shards`` ring steps — each
step combines the local query block with the currently-held key/value block
using an online (flash-style) softmax accumulator, then rotates the KV block
to the next device with ``lax.ppermute`` so compute overlaps the ICI
transfer. Results are bit-for-bit the same attention as the unsharded
computation (up to float reassociation).

The reference framework has no model-side parallelism at all (SURVEY.md
§2.2: sharding stops at row-group assignment); this op is part of the
framework's TPU-native consumer layer, alongside the dp×tp transformer in
:mod:`petastorm_tpu.models.transformer`.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from petastorm_tpu.parallel.mesh import SEQ_AXIS  # canonical axis name


def _online_block(carry, k_blk, v_blk, q, q_pos, kv_pos, causal, scale):
    """Fold one KV block into the running (output, rowmax, denom) state."""
    o, m, l = carry
    # scores: (B, H, Sq, Skv) with f32 accumulation
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # guard fully-masked rows: exp(-inf - -inf) otherwise
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isneginf(scores), -jnp.inf,
                          scores - safe_m[..., None]))
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum('bhqk,bkhd->bqhd', p, v_blk,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def _ring_attention_local(q, k, v, axis_name, causal, scale):
    """Per-device body (runs under shard_map): q/k/v are the LOCAL sequence
    blocks of shape (B, S_local, H, D)."""
    n_shards = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_local, h, _ = q.shape

    q_pos = my_idx * s_local + jnp.arange(s_local)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]

    # vma promotion: under a check_vma=True manual region (the pp×sp
    # pipeline calls this body directly) the fori_loop carry must already
    # vary over every axis q does; standalone (manual_shard_map,
    # check_vma=False) this is a no-op
    from petastorm_tpu.parallel.mesh import match_vma
    o = match_vma(jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32), q)
    m = match_vma(jnp.full((b, h, s_local), -jnp.inf, jnp.float32), q)
    l = match_vma(jnp.zeros((b, h, s_local), jnp.float32), q)

    def step(t, state):
        o, m, l, k_blk, v_blk = state
        kv_owner = (my_idx - t) % n_shards
        kv_pos = kv_owner * s_local + jnp.arange(s_local)
        o, m, l = _online_block((o, m, l), k_blk, v_blk, q, q_pos, kv_pos,
                                causal, scale)
        # rotate AFTER consuming: block from device j moves to j+1
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    o, m, l, _, _ = lax.fori_loop(0, n_shards, step, (o, m, l, k, v))
    # fully-masked rows (causal, early positions with no visible keys) have
    # l == 0; emit zeros for them
    denom = jnp.where(l == 0.0, 1.0, l)
    out = o / denom.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name=SEQ_AXIS, causal=True,
                   scale=None, batch_axis=None):
    """Exact multi-head attention with the sequence axis sharded over
    ``mesh[axis_name]``.

    :param q, k, v: (B, S, H, D) arrays whose S axis is (or will be) sharded
        over ``axis_name``; B/H/D are replicated on that axis.
    :param causal: apply a causal mask over GLOBAL positions.
    :param scale: score scale (default ``1/sqrt(D)``).
    :param batch_axis: optional mesh axis the batch dim is sharded over
        (combined data x seq meshes); keeps B sharded instead of gathered.
        The ring only communicates over ``axis_name``, so batch sharding
        is transparent to the algorithm.
    :return: (B, S, H, D) attention output, same sharding as ``q``.
    """
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, axis_name, None, None)
    body = functools.partial(_ring_attention_local, axis_name=axis_name,
                             causal=causal, scale=scale)
    from petastorm_tpu.parallel.mesh import manual_shard_map
    fn = manual_shard_map(body, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, causal=True, scale=None):
    """Unsharded attention with identical semantics (test oracle)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bhqk,bkhd->bqhd', probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
