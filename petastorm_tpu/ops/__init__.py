"""TPU kernels (Pallas) for the hot data-path ops."""

from petastorm_tpu.ops.normalize import normalize_images  # noqa: F401
