"""TPU kernels (Pallas) and collective ops for the hot paths."""

from petastorm_tpu.ops.normalize import normalize_images  # noqa: F401
from petastorm_tpu.ops.ring_attention import ring_attention  # noqa: F401
