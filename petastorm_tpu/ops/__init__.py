"""TPU kernels (Pallas) and collective ops for the hot paths."""

from petastorm_tpu.ops.augment import (  # noqa: F401
    random_crop, random_cutout, random_flip_horizontal,
)
from petastorm_tpu.ops.normalize import normalize_images  # noqa: F401
from petastorm_tpu.ops.ring_attention import ring_attention  # noqa: F401
