"""On-device image augmentation: flips, crops, cutout — jit/vmap native.

The reference's augmentation story is "run it in the TransformSpec on the
decode workers" (host CPU, per-row Python). On TPU the better split is:
workers decode + resize to a FIXED shape (static shapes for XLA), and the
cheap elementwise/gather augmentations run ON DEVICE inside the jitted
step — they fuse into the input pipeline of the model and cost ~nothing
next to the first conv/matmul, while the host stays free for decode.

All ops take an explicit ``jax.random`` key (functional, reproducible,
per-step keys via ``jax.random.fold_in``) and NHWC uint8/float batches.
Randomness is PER IMAGE (a ``vmap`` over the batch), not per batch.
"""

import jax
import jax.numpy as jnp
from jax import lax


def random_flip_horizontal(key, images, p=0.5):
    """Flip each image left-right with probability ``p`` (per image)."""
    flags = jax.random.bernoulli(key, p, (images.shape[0],))
    return jnp.where(flags[:, None, None, None], images[:, :, ::-1], images)


def random_crop(key, images, crop_h, crop_w):
    """Crop a random (crop_h, crop_w) window per image (uniform offsets).

    (B, H, W, C) → (B, crop_h, crop_w, C); requires crop ≤ image dims.
    ``lax.dynamic_slice`` under ``vmap`` — one gather per image, static
    output shape.
    """
    b, h, w, c = images.shape
    if crop_h > h or crop_w > w:
        raise ValueError('crop (%d, %d) exceeds image (%d, %d)'
                         % (crop_h, crop_w, h, w))
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (b,), 0, h - crop_h + 1)
    xs = jax.random.randint(kx, (b,), 0, w - crop_w + 1)

    def crop_one(image, y, x):
        return lax.dynamic_slice(image, (y, x, 0), (crop_h, crop_w, c))

    return jax.vmap(crop_one)(images, ys, xs)


def random_cutout(key, images, size, fill=0):
    """Zero (or ``fill``) a random ``size``×``size`` square per image —
    the standard cutout regularizer, as a mask (no scatter: a boolean
    window test against per-image offsets, fused elementwise)."""
    b, h, w, _ = images.shape
    if size > h or size > w:
        raise ValueError('cutout size %d exceeds image (%d, %d)'
                         % (size, h, w))
    ky, kx = jax.random.split(key)
    ys = jax.random.randint(ky, (b,), 0, h - size + 1)
    xs = jax.random.randint(kx, (b,), 0, w - size + 1)
    rows = jnp.arange(h)[None, :, None]            # (1, H, 1)
    cols = jnp.arange(w)[None, None, :]            # (1, 1, W)
    inside = ((rows >= ys[:, None, None]) & (rows < ys[:, None, None] + size)
              & (cols >= xs[:, None, None]) & (cols < xs[:, None, None] + size))
    return jnp.where(inside[..., None], jnp.asarray(fill, images.dtype),
                     images)
