"""Fused uint8→bfloat16 image normalization as a Pallas TPU kernel.

The first device-side op of every image pipeline: ``(x/255 - mean)/std``
with a dtype cast. Staging images as uint8 and normalizing on device
quarters the H2D traffic vs shipping f32 — this kernel fuses the cast,
scale, and normalize into one VMEM pass so the lowering never materializes
an intermediate f32 image in HBM.

Falls back to plain jnp (which XLA fuses fine on CPU) when not running on
TPU; the kernel and fallback are numerically identical, which the tests
assert.
"""

import functools

import jax
import jax.numpy as jnp


def _norm_kernel(x_ref, scale_ref, bias_ref, o_ref):
    # One batch row per grid step: (1, H, W, C) block in VMEM.
    x = x_ref[...].astype(jnp.float32)
    # (x/255 - mean)/std  ==  x * scale + bias  with precomputed
    # scale = 1/(255*std), bias = -mean/std — one fused multiply-add.
    o_ref[...] = (x * scale_ref[...] + bias_ref[...]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=('out_dtype', 'interpret'))
def normalize_images(images, mean, std, out_dtype=jnp.bfloat16,
                     interpret=False):
    """Normalize a uint8 NHWC image batch on device.

    :param images: (N, H, W, C) uint8 array.
    :param mean: per-channel mean in [0, 1], shape (C,).
    :param std: per-channel std in [0, 1], shape (C,).
    :param interpret: run the Pallas kernel in interpret mode (testing).
    """
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    scale = (1.0 / (255.0 * std)).astype(jnp.float32)
    bias = (-mean / std).astype(jnp.float32)

    on_tpu = jax.devices()[0].platform == 'tpu'
    if not (on_tpu or interpret):
        x = images.astype(jnp.float32)
        return (x * scale + bias).astype(out_dtype)

    from jax.experimental import pallas as pl

    n, h, w, c = images.shape
    grid = (n,)
    return pl.pallas_call(
        _norm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((c,), lambda i: (0,)),
            pl.BlockSpec((c,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), out_dtype),
        interpret=interpret,
    )(images, scale, bias)
