"""Ulysses-style all-to-all sequence parallelism for attention.

The second of the framework's two long-context strategies (the other is
:mod:`petastorm_tpu.ops.ring_attention`):

* **Ring**: keep the sequence sharded, rotate KV blocks device-to-device
  with ``ppermute``; communication is O(S/N) per step overlapping compute.
* **Ulysses** (this module): two ``all_to_all`` collectives re-partition the
  tensors from sequence-sharded to *head*-sharded, so every device computes
  exact attention over the FULL sequence for its subset of heads, then a
  second pair of ``all_to_all``s restores sequence sharding.

Ulysses wins when heads are plentiful and the per-device sequence block is
small (fewer collective launches, one big MXU-friendly attention per
device); ring wins when ``n_heads < n_devices`` or HBM cannot hold the full
S×S score block. Both are exact — the choice is a performance decision,
so both are verified against the same oracle
(:func:`petastorm_tpu.ops.ring_attention.reference_attention`).

The reference framework has no model-side parallelism (SURVEY.md §2.2);
this op belongs to the TPU-native consumer layer the reference delegates to
Horovod-era trainers.
"""

import functools

import jax.numpy as jnp
from jax import lax

from petastorm_tpu.ops.ring_attention import SEQ_AXIS


def _ulysses_local(q, k, v, axis_name, causal, scale):
    """Per-device body under shard_map.

    Local inputs are (B, S/N, H, D). ``all_to_all`` splits the head axis N
    ways and gathers the sequence axis, yielding (B, S, H/N, D); plain
    attention runs on the full sequence; the inverse collective restores
    (B, S/N, H, D).
    """
    # the wrapper validates this too, but direct callers (the pp×sp
    # pipeline's seq_manual branch) must get the same actionable error,
    # not an obscure all_to_all shape failure mid-trace
    n = lax.psum(1, axis_name)
    if q.shape[2] % n:
        raise ValueError(
            'ulysses attention needs n_heads %% n_seq_shards == 0 (got %d '
            'heads over %d shards on axis %r); use ring attention instead'
            % (q.shape[2], n, axis_name))
    # seq-sharded -> head-sharded: split heads (axis 2), concat seq (axis 1)
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)

    scores = jnp.einsum('bqhd,bkhd->bhqk', qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s = qh.shape[1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    # f32 softmax statistics AND f32 probabilities through the PV product,
    # exactly like ring_attention's online accumulator — the two strategies
    # must be numerically interchangeable, not just oracle-close
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum('bhqk,bkhd->bqhd', probs, vh,
                     preferred_element_type=jnp.float32).astype(q.dtype)

    # head-sharded -> seq-sharded: split seq (axis 1), concat heads (axis 2)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, mesh, axis_name=SEQ_AXIS, causal=True,
                      scale=None, batch_axis=None):
    """Exact multi-head attention with the sequence axis sharded over
    ``mesh[axis_name]``, computed head-parallel via all-to-all.

    :param q, k, v: (B, S, H, D) arrays whose S axis is (or will be)
        sharded over ``axis_name``. Requires ``H % mesh.shape[axis_name]
        == 0`` (each device takes a head subset).
    :param causal: apply a causal mask over global positions.
    :param scale: score scale (default ``1/sqrt(D)``).
    :param batch_axis: optional mesh axis the batch dim is sharded over
        (combined data x seq meshes); the all-to-alls only touch
        ``axis_name``, so batch sharding is transparent.
    :return: (B, S, H, D) attention output, same sharding as ``q``.
    """
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    if q.shape[2] % n:
        raise ValueError(
            'ulysses_attention needs n_heads %% n_devices == 0 (got %d heads '
            'over %d devices on axis %r); use ring_attention instead'
            % (q.shape[2], n, axis_name))
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, axis_name, None, None)
    body = functools.partial(_ulysses_local, axis_name=axis_name,
                             causal=causal, scale=scale)
    from petastorm_tpu.parallel.mesh import manual_shard_map
    fn = manual_shard_map(body, mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)
