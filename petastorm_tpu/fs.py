"""URL → filesystem resolution.

TPU-first replacement for the reference's ``FilesystemResolver``
(``petastorm/fs_utils.py:39-241``): on TPU VMs the storage universe is
local disk + GCS (+ optionally s3/hdfs), and fsspec already speaks all of
them, so scheme dispatch collapses onto :func:`fsspec.core.url_to_fs` instead
of hand-rolled per-scheme clients (the reference's HDFS-HA machinery lives in
the fsspec/pyarrow HDFS drivers now). The public helpers keep the reference
names so call sites translate one-to-one.
"""

from urllib.parse import urlparse

import fsspec


def normalize_dir_url(url):
    """Strip a trailing slash so cache keys and relpaths are stable.

    Reference: ``petastorm/fs_utils.py:235-241``.
    """
    if not isinstance(url, str):
        raise ValueError('Expected a string url, got %r' % (url,))
    return url.rstrip('/')


def get_dataset_path(url):
    """Path component of a dataset URL; bucket stays in the path for object stores.

    Reference: ``petastorm/fs_utils.py:26-36``.
    """
    parsed = urlparse(url)
    if parsed.scheme in ('s3', 's3a', 's3n', 'gs', 'gcs'):
        return parsed.netloc + parsed.path
    return parsed.path


def get_filesystem_and_path_or_paths(url_or_urls, storage_options=None,
                                     filesystem=None):
    """Resolve one URL (or a homogeneous list of URLs) to (fsspec_fs, path(s)).

    All URLs in a list must share scheme and netloc
    (reference: ``petastorm/fs_utils.py:202-232``).

    :param filesystem: an already-constructed fsspec filesystem to use
        instead of resolving one from the URL scheme (reference
        ``reader.py``'s ``filesystem=`` kwarg) — e.g. a pre-authenticated
        ``gcsfs``/``s3fs`` instance. URLs are stripped to fs-native paths
        via the filesystem's own protocol rules. Mutually exclusive with
        ``storage_options`` (options belong to the construction this
        bypasses).
    """
    urls = url_or_urls if isinstance(url_or_urls, list) else [url_or_urls]
    parsed = [urlparse(u) for u in urls]
    if len({(p.scheme, p.netloc) for p in parsed}) != 1:
        raise ValueError('All dataset URLs must share scheme and netloc: %r' % urls)
    if filesystem is not None:
        if storage_options:
            raise ValueError('filesystem and storage_options are mutually '
                             'exclusive: the explicit filesystem was already '
                             'constructed, so the options cannot apply')
        scheme = parsed[0].scheme
        protocols = (filesystem.protocol if isinstance(filesystem.protocol,
                                                       (tuple, list))
                     else (filesystem.protocol,))
        # a mismatched scheme would be silently mangled by _strip_protocol
        # (e.g. LocalFileSystem turns 'gs://b/x' into '<cwd>/gs:/b/x') and
        # surface as a baffling not-found error far downstream — reject it
        # here, where the scheme is known. Scheme-less bare paths are
        # allowed: there is nothing to check them against.
        if scheme and scheme not in protocols:
            raise ValueError(
                'URL scheme %r does not match the explicit filesystem '
                '(protocol %r)' % (scheme, filesystem.protocol))
        paths = [filesystem._strip_protocol(u) for u in urls]
        return (filesystem, paths if isinstance(url_or_urls, list)
                else paths[0])
    if parsed[0].scheme == 'hdfs':
        # HA nameservice expansion + namenode failover
        from petastorm_tpu.hdfs import connect_hdfs_url
        fs, path0 = connect_hdfs_url(urls[0],
                                     storage_options=storage_options)
        paths = [path0] + [urlparse(u).path for u in urls[1:]]
    else:
        fs, path0 = fsspec.core.url_to_fs(urls[0], **(storage_options or {}))
        paths = [path0] + [fsspec.core.url_to_fs(u, **(storage_options or {}))[1]
                           for u in urls[1:]]
    if isinstance(url_or_urls, list):
        return fs, paths
    return fs, paths[0]
