"""URL → filesystem resolution.

TPU-first replacement for the reference's ``FilesystemResolver``
(``petastorm/fs_utils.py:39-241``): on TPU VMs the storage universe is
local disk + GCS (+ optionally s3/hdfs), and fsspec already speaks all of
them, so scheme dispatch collapses onto :func:`fsspec.core.url_to_fs` instead
of hand-rolled per-scheme clients (the reference's HDFS-HA machinery lives in
the fsspec/pyarrow HDFS drivers now). The public helpers keep the reference
names so call sites translate one-to-one.
"""

from urllib.parse import urlparse

import fsspec


def normalize_dir_url(url):
    """Strip a trailing slash so cache keys and relpaths are stable.

    Reference: ``petastorm/fs_utils.py:235-241``.
    """
    if not isinstance(url, str):
        raise ValueError('Expected a string url, got %r' % (url,))
    return url.rstrip('/')


def get_dataset_path(url):
    """Path component of a dataset URL; bucket stays in the path for object stores.

    Reference: ``petastorm/fs_utils.py:26-36``.
    """
    parsed = urlparse(url)
    if parsed.scheme in ('s3', 's3a', 's3n', 'gs', 'gcs'):
        return parsed.netloc + parsed.path
    return parsed.path


def get_filesystem_and_path_or_paths(url_or_urls, storage_options=None):
    """Resolve one URL (or a homogeneous list of URLs) to (fsspec_fs, path(s)).

    All URLs in a list must share scheme and netloc
    (reference: ``petastorm/fs_utils.py:202-232``).
    """
    urls = url_or_urls if isinstance(url_or_urls, list) else [url_or_urls]
    parsed = [urlparse(u) for u in urls]
    if len({(p.scheme, p.netloc) for p in parsed}) != 1:
        raise ValueError('All dataset URLs must share scheme and netloc: %r' % urls)
    if parsed[0].scheme == 'hdfs':
        # HA nameservice expansion + namenode failover
        from petastorm_tpu.hdfs import connect_hdfs_url
        fs, path0 = connect_hdfs_url(urls[0],
                                     storage_options=storage_options)
        paths = [path0] + [urlparse(u).path for u in urls[1:]]
    else:
        fs, path0 = fsspec.core.url_to_fs(urls[0], **(storage_options or {}))
        paths = [path0] + [fsspec.core.url_to_fs(u, **(storage_options or {}))[1]
                           for u in urls[1:]]
    if isinstance(url_or_urls, list):
        return fs, paths
    return fs, paths[0]
