"""Reader throughput measurement.

Parity with ``petastorm/benchmark/throughput.py:112-168``: warmup then
measured read cycles against a dataset URL, reporting samples/sec
(= samples / elapsed), RSS and CPU utilisation via psutil. Extensions over
the reference: a ``read_method='jax'`` mode that measures the full
host→device staging path (rows/sec INTO device memory), and a clean-process
measurement without self-re-spawning (RSS is sampled as a delta).
"""

import dataclasses
import logging
import time

from petastorm_tpu.telemetry import get_registry, pipeline_report, span

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class BenchmarkResult:
    samples_per_second: float
    memory_rss_mb: float
    cpu_percent: float
    samples: int
    elapsed_s: float
    #: write benchmark only: encoded bytes landed on storage per second
    encoded_mb_per_second: float = None
    #: read benchmarks: telemetry.pipeline_report over the measure window
    #: (per-stage seconds/shares vs measured wall, stall attribution) —
    #: registry reads replace the hand-rolled timers the benchmark once
    #: needed for stage breakdowns
    pipeline: dict = None

    def __str__(self):
        text = ('%.2f samples/sec; RSS %.1f MB; CPU %.1f%%'
                % (self.samples_per_second, self.memory_rss_mb,
                   self.cpu_percent))
        if self.encoded_mb_per_second is not None:
            text += '; encoded %.1f MB/sec' % self.encoded_mb_per_second
        if self.pipeline is not None:
            from petastorm_tpu.telemetry import format_pipeline_report
            text += '\n' + format_pipeline_report(self.pipeline)
        return text


def _measure_window(fn):
    """Run one measure loop under a scoped telemetry window: snapshot the
    registry (stage-counter baseline) AND reset the stall attributor, so
    both the per-stage shares and the stall verdict cover exactly the
    measured interval — warmup/spin-up waits (reader startup blocking the
    first pulls) would otherwise misattribute a balanced steady state as
    producer-bound. With tracing on, the flight recorder resets here too:
    the exported trace (``--trace-out``) and its slowest-row-group
    ranking must cover the measure window, not warmup's cold-cache I/O
    (items straddling the boundary keep only their post-boundary
    events). Returns ``(samples, elapsed, report)``."""
    from petastorm_tpu.telemetry import (
        get_attributor, reset_recorder, trace_enabled,
    )
    baseline = get_registry().snapshot()
    get_attributor().reset()
    if trace_enabled():
        reset_recorder()
    start = time.monotonic()
    samples = fn()
    elapsed = time.monotonic() - start
    report = pipeline_report(wall_time_s=elapsed, baseline=baseline)
    return samples, elapsed, report


def reader_throughput(dataset_url, field_regex=None, warmup_cycles=200,
                      measure_cycles=1000, pool_type='thread',
                      loaders_count=None, read_method='python',
                      shuffle_row_groups=True, batch_size=128,
                      spawn_new_process=False, reader_type='real',
                      dummy_fields=None):
    """Measure read throughput of a dataset.

    :param read_method: ``'python'`` — rows via ``make_reader`` (the
        reference's measurement); ``'batch'`` — row-groups via
        ``make_batch_reader`` counted in rows; ``'jax'`` — fixed batches
        staged to the default jax device via
        :func:`~petastorm_tpu.jax.make_jax_loader`.
    :param spawn_new_process: re-run the measurement in a fresh process for
        clean RSS numbers (reference: ``throughput.py:144-149``).
    :param reader_type: ``'real'`` reads ``dataset_url``; ``'dummy'``
        substitutes a zero-I/O zero-decode synthetic reader
        (:mod:`~petastorm_tpu.benchmark.dummy_reader`) so the result is the
        framework-machinery upper bound — the real/dummy delta is the
        I/O+decode cost. ``dataset_url`` is ignored under ``'dummy'``.
    :param dummy_fields: ``{name: (row_shape, dtype)}`` for the synthetic
        reader (default: one 64-float32 vector field).
    """
    if reader_type not in ('real', 'dummy'):
        raise ValueError("reader_type must be 'real' or 'dummy'; got %r"
                         % (reader_type,))
    if spawn_new_process:
        return _run_in_subprocess(
            dataset_url, field_regex=field_regex, warmup_cycles=warmup_cycles,
            measure_cycles=measure_cycles, pool_type=pool_type,
            loaders_count=loaders_count, read_method=read_method,
            shuffle_row_groups=shuffle_row_groups, batch_size=batch_size,
            reader_type=reader_type, dummy_fields=dummy_fields)

    import psutil
    process = psutil.Process()
    process.cpu_percent()  # prime the sampler

    dummy = dummy_fields if reader_type == 'dummy' else None
    if read_method == 'python':
        counter = _measure_rows(dataset_url, field_regex, warmup_cycles,
                                measure_cycles, pool_type, loaders_count,
                                shuffle_row_groups,
                                dummy=dummy, use_dummy=reader_type == 'dummy')
    elif read_method == 'batch':
        counter = _measure_batches(dataset_url, field_regex, warmup_cycles,
                                   measure_cycles, pool_type, loaders_count,
                                   shuffle_row_groups,
                                   dummy=dummy,
                                   use_dummy=reader_type == 'dummy')
    elif read_method == 'jax':
        counter = _measure_jax(dataset_url, field_regex, warmup_cycles,
                               measure_cycles, shuffle_row_groups, batch_size,
                               loaders_count, pool_type,
                               dummy=dummy, use_dummy=reader_type == 'dummy')
    else:
        raise ValueError("read_method must be 'python', 'batch' or 'jax'; "
                         'got %r' % read_method)

    samples, elapsed, report = counter
    return BenchmarkResult(
        samples_per_second=samples / elapsed if elapsed else float('inf'),
        memory_rss_mb=process.memory_info().rss / 2 ** 20,
        cpu_percent=process.cpu_percent(),
        samples=samples,
        elapsed_s=elapsed,
        pipeline=report)


def _measure_rows(url, field_regex, warmup, measure, pool_type, workers,
                  shuffle, dummy=None, use_dummy=False):
    if use_dummy:
        from petastorm_tpu.benchmark.dummy_reader import DummyRowReader
        reader_cm = DummyRowReader(fields=dummy)
    else:
        from petastorm_tpu.reader import make_reader
        reader_cm = make_reader(url, schema_fields=field_regex,
                                num_epochs=None, reader_pool_type=pool_type,
                                workers_count=workers,
                                shuffle_row_groups=shuffle)
    with reader_cm as reader:
        for _ in range(warmup):
            next(reader)

        def loop():
            # a real Reader records queue_wait itself (_pull_result); the
            # synthetic reader has no internal spans, so its pull loop is
            # wrapped HERE — ONE span over the whole loop, not one per
            # row: a dummy row serves in single-digit µs, so per-row span
            # bookkeeping would dwarf the thing measured and the report
            # could never attribute the wall it is asserted to attribute
            if use_dummy:
                with span('queue_wait'):
                    for _ in range(measure):
                        next(reader)
            else:
                for _ in range(measure):
                    next(reader)
            return measure

        samples, elapsed, report = _measure_window(loop)
        return samples, elapsed, report


def _measure_batches(url, field_regex, warmup, measure, pool_type, workers,
                     shuffle, dummy=None, use_dummy=False):
    if use_dummy:
        from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
        reader_cm = DummyBatchReader(fields=dummy)
    else:
        from petastorm_tpu.reader import make_batch_reader
        reader_cm = make_batch_reader(url, schema_fields=field_regex,
                                      num_epochs=None,
                                      reader_pool_type=pool_type,
                                      workers_count=workers,
                                      shuffle_row_groups=shuffle)
    with reader_cm as reader:
        seen = 0
        for batch in reader:
            seen += len(next(iter(batch._asdict().values())))
            if seen >= warmup:
                break

        def loop():
            seen = 0
            it = iter(reader)
            if use_dummy:
                # one span over the loop (see _measure_rows): the
                # synthetic batch serve is too cheap for per-pull spans
                with span('queue_wait'):
                    while seen < measure:
                        batch = next(it)
                        seen += len(next(iter(
                            batch._asdict().values())))
            else:
                while seen < measure:
                    batch = next(it)
                    seen += len(next(iter(batch._asdict().values())))
            return seen

        samples, elapsed, report = _measure_window(loop)
        return samples, elapsed, report


def _measure_jax(url, field_regex, warmup, measure, shuffle, batch_size,
                 workers, pool_type='thread', dummy=None, use_dummy=False):
    from petastorm_tpu.jax import make_jax_loader
    kwargs = {}
    if use_dummy:
        from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader

        def factory(_url, schema_fields=None, num_epochs=None, **_kw):
            return DummyBatchReader(fields=dummy)

        kwargs['reader_factory'] = factory
    else:
        kwargs['workers_count'] = workers
        kwargs['shuffle_row_groups'] = shuffle
        kwargs['reader_pool_type'] = pool_type
    with make_jax_loader(url, batch_size=batch_size, fields=field_regex,
                         num_epochs=None, **kwargs) as loader:
        it = iter(loader)
        seen = 0
        while seen < warmup:
            seen += batch_size
            next(it)

        def loop():
            seen = 0
            while seen < measure:
                batch = next(it)
                # block on the transfer so we measure staged rows, not
                # enqueues
                next(iter(batch.values())).block_until_ready()
                seen += batch_size
            return seen

        samples, elapsed, report = _measure_window(loop)
        return samples, elapsed, report


def write_throughput(dataset_url, rows=512, image_hw=(224, 224),
                     rowgroup_size_rows=64, workers_count=None,
                     image_format='jpeg'):
    """Measure the write path: synthetic image rows through
    :class:`~petastorm_tpu.etl.dataset_metadata.DatasetWriter` (codec
    encode + parquet write), reporting rows/sec and encoded MB/s.

    The reference has no write benchmark (its write path is a Spark job);
    this measures the first-party writer, including ``workers_count``
    parallel encode — pass e.g. ``workers_count=8`` on a multi-core host
    to measure the thread-pooled encode against the serial default.
    """
    import numpy as np
    import pyarrow as pa

    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import (
        DatasetWriter, ParquetDatasetInfo, materialize_dataset,
    )
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    from petastorm_tpu.unischema import Unischema, UnischemaField

    # Refuse a non-empty target: DatasetWriter restarts part numbering at
    # 0, so writing over a previous (larger) run would leave a mixed
    # dataset AND count the stale files' bytes against this run's elapsed
    # time, silently inflating encoded MB/s.
    fs, root = get_filesystem_and_path_or_paths(dataset_url)
    if fs.exists(root) and fs.glob(root.rstrip('/') + '/*.parquet'):
        raise ValueError('write benchmark target %r already contains '
                         'parquet files; point it at a fresh directory'
                         % dataset_url)

    h, w = image_hw
    schema = Unischema('WriteBench', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('image', np.uint8, (h, w, 3),
                       CompressedImageCodec(image_format, quality=90), False),
    ])
    rng = np.random.RandomState(0)
    base = rng.randint(0, 255, (h, w, 3), np.uint8)

    def row_stream():
        # vary rows cheaply (roll, not regenerate) so encode output —
        # and thus the measured encode work — is not one cached artifact
        for i in range(rows):
            yield {'id': i, 'image': np.roll(base, i, axis=0)}

    import psutil
    process = psutil.Process()
    process.cpu_percent()
    start = time.monotonic()
    with materialize_dataset(dataset_url, schema):
        with DatasetWriter(dataset_url, schema,
                           rowgroup_size_rows=rowgroup_size_rows,
                           workers_count=workers_count) as writer:
            writer.write_row_dicts(row_stream())
    elapsed = time.monotonic() - start
    info = ParquetDatasetInfo(dataset_url)
    encoded_bytes = sum(info.fs.size(f) for f in info.file_paths)
    return BenchmarkResult(
        samples_per_second=rows / elapsed if elapsed else float('inf'),
        memory_rss_mb=process.memory_info().rss / 2 ** 20,
        cpu_percent=process.cpu_percent(),
        samples=rows,
        elapsed_s=elapsed,
        encoded_mb_per_second=(encoded_bytes / 2 ** 20 / elapsed
                               if elapsed else float('inf')))


def _run_in_subprocess(dataset_url, **kwargs):
    import pickle
    import subprocess
    import sys
    import tempfile

    code = (
        'import pickle, sys\n'
        'from petastorm_tpu.benchmark.throughput import reader_throughput\n'
        'url, kwargs, out = sys.argv[1], pickle.load(open(sys.argv[2], "rb")), sys.argv[3]\n'
        'result = reader_throughput(url, **kwargs)\n'
        'pickle.dump(result, open(out, "wb"))\n')
    with tempfile.NamedTemporaryFile(suffix='.pkl') as kw_f, \
            tempfile.NamedTemporaryFile(suffix='.pkl') as out_f:
        pickle.dump(kwargs, kw_f)
        kw_f.flush()
        # dataset_url may be None under reader_type='dummy' (ignored by the
        # measurement); argv entries must still be strings
        subprocess.check_call([sys.executable, '-c', code, dataset_url or '',
                               kw_f.name, out_f.name])
        with open(out_f.name, 'rb') as result_f:
            return pickle.load(result_f)
