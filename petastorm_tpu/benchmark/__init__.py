"""Throughput benchmark suite (reference: ``petastorm/benchmark/``)."""

from petastorm_tpu.benchmark.throughput import (  # noqa: F401
    BenchmarkResult, reader_throughput,
)
