"""Throughput CLI (reference: ``petastorm/benchmark/cli.py:30-107``).

Usage: ``python -m petastorm_tpu.benchmark.cli file:///path/to/dataset``
"""

import argparse
import logging
import sys


def build_parser():
    parser = argparse.ArgumentParser(
        description='petastorm_tpu reader throughput benchmark')
    parser.add_argument('dataset_url', nargs='?', default=None,
                        help='file:// or remote dataset URL (optional with '
                             '--reader dummy)')
    parser.add_argument('--reader', default='real',
                        choices=['real', 'dummy'],
                        help="'dummy' serves synthetic in-RAM data (no I/O, "
                             'no decode): the framework-overhead upper '
                             'bound to calibrate real numbers against')
    parser.add_argument('--dummy-dim', type=int, default=64,
                        help='row vector length for --reader dummy')
    parser.add_argument('--field-regex', nargs='+', default=None,
                        help='regex patterns selecting fields to read')
    parser.add_argument('-w', '--warmup-cycles', type=int, default=200)
    parser.add_argument('-m', '--measure-cycles', type=int, default=1000)
    parser.add_argument('-p', '--pool', '--pool-type', dest='pool_type',
                        default='thread',
                        choices=['thread', 'process', 'dummy', 'service'],
                        help="'service' measures the disaggregated decode "
                             'path: localhost worker servers are spawned '
                             'automatically unless '
                             'PETASTORM_TPU_SERVICE_DISPATCHER points at an '
                             'existing dispatcher endpoint with an external '
                             'fleet (docs/service.md), so thread/process/'
                             'service throughput is comparable from one '
                             'command')
    parser.add_argument('-l', '--loaders-count', type=int, default=None,
                        help='decode workers; default auto-sizes to the host')
    parser.add_argument('-r', '--read-method', default='python',
                        choices=['python', 'batch', 'jax'])
    parser.add_argument('--batch-size', type=int, default=128,
                        help="batch size for read-method 'jax'")
    parser.add_argument('--write', action='store_true',
                        help='measure the WRITE path instead: synthetic '
                             'image rows through DatasetWriter '
                             '(codec encode + parquet) to dataset_url; '
                             'reader flags (-w/-m/-p/-l/-r/--reader/'
                             '--spawn-new-process) do not apply')
    parser.add_argument('--write-rows', type=int, default=512)
    parser.add_argument('--write-workers', type=int, default=None,
                        help='parallel-encode threads for --write '
                             '(default: serial)')
    parser.add_argument('--no-shuffle', action='store_true')
    parser.add_argument('--spawn-new-process', action='store_true',
                        help='measure in a fresh process for clean RSS')
    parser.add_argument('--metrics-out', default=None, metavar='PATH',
                        help='append one JSONL telemetry snapshot (full '
                             'metrics registry + per-stage pipeline '
                             'report) after the run — the machine-readable '
                             'twin of the printed report '
                             '(docs/telemetry.md)')
    parser.add_argument('--trace-out', default=None, metavar='PATH',
                        help='enable per-item tracing for the run '
                             '(PETASTORM_TPU_TRACE=1) and write the '
                             'Perfetto-viewable Chrome trace-event JSON '
                             'for the measure window here, mirroring '
                             '--metrics-out; also prints the stall '
                             'verdict and the top-3 slowest row-groups '
                             '(docs/telemetry.md)')
    parser.add_argument('-v', '--verbose', action='store_true')
    return parser


def _write_metrics(path, result):
    """One JSONL line: registry snapshot + run metadata + the measure
    window's pipeline report (when the run produced one)."""
    from petastorm_tpu.telemetry import write_jsonl_snapshot
    extra = {'samples_per_second': result.samples_per_second,
             'samples': result.samples,
             'elapsed_s': result.elapsed_s}
    if getattr(result, 'pipeline', None) is not None:
        extra['pipeline_report'] = result.pipeline
    write_jsonl_snapshot(path, extra=extra)


def _write_trace(path, result):
    """Dump the run's flight recorder as a Chrome trace and print the
    timeline-level summary: the stall verdict plus the top-3 slowest
    row-groups (summed worker-side attempt time per trace)."""
    from petastorm_tpu.telemetry import dump_trace, slowest_items
    count = dump_trace(path)
    print('trace: %d event(s) -> %s (open in ui.perfetto.dev)'
          % (count, path))
    pipeline = getattr(result, 'pipeline', None)
    if pipeline is not None:
        print('stall verdict: %s' % pipeline['stall']['verdict'])
    slowest = slowest_items(n=3)
    if slowest:
        print('slowest row-groups (worker-side time):')
        for trace_id, seconds, args in slowest:
            where = ['%s=%s' % (k, args[k])
                     for k in ('item', 'epoch', 'shard', 'worker')
                     if k in args]
            print('  %-28s %8.3fs  %s'
                  % (trace_id, seconds, ' '.join(where)))


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.verbose:
        logging.basicConfig(level=logging.DEBUG)
    if args.trace_out:
        if args.spawn_new_process:
            parser.error('--trace-out needs the measurement in THIS '
                         'process (the flight recorder is per-process); '
                         'drop --spawn-new-process')
        if args.write:
            parser.error('--trace-out applies to read measurements only, '
                         'not --write')
        # the knob must be live before any reader/ventilator exists
        from petastorm_tpu import telemetry
        telemetry.knobs.set_env('PETASTORM_TPU_TRACE', '1')
        telemetry.refresh()
    if args.write:
        if args.dataset_url is None:
            parser.error('dataset_url is required with --write')
        if args.spawn_new_process:
            parser.error('--spawn-new-process applies to read '
                         'measurements only, not --write')
        from petastorm_tpu.benchmark.throughput import write_throughput
        result = write_throughput(args.dataset_url, rows=args.write_rows,
                                  workers_count=args.write_workers)
        print(result)
        if args.metrics_out:
            _write_metrics(args.metrics_out, result)
        return 0
    if args.dataset_url is None and args.reader != 'dummy':
        parser.error('dataset_url is required unless --reader dummy')
    import numpy as np
    from petastorm_tpu.benchmark.throughput import reader_throughput
    result = reader_throughput(
        args.dataset_url, field_regex=args.field_regex,
        warmup_cycles=args.warmup_cycles, measure_cycles=args.measure_cycles,
        pool_type=args.pool_type, loaders_count=args.loaders_count,
        read_method=args.read_method, batch_size=args.batch_size,
        shuffle_row_groups=not args.no_shuffle,
        spawn_new_process=args.spawn_new_process,
        reader_type=args.reader,
        dummy_fields={'test': ((args.dummy_dim,), np.float32)})
    print(result)
    if args.metrics_out:
        _write_metrics(args.metrics_out, result)
    if args.trace_out:
        _write_trace(args.trace_out, result)
    return 0


if __name__ == '__main__':
    sys.exit(main())
