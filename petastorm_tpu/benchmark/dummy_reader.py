"""Synthetic calibration readers: the framework-overhead upper bound.

Reader-shaped objects that serve pre-generated in-RAM data with zero I/O
and zero decode cost (reference: ``petastorm/benchmark/dummy_reader.py:25-44``,
whose ``DummyReader`` yields one cached numpy batch forever). Feeding one
through the SAME consumers as a real reader — the throughput benchmark's
measure loops, :func:`~petastorm_tpu.jax.make_jax_loader` — isolates the
framework's own cost, so an end-to-end number decomposes::

    sec/row(real) = sec/row(dummy)        # staging/re-batch/H2D machinery
                  + I/O + decode          # the remainder

Unlike the reference's (one frozen batch), a small pool of distinct random
batches is cycled so downstream shuffling buffers and caches cannot
degenerate to a single hot cache line; generation still happens once, at
construction.
"""

import collections
import itertools

import numpy as np

#: default synthetic schema, matching the reference's ``dim=64`` float32
DEFAULT_FIELDS = {'test': ((64,), np.float32)}


def _make_schema(fields):
    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.unischema import Unischema, UnischemaField
    import pyarrow as pa
    out = []
    for name, (shape, dtype) in fields.items():
        dtype = np.dtype(dtype)
        if shape == ():
            codec = ScalarCodec(pa.from_numpy_dtype(dtype))
        else:
            codec = NdarrayCodec()
        out.append(UnischemaField(name, dtype.type, shape, codec, False))
    return Unischema('dummy', out)


class DummyBatchReader:
    """Batched reader over synthetic data; duck-type compatible with
    ``make_batch_reader`` consumers (iteration, ``stop``/``join``/``reset``,
    ``schema``, ``diagnostics``).

    :param fields: ``{name: (row_shape, dtype)}`` (default: one 64-float32
        vector field, the reference's shape).
    :param batch_size: rows per served batch.
    :param num_batches: batches per epoch, or None for an endless stream.
    :param distinct_batches: size of the pre-generated pool that is cycled.
    """

    batched_output = True

    def __init__(self, fields=None, batch_size=1000, num_batches=None,
                 distinct_batches=8, seed=0):
        self._fields = dict(fields or DEFAULT_FIELDS)
        self._batch_size = batch_size
        self._num_batches = num_batches
        self._schema = _make_schema(self._fields)
        self._row_type = collections.namedtuple(  # noqa: PYI024 - data row
            'dummy_batch', list(self._fields))
        rng = np.random.RandomState(seed)
        self._pool = [self._row_type(**{
            name: rng.uniform(size=(batch_size,) + tuple(shape))
                     .astype(dtype, copy=False)
            for name, (shape, dtype) in self._fields.items()})
            for _ in range(distinct_batches)]
        self._served = 0
        self._stopped = False

    # -- reader surface ------------------------------------------------------

    @property
    def schema(self):
        return self._schema

    @property
    def batch_size(self):
        return self._batch_size

    @property
    def diagnostics(self):
        return {'dummy_batches_served': self._served}

    @property
    def last_row_consumed(self):
        return (self._num_batches is not None
                and self._served >= self._num_batches)

    def __iter__(self):
        source = (itertools.cycle(self._pool) if self._num_batches is None
                  else itertools.islice(itertools.cycle(self._pool),
                                        self._num_batches - self._served))
        for batch in source:
            if self._stopped:
                return
            self._served += 1
            yield batch

    def __next__(self):
        if self._iter is None:
            self._iter = iter(self)
        return next(self._iter)

    _iter = None

    def reset(self):
        self._served = 0
        self._iter = None

    def stop(self):
        self._stopped = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()


class DummyRowReader:
    """Row-at-a-time flavor for ``make_reader``-style consumers: the same
    synthetic pool, served as per-row namedtuples."""

    batched_output = False

    def __init__(self, fields=None, num_rows=None, distinct_batches=8,
                 seed=0, batch_size=1000):
        self._batched = DummyBatchReader(fields=fields, batch_size=batch_size,
                                         distinct_batches=distinct_batches,
                                         seed=seed)
        self._num_rows = num_rows
        self._row_type = self._batched._row_type
        self._served = 0
        self._stopped = False

    @property
    def schema(self):
        return self._batched.schema

    @property
    def diagnostics(self):
        return {'dummy_rows_served': self._served}

    @property
    def last_row_consumed(self):
        return self._num_rows is not None and self._served >= self._num_rows

    def __iter__(self):
        for batch in self._batched:
            n = len(batch[0])
            for i in range(n):
                if self._stopped or (self._num_rows is not None
                                     and self._served >= self._num_rows):
                    return
                self._served += 1
                yield self._row_type(*(col[i] for col in batch))

    def __next__(self):
        if self._iter is None:
            self._iter = iter(self)
        return next(self._iter)

    _iter = None

    def reset(self):
        self._batched.reset()
        self._served = 0
        self._iter = None

    def stop(self):
        self._stopped = True
        self._batched.stop()

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
