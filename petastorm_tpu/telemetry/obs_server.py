"""HTTP observability endpoint: scrape/report/health/trace, stdlib-only.

The outside-the-process half of the live observability plane
(docs/telemetry.md). One loopback-by-default HTTP server per process,
armed ONLY when ``PETASTORM_TPU_OBS_PORT`` names a port (``0`` = pick a
free one) and metrics are on; with the knob unset no server (and no
sampler) thread is ever created. Routes:

* ``/metrics`` — Prometheus text exposition (the same
  :func:`~petastorm_tpu.telemetry.export.prometheus_text` the file
  exporter writes), scrapeable by a live Prometheus.
* ``/report`` — the live ``pipeline_report()`` JSON, plus the windowed
  ``rollup`` section and any mounted component's report contribution
  (the service dispatcher adds the merged ``fleet`` view with
  per-worker breakdown).
* ``/health`` — heartbeat: pid/uptime plus every mounted component's
  health dict (reader pool gauges, loader queue depth, dispatcher
  backlog/quiesce state, worker-server job state).
* ``/trace`` — the flight recorder's Perfetto-viewable Chrome trace
  JSON, pulled on demand — no SIGUSR1, no file path needed
  (``PETASTORM_TPU_TRACE=1`` must have been on during the run for the
  events to exist).
* ``/critpath`` — the critical-path engine's live analysis of the same
  recorder (:mod:`~petastorm_tpu.telemetry.critpath`): self vs
  overlapped time per stage and the what-if projections. The view is
  whatever this process's recorder holds — a Reader shows the read
  plane, a JaxLoader adds the staging stages, and the service
  dispatcher (whose DONE-frame merges fold worker events in) serves the
  fleet-merged view.

Components *mount* themselves (:func:`mount`): the Reader, JaxLoader,
service dispatcher (via the ServicePool) and worker servers each
register a named health/report provider; the first armed mount starts
the server and the sampler (:func:`~petastorm_tpu.telemetry.timeseries
.ensure_collector`). The server then lives for the process — a standing
observability plane — while mounts come and go with their components.

Trust model: binds ``127.0.0.1`` by default; set
``PETASTORM_TPU_OBS_HOST`` to expose on a private cluster network only —
the endpoint is read-only but leaks operational detail (same stance as
the service dispatcher, docs/service.md).
"""

import http.server
import io
import json
import logging
import os
import threading
import time

from petastorm_tpu.telemetry import knobs
from petastorm_tpu.telemetry import timeseries
from petastorm_tpu.telemetry.spans import metrics_disabled

logger = logging.getLogger(__name__)

#: endpoint requests served, by route (observability self-metrics)
OBS_SCRAPES = 'petastorm_tpu_obs_scrapes_total'

_DEFAULT_HOST = '127.0.0.1'


class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.server = None
        self.thread = None
        self.mounts = {}
        self.started_ts = None
        self.bind_failed = False
        self.seq = 0


_state = _State()


class Mount:
    """Handle of one mounted component; ``close()`` detaches it."""

    def __init__(self, key):
        self._key = key

    @property
    def live(self):
        return True

    def close(self):
        with _state.lock:
            _state.mounts.pop(self._key, None)


class _NoopMount:
    """Returned when the plane is unarmed: nothing started, nothing to
    close — the zero-cost contract of ``PETASTORM_TPU_METRICS=0`` / an
    unset ``PETASTORM_TPU_OBS_PORT``."""

    @property
    def live(self):
        return False

    def close(self):
        pass


_NOOP_MOUNT = _NoopMount()


class _Provider:
    __slots__ = ('name', 'health', 'report')

    def __init__(self, name, health, report):
        self.name = name
        self.health = health
        self.report = report


def requested_port():
    """The knob's port, or None when unset/invalid (= plane disabled)."""
    text = knobs.get_str('PETASTORM_TPU_OBS_PORT')
    if text == '':
        return None
    port = knobs.get_int('PETASTORM_TPU_OBS_PORT', None, floor=0)
    return port


def mount(name, health=None, report=None):
    """Attach one component to this process's observability endpoint.

    Arms lazily: when ``PETASTORM_TPU_OBS_PORT`` is set (and metrics
    on), the first mount binds the HTTP server and starts the rollup
    sampler; otherwise a shared no-op handle is returned and no thread
    or socket is ever created. ``health``/``report`` are zero-arg
    callables returning JSON-ish dicts, polled per request (exceptions
    are contained per provider). Returns a handle whose ``close()``
    detaches the component."""
    if metrics_disabled():
        return _NOOP_MOUNT
    port = requested_port()
    if port is None:
        return _NOOP_MOUNT
    with _state.lock:
        _state.seq += 1
        key = '%s-%d' % (name, _state.seq)
        _state.mounts[key] = _Provider(name, health, report)
    _ensure_server(port)
    timeseries.ensure_collector()
    return Mount(key)


def _ensure_server(port):
    with _state.lock:
        if _state.server is not None or _state.bind_failed:
            return
        host = knobs.get_str('PETASTORM_TPU_OBS_HOST') or _DEFAULT_HOST
        try:
            server = http.server.ThreadingHTTPServer((host, port),
                                                     _Handler)
        except OSError as e:
            # a second process on the same fixed port (dispatcher +
            # worker server on one host): observability is advisory, so
            # log-and-continue — and remember, so every later mount does
            # not retry the doomed bind
            _state.bind_failed = True
            logger.warning('Observability endpoint failed to bind %s:%s '
                           '(%s); set PETASTORM_TPU_OBS_PORT=0 for an '
                           'ephemeral per-process port', host, port, e)
            return
        server.daemon_threads = True
        _state.server = server
        _state.started_ts = time.time()
        _state.thread = threading.Thread(
            target=server.serve_forever, daemon=True,
            name='petastorm-tpu-obs-http')
        _state.thread.start()
        logger.info('Observability endpoint listening on http://%s:%d '
                    '(/metrics /report /health /trace /critpath)',
                    *server.server_address[:2])


def server_port():
    """The bound port of this process's endpoint, or None."""
    server = _state.server
    return server.server_address[1] if server is not None else None


def server_address():
    """``(host, port)`` of the live endpoint, or None."""
    server = _state.server
    return tuple(server.server_address[:2]) if server is not None else None


def _providers():
    with _state.lock:
        return list(_state.mounts.values())


def _component_sections(attr):
    """``{name: provider_result}`` over every mount's ``attr`` callable,
    exceptions contained per provider; duplicate component names get a
    numeric suffix so two Readers in one process both show."""
    out = {}
    for provider in _providers():
        fn = getattr(provider, attr)
        if fn is None:
            continue
        try:
            value = fn()
        except Exception as e:  # noqa: BLE001 - a scrape must not 500
            value = {'error': repr(e)[:200]}
        name = provider.name
        n = 2
        while name in out:
            name = '%s-%d' % (provider.name, n)
            n += 1
        out[name] = value
    return out


def build_health():
    """The ``/health`` document (also the programmatic probe). Carries
    the live SLO section whenever a ``PETASTORM_TPU_SLO`` policy is
    armed, so every mounted component's health probe shows the burn."""
    from petastorm_tpu.telemetry import slo
    started = _state.started_ts
    doc = {
        'status': 'ok',
        'pid': os.getpid(),
        'ts': time.time(),
        'uptime_s': round(time.time() - started, 3) if started else None,
        'components': _component_sections('health'),
    }
    slo_view = slo.slo_section()
    if slo_view is not None:
        doc['slo'] = slo_view
        if any(t['breaching'] for t in slo_view['targets']):
            doc['status'] = 'slo-breach'
    return doc


def build_report():
    """The ``/report`` document: live ``pipeline_report()`` + the rollup
    section + every mounted component's report contribution (the service
    dispatcher's ``fleet`` view lands here)."""
    from petastorm_tpu.telemetry.export import pipeline_report
    report = pipeline_report()
    rollup = timeseries.rollup_section()
    if rollup is not None:
        report['rollup'] = rollup
    for section in _component_sections('report').values():
        if not isinstance(section, dict):
            continue
        for key, value in section.items():
            # never clobber: a second loader's 'autotune' (or a provider
            # key colliding with a canonical pipeline_report section)
            # gets a numeric suffix, same dedup rule as /health
            out_key = key
            n = 2
            while out_key in report:
                out_key = '%s-%d' % (key, n)
                n += 1
            report[out_key] = value
    return report


def _json_default(value):
    try:
        return float(value)
    except (TypeError, ValueError):
        return str(value)


class _Handler(http.server.BaseHTTPRequestHandler):
    # observability must not spam stderr per scrape
    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        logger.debug('obs-http ' + fmt, *args)

    def do_GET(self):  # noqa: N802 - stdlib handler naming
        route = self.path.split('?', 1)[0].rstrip('/') or '/'
        try:
            if route == '/metrics':
                from petastorm_tpu.telemetry.export import prometheus_text
                body = prometheus_text().encode()
                content_type = 'text/plain; version=0.0.4'
            elif route == '/report':
                body = json.dumps(build_report(),
                                  default=_json_default).encode()
                content_type = 'application/json'
            elif route == '/health':
                body = json.dumps(build_health(),
                                  default=_json_default).encode()
                content_type = 'application/json'
            elif route == '/trace':
                from petastorm_tpu.telemetry.recorder import (
                    export_chrome_trace,
                )
                buf = io.StringIO()
                export_chrome_trace(buf)
                body = buf.getvalue().encode()
                content_type = 'application/json'
            elif route == '/critpath':
                from petastorm_tpu.telemetry import critpath
                section = critpath.critpath_section()
                body = json.dumps(
                    section if section is not None
                    else {'error': 'no trace events recorded (set '
                                   'PETASTORM_TPU_TRACE=1)'},
                    default=_json_default).encode()
                content_type = 'application/json'
            else:
                self.send_error(404, 'routes: /metrics /report /health '
                                     '/trace /critpath')
                return
        except Exception:  # noqa: BLE001 - a scrape must not kill serving
            logger.debug('obs-http %s failed', route, exc_info=True)
            self.send_error(500)
            return
        if not metrics_disabled():
            from petastorm_tpu.telemetry.registry import get_registry
            get_registry().counter(OBS_SCRAPES, route=route.strip('/')).inc()
        self.send_response(200)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _reset_for_tests():
    """Shut the server down and drop every mount (test isolation only —
    production servers deliberately live for the process)."""
    with _state.lock:
        server, thread = _state.server, _state.thread
        _state.server = None
        _state.thread = None
        _state.mounts.clear()
        _state.started_ts = None
        _state.bind_failed = False
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5)
