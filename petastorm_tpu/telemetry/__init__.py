"""Telemetry: unified metrics registry, per-stage spans, stall attribution.

The observability subsystem the pipeline layers share (SURVEY.md §5.5 names
the reference's total absence of instrumentation; tf.data (Murray et al.,
2021) and the tf.data service (Audibert et al., 2022) both make per-stage
timing + producer/consumer stall attribution the prerequisite for
autotuning). Dependency-free by design: stdlib only, cheap enough for
per-row-group hot paths, safe under threads, and mergeable across the
process/service pools (worker processes ship counter deltas back over the
existing result channels — markers for the ZMQ process pool, DONE messages
for the disaggregated service, aggregated fleet-wide at the dispatcher).

Three layers:

* :class:`MetricsRegistry` (:func:`get_registry` is the process-wide one) —
  counters, gauges, fixed-bucket histograms, with ``collect_delta`` /
  ``merge_delta`` for cross-process aggregation.
* :func:`span` — per-stage timing context managers over the canonical
  pipeline stages (:data:`STAGES`); compiled to shared no-ops when
  ``PETASTORM_TPU_METRICS=0``.
* :class:`StallAttributor` (:func:`get_attributor` is the process-wide one)
  — classifies each sampling window as producer-bound / consumer-bound /
  balanced from the two wait clocks (consumer blocked pulling vs producer
  blocked pushing).

Exporters: :func:`write_jsonl_snapshot` / :func:`read_jsonl_snapshots`
(JSONL), :func:`prometheus_text` (Prometheus text format), and
:func:`pipeline_report` / :func:`format_pipeline_report` (per-stage time
breakdown + stall attribution). See docs/telemetry.md.

A fourth layer rides on the first three: per-item distributed tracing
(:mod:`~petastorm_tpu.telemetry.tracing` +
:mod:`~petastorm_tpu.telemetry.recorder`) — ``PETASTORM_TPU_TRACE=1``
mints a trace context per ventilated row-group, worker-side events ride
the same delta channels the metrics use, and the per-process flight
recorder exports a Perfetto-viewable Chrome trace
(:func:`dump_trace`; ``Reader.dump_trace`` / ``JaxLoader.dump_trace`` /
``benchmark --trace-out``). Off by default with the spans' no-op
discipline. See the tracing section of docs/telemetry.md.
"""

from petastorm_tpu.telemetry import knobs  # noqa: F401
from petastorm_tpu.telemetry.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, dump_delta_frame,
    get_registry, load_delta_frame, merge_worker_delta, reset_registry,
)
from petastorm_tpu.telemetry.spans import (  # noqa: F401
    STAGES, metrics_disabled, refresh_enabled, span,
)
from petastorm_tpu.telemetry.stall import (  # noqa: F401
    BALANCED, CONSUMER_BOUND, PRODUCER_BOUND, StallAttributor,
    get_attributor, reset_attributor,
)
from petastorm_tpu.telemetry.export import (  # noqa: F401
    classify_cache_phase, decoded_cache_section, format_pipeline_report,
    pipeline_report, prometheus_text, read_jsonl_snapshots,
    write_jsonl_snapshot,
)
from petastorm_tpu.telemetry.recorder import (  # noqa: F401
    FlightRecorder, export_chrome_trace, get_recorder, reset_recorder,
    slowest_items,
)
from petastorm_tpu.telemetry import tracing  # noqa: F401
from petastorm_tpu.telemetry.tracing import (  # noqa: F401
    TRACE_CTX_KEY, TraceContext, dump_trace, refresh_trace, trace_enabled,
)
from petastorm_tpu.telemetry import timeseries  # noqa: F401
from petastorm_tpu.telemetry.timeseries import (  # noqa: F401
    AnomalyDetector, HeartbeatSummarizer, ObsCollector, WindowedRollup,
    recent_anomalies, record_anomaly,
)
from petastorm_tpu.telemetry import obs_server  # noqa: F401
from petastorm_tpu.telemetry import critpath  # noqa: F401
from petastorm_tpu.telemetry import obslog  # noqa: F401
from petastorm_tpu.telemetry import slo  # noqa: F401

#: registry counter names the wait clocks accumulate into (seconds)
STALL_PRODUCER_WAIT = 'petastorm_tpu_stall_producer_wait_seconds_total'
STALL_CONSUMER_WAIT = 'petastorm_tpu_stall_consumer_wait_seconds_total'

#: swallowed-failure counter (docs/telemetry.md): every broad exception
#: handler that intentionally continues — best-effort shutdowns, advisory
#: telemetry frames, peer-may-be-gone sends — increments this with its
#: site label, so "silent" degradation is never invisible to the
#: observability plane (ISSUE 11 satellite: no swallow without a count)
SWALLOWED_ERRORS = 'petastorm_tpu_swallowed_errors_total'


def count_swallowed(site):
    """Count one intentionally-swallowed failure at ``site`` (a short
    kebab-case label). Deliberately exception-free and metrics-gated:
    the callers are already in degraded paths."""
    if metrics_disabled():
        return
    get_registry().counter(SWALLOWED_ERRORS, site=site).inc()

#: waits shorter than this are scheduling noise, not stalls; callers skip
#: noting them so fast balanced pipelines don't accumulate phantom waits
STALL_NOTE_FLOOR_S = 0.001


def note_producer_wait(seconds):
    """Producer blocked pushing results toward the consumer (back-pressure:
    the CONSUMER is the slow side). Feeds both the process-wide registry
    and the process-wide stall attributor."""
    if seconds <= 0.0 or metrics_disabled():
        return
    get_registry().counter(STALL_PRODUCER_WAIT).inc(seconds)
    get_attributor().note_producer_wait(seconds)


def note_consumer_wait(seconds):
    """Consumer blocked waiting for data (starvation: the PRODUCER is the
    slow side). Feeds both the process-wide registry and the process-wide
    stall attributor."""
    if seconds <= 0.0 or metrics_disabled():
        return
    get_registry().counter(STALL_CONSUMER_WAIT).inc(seconds)
    get_attributor().note_consumer_wait(seconds)


#: extra knob-refreshers registered by other subsystems (the jax staging
#: arena) so ``refresh()`` stays the ONE entry point that re-reads every
#: cached PETASTORM_TPU_* knob in the process
_extra_refreshers = []


def register_refresh(fn):
    """Hook a subsystem's knob-refresh function into :func:`refresh`."""
    if fn not in _extra_refreshers:
        _extra_refreshers.append(fn)


def refresh():
    """Re-read EVERY cached knob — metrics enable, trace enable, sampling
    stride, autodump state, plus any registered subsystem knobs (the jax
    staging arena's, the observability plane's) — so tests and long-lived
    processes flip all of them through one entry point (the per-module
    ``refresh_enabled``/``refresh_trace``/``refresh_staging``/
    ``refresh_obs`` remain as the halves)."""
    refresh_enabled()
    refresh_trace()
    for fn in list(_extra_refreshers):
        fn()


# the live-observability knobs (window length, anomaly thresholds) ride
# the same one-entry-point refresh discipline as the staging arena's
register_refresh(timeseries.refresh_obs)


def reset_for_tests():
    """Fresh process-wide registry + attributor + flight recorder, the
    observability plane torn down, planner summaries cleared, and knobs
    re-read (test isolation only)."""
    obs_server._reset_for_tests()
    timeseries._reset_for_tests()
    slo._reset_for_tests()
    obslog._reset_for_tests()
    reset_registry()
    reset_attributor()
    reset_recorder()
    tracing._reset_for_tests()
    # lazy: pushdown/readahead import telemetry at their module tops
    from petastorm_tpu import pushdown, readahead
    pushdown.reset_for_tests()
    readahead._reset_for_tests()
    # the staging autotuner's decision ring — only when its module is
    # already loaded (never force the jax package in for a reset)
    import sys as _sys
    autotune = _sys.modules.get('petastorm_tpu.jax.autotune')
    if autotune is not None:
        autotune._reset_for_tests()
    refresh_enabled()
