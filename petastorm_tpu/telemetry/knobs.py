"""Central registry of ``PETASTORM_TPU_*`` environment knobs.

The package's ONE place that touches ``os.environ`` for its own knobs.
Every knob name must be a member of
:data:`petastorm_tpu.analysis.contracts.KNOWN_KNOBS` (reading an
unregistered name raises — a typo'd knob fails loudly instead of
silently reading the default forever) and must carry a row in
docs/env_knobs.md. Both contracts are enforced statically by the
``env-knob`` pass of :mod:`petastorm_tpu.analysis`: a raw
``os.environ`` read of the namespace anywhere else in the package is a
finding, so call-site parsing can never drift from the registry again.

Call sites keep their own caching discipline (resolve once, re-read via
``petastorm_tpu.telemetry.refresh()``); this module is deliberately
cache-free so a refresh sees the live environment.
"""

import logging
import os

from petastorm_tpu.analysis.contracts import (  # noqa: F401 - re-exported
    DISABLED_VALUES, ENABLED_VALUES, KNOB_PREFIX, KNOWN_KNOBS,
)

logger = logging.getLogger(__name__)


def _check(name):
    if name not in KNOWN_KNOBS:
        raise ValueError(
            'Unregistered environment knob %r: add it to '
            'petastorm_tpu/analysis/contracts.py KNOWN_KNOBS and document '
            'it in docs/env_knobs.md' % (name,))


def raw(name, default=None):
    """The registry's one ``os.environ`` read: the raw string value of a
    REGISTERED knob (``default`` when unset)."""
    _check(name)
    return os.environ.get(name, default)


def get_str(name, default=''):
    """Stripped string value of a registered knob."""
    value = raw(name, default)
    return value.strip() if isinstance(value, str) else value


def is_disabled(name):
    """True when the knob carries a disable spelling
    (:data:`DISABLED_VALUES`); unset/empty is NOT disabled — the pattern
    of every on-by-default kill switch (metrics, staging, native)."""
    return get_str(name).lower() in DISABLED_VALUES


def is_enabled(name):
    """True when the knob carries an enable spelling
    (:data:`ENABLED_VALUES`); unset/empty is NOT enabled — the pattern of
    every off-by-default opt-in (tracing)."""
    return get_str(name).lower() in ENABLED_VALUES


def get_int(name, default, floor=None):
    """Integer value of a registered knob; unparseable values log a
    warning and fall back to ``default``; ``floor`` clamps from below."""
    text = get_str(name)
    value = default
    if text:
        try:
            value = int(text)
        except ValueError:
            logger.warning('Unparseable %s=%r; using %r', name, text,
                           default)
            value = default
    if floor is not None and value is not None:
        value = max(floor, value)
    return value


def get_float(name, default, floor=None):
    """Float value of a registered knob; same fallback rules as
    :func:`get_int`."""
    text = get_str(name)
    value = default
    if text:
        try:
            value = float(text)
        except ValueError:
            logger.warning('Unparseable %s=%r; using %r', name, text,
                           default)
            value = default
    if floor is not None and value is not None:
        value = max(floor, value)
    return value


def set_env(name, value):
    """Write a registered knob into this process's environment (the
    benchmark CLI arming ``PETASTORM_TPU_TRACE`` before any reader
    exists). Callers still need ``telemetry.refresh()`` for already-cached
    call sites to notice."""
    _check(name)
    os.environ[name] = value
