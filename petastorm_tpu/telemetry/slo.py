"""SLO plane: declarative objectives, multi-window burn rates, error
budgets.

The standing-daemon posture (ROADMAP north star; the tf.data service
paper's shared-fleet argument) needs more than anomaly heuristics: an
operator states what the pipeline MUST deliver and the plane accounts
for how fast reality is eating the allowance. One knob holds the spec::

    PETASTORM_TPU_SLO='rows_per_sec>=40000;queue_wait_p99<=50ms;'
                      'append_staleness<=30s;h2d_overlap>=0.3'

Each clause is ``target op threshold[unit]`` (``>=``/``<=``; ``ms``/``s``
units normalize to seconds). The registered targets read the closed
rollup windows the :class:`~petastorm_tpu.telemetry.timeseries
.ObsCollector` already produces:

* ``rows_per_sec`` — the window's throughput proxy;
* ``queue_wait_p99`` — the ``queue_wait`` stage-duration p99 (the
  consumer-visible latency of one pull);
* ``append_staleness`` — the ``petastorm_tpu_append_staleness_s`` gauge
  the :class:`~petastorm_tpu.write.append.AppendFollower` publishes (the
  PR 18 bounded-staleness bound, now measurable);
* ``h2d_overlap`` — the staging arena's per-window overlap share.

Accounting is the SRE multi-window burn-rate scheme: a window where the
target misses its threshold is a *bad window*; the error budget allows
``_BUDGET_FRAC`` of windows bad; the *burn rate* is the observed bad
fraction over the budget, tracked over a short (fast-burn) and a long
(budget) horizon. A breach — both horizons burning — fires the
edge-triggered ``slo_breach`` anomaly (runbook-keyed like every
ANOMALY_KINDS member), increments
``petastorm_tpu_slo_breach_windows_total{target=…}`` per bad window, and
publishes ``petastorm_tpu_slo_budget_remaining{target=…}`` so dashboards
see the budget drain before the breach. ``/health`` carries the live
:func:`slo_section` on every mounted component, and the service daemon
reads :func:`qos_weight_advice` to advise per-job QoS weight rebinding
(advice recorded, not yet steering).
"""

import logging
import threading
import collections

from petastorm_tpu.telemetry import knobs
from petastorm_tpu.telemetry.registry import get_registry, metric_key
from petastorm_tpu.telemetry.spans import (
    STAGE_DURATION, STAGE_SECONDS, metrics_disabled,
)

logger = logging.getLogger(__name__)

SLO_BREACH_WINDOWS = 'petastorm_tpu_slo_breach_windows_total'
SLO_BUDGET_REMAINING = 'petastorm_tpu_slo_budget_remaining'

#: share of windows the error budget allows to be bad
_BUDGET_FRAC = 0.1
#: fast-burn horizon (windows) — catches a sharp regression quickly
_SHORT_WINDOWS = 12
#: budget horizon (windows) — the denominator of the budget accounting
_LONG_WINDOWS = 60
#: short-horizon burn must exceed this multiple of the budget rate (the
#: "fast burn" arm of the multi-window rule)
_FAST_BURN = 2.0
#: evaluated windows before a breach may fire: with one sample both
#: horizons read 100% bad, so an un-warmed policy would page on the
#: first rough window of every run
_MIN_WINDOWS = 5

_QUEUE_WAIT_P99_KEY = metric_key(STAGE_DURATION, {'stage': 'queue_wait'})
_APPEND_STALENESS = 'petastorm_tpu_append_staleness_s'
_STAGE_FILL_KEY = metric_key(STAGE_SECONDS, {'stage': 'stage_fill'})
_H2D_DISPATCH_KEY = metric_key(STAGE_SECONDS, {'stage': 'h2d_dispatch'})
_H2D_READY_KEY = metric_key(STAGE_SECONDS, {'stage': 'h2d_ready'})


def _resolve_rows_per_sec(window):
    return window.get('throughput')


def _resolve_queue_wait_p99(window):
    q = window.get('quantiles', {}).get(_QUEUE_WAIT_P99_KEY)
    return q.get('p99') if q else None


def _resolve_append_staleness(window):
    return window.get('gauges', {}).get(_APPEND_STALENESS)


def _resolve_h2d_overlap(window):
    rates = window.get('rates', {})
    fill = rates.get(_STAGE_FILL_KEY, 0.0)
    dispatch = rates.get(_H2D_DISPATCH_KEY, 0.0)
    ready = rates.get(_H2D_READY_KEY, 0.0)
    total = fill + dispatch + ready
    if not total:
        return None
    return 1.0 - ready / total


_RESOLVERS = {
    'rows_per_sec': _resolve_rows_per_sec,
    'queue_wait_p99': _resolve_queue_wait_p99,
    'append_staleness': _resolve_append_staleness,
    'h2d_overlap': _resolve_h2d_overlap,
}


def parse_spec(text):
    """``[{'target', 'op', 'threshold'}, ...]`` from one spec string;
    unknown targets and unparseable clauses are warned about and dropped
    (a typo'd clause must not take the whole plane down)."""
    targets = []
    for clause in (text or '').split(';'):
        clause = clause.strip()
        if not clause:
            continue
        op = None
        for candidate in ('>=', '<='):
            if candidate in clause:
                op = candidate
                break
        if op is None:
            logger.warning('SLO clause %r has no >=/<= operator; dropped',
                           clause)
            continue
        name, raw = (part.strip() for part in clause.split(op, 1))
        if name not in _RESOLVERS:
            logger.warning('SLO clause %r names unknown target %r '
                           '(known: %s); dropped', clause, name,
                           ', '.join(sorted(_RESOLVERS)))
            continue
        scale = 1.0
        if raw.endswith('ms'):
            raw, scale = raw[:-2], 1e-3
        elif raw.endswith('s'):
            raw = raw[:-1]
        try:
            threshold = float(raw) * scale
        except ValueError:
            logger.warning('SLO clause %r has unparseable threshold; '
                           'dropped', clause)
            continue
        targets.append({'target': name, 'op': op, 'threshold': threshold})
    return targets


class _TargetState:
    __slots__ = ('spec', 'short', 'long', 'last_value', 'breaching',
                 'bad_total', 'eval_total')

    def __init__(self, spec):
        self.spec = spec
        self.short = collections.deque(maxlen=_SHORT_WINDOWS)
        self.long = collections.deque(maxlen=_LONG_WINDOWS)
        self.last_value = None
        self.breaching = False
        self.bad_total = 0
        self.eval_total = 0


class SloPolicy:
    """One parsed spec, evaluated window-by-window with per-target
    burn-rate state. Thread-safe enough for its use: the collector's
    sampler thread writes, scrape handlers read a consistent-at-a-glance
    section."""

    def __init__(self, targets):
        self.targets = [_TargetState(spec) for spec in targets]
        self._lock = threading.Lock()

    def observe(self, window):
        """Evaluate one closed rollup window; returns the verdict record
        (also the flight-recorder log line) or None when no target had a
        resolvable value. Fires the edge-triggered ``slo_breach`` anomaly
        when a target's short AND long horizons burn over budget."""
        from petastorm_tpu.telemetry.timeseries import record_anomaly
        verdicts = []
        with self._lock:
            for state in self.targets:
                spec = state.spec
                value = _RESOLVERS[spec['target']](window)
                if value is None:
                    continue
                state.last_value = value
                bad = (value < spec['threshold'] if spec['op'] == '>='
                       else value > spec['threshold'])
                state.short.append(bad)
                state.long.append(bad)
                state.eval_total += 1
                if bad:
                    state.bad_total += 1
                    if not metrics_disabled():
                        get_registry().counter(
                            SLO_BREACH_WINDOWS,
                            target=spec['target']).inc()
                short_frac = (sum(state.short) / len(state.short)
                              if state.short else 0.0)
                long_frac = (sum(state.long) / len(state.long)
                             if state.long else 0.0)
                remaining = max(0.0, 1.0 - long_frac / _BUDGET_FRAC)
                if not metrics_disabled():
                    get_registry().gauge(
                        SLO_BUDGET_REMAINING,
                        target=spec['target']).set(round(remaining, 4))
                burning = (len(state.long) >= _MIN_WINDOWS
                           and short_frac >= _FAST_BURN * _BUDGET_FRAC
                           and long_frac >= _BUDGET_FRAC)
                detail = {
                    'target': spec['target'],
                    'op': spec['op'],
                    'threshold': spec['threshold'],
                    'value': round(float(value), 6),
                    'bad': bad,
                    'short_burn': round(short_frac / _BUDGET_FRAC, 3),
                    'long_burn': round(long_frac / _BUDGET_FRAC, 3),
                    'budget_remaining': round(remaining, 4),
                    'breaching': burning,
                }
                if burning and not state.breaching:
                    record_anomaly('slo_breach', detail=dict(detail),
                                   window_start=window.get('start'))
                state.breaching = burning
                verdicts.append(detail)
        if not verdicts:
            return None
        return {'ts': window.get('start'), 'targets': verdicts}

    def section(self):
        """The ``/health``/report rendering: per-target spec, last value,
        burn rates and budget remaining."""
        out = []
        with self._lock:
            for state in self.targets:
                spec = state.spec
                short_frac = (sum(state.short) / len(state.short)
                              if state.short else 0.0)
                long_frac = (sum(state.long) / len(state.long)
                             if state.long else 0.0)
                out.append({
                    'target': spec['target'],
                    'op': spec['op'],
                    'threshold': spec['threshold'],
                    'last_value': (round(float(state.last_value), 6)
                                   if state.last_value is not None
                                   else None),
                    'windows_evaluated': state.eval_total,
                    'windows_bad': state.bad_total,
                    'short_burn': round(short_frac / _BUDGET_FRAC, 3),
                    'long_burn': round(long_frac / _BUDGET_FRAC, 3),
                    'budget_remaining': round(
                        max(0.0, 1.0 - long_frac / _BUDGET_FRAC), 4),
                    'breaching': state.breaching,
                })
        return {'budget_frac': _BUDGET_FRAC,
                'short_windows': _SHORT_WINDOWS,
                'long_windows': _LONG_WINDOWS,
                'targets': out}


_policy_lock = threading.Lock()
_policy = None
_policy_spec = None


def get_policy():
    """The process-wide policy parsed from ``PETASTORM_TPU_SLO``, or None
    when the knob is empty. Re-parsed only when the spec text changes, so
    burn-rate state survives unrelated ``telemetry.refresh()`` calls."""
    global _policy, _policy_spec
    text = knobs.get_str('PETASTORM_TPU_SLO')
    with _policy_lock:
        if text != _policy_spec:
            _policy_spec = text
            targets = parse_spec(text) if text else []
            _policy = SloPolicy(targets) if targets else None
        return _policy


def observe_window(window):
    """Evaluate the active policy against one closed window (the
    ObsCollector tick hook); None when no policy is armed."""
    policy = get_policy()
    if policy is None:
        return None
    return policy.observe(window)


def slo_section():
    """The live SLO view for ``/health`` and ``pipeline_report()`` —
    None when no spec is armed, so SLO-less runs keep their shapes."""
    policy = get_policy()
    if policy is None:
        return None
    return policy.section()


def qos_weight_advice(qos_entries, slo=None):
    """Per-job QoS weight advice for the daemon's rebinding loop.

    ``qos_entries`` is the dispatcher's ``stats()['qos']`` list
    (``worker_share`` vs ``target_share`` per job). A job starved below
    its declared share while the fleet's SLO budget is burning should be
    rebound heavier; a job holding more than its share while budgets
    burn is the donor. With budgets intact the advice is ``ok`` — weight
    churn without an objective at risk is noise. Advice only: the daemon
    records it in ``/health``, the operator (or a later PR) acts."""
    if slo is None:
        slo = slo_section()
    burning = bool(slo) and any(t['breaching'] for t in slo['targets'])
    advice = []
    for entry in qos_entries or []:
        worker_share = entry.get('worker_share') or 0.0
        target_share = entry.get('target_share') or 0.0
        gap = target_share - worker_share
        if burning and gap > 0.05:
            verdict = 'raise_weight'
        elif burning and gap < -0.05:
            verdict = 'lower_weight'
        else:
            verdict = 'ok'
        advice.append({'job_id': entry.get('job_id'),
                       'name': entry.get('name'),
                       'worker_share': round(worker_share, 4),
                       'target_share': round(target_share, 4),
                       'advice': verdict})
    return advice


def refresh_slo():
    """Knob-refresh hook (``telemetry.refresh()``): re-resolve the spec;
    an unchanged spec keeps its burn-rate state."""
    get_policy()


def _reset_for_tests():
    global _policy, _policy_spec
    with _policy_lock:
        _policy = None
        _policy_spec = None
