"""Per-item distributed tracing: trace contexts, activation, dump hooks.

PR 3's telemetry says *which stage* is slow on average; this layer says
*which row-group, on which worker, spent its time where* — the per-element
event traces the tf.data papers use to localize input-bound stalls on a
timeline. Every ventilated work item gets a :class:`TraceContext` (trace
id, item sequence, epoch, shard) minted at the ventilator; the context
rides the channels the pipeline already has — the pools' ventilate
queues and the service protocol's WORK frames, as a reserved
``_trace_ctx`` kwarg (:data:`TRACE_CTX_KEY`) the pools strip before
``worker.process`` — and worker-side events travel back piggybacked on
the same metric-delta frames the pools already ship (process-pool
markers, service DONE messages). Consumer-side events (``queue_wait``,
``collate``, ``h2d``, the dispatcher's dispatch/re-ventilation/dedup
instants) land in the same per-process flight recorder
(:mod:`~petastorm_tpu.telemetry.recorder`), so one export shows the whole
distributed life of an item — including both attempts of a re-ventilated
item after a worker death, with the single deduped completion marked.

No-op discipline (the overhead contract): with ``PETASTORM_TPU_TRACE``
unset/0 — the default — :func:`mint` is one cached-boolean check
returning None, :func:`activate`/:func:`attempt` on a None context return
a shared do-nothing singleton, and the metrics spans never see a trace
hook; the hot path pays exactly what it paid before this module existed
(enforced by ``tests/test_tracing.py``). Sampling
(``PETASTORM_TPU_TRACE_SAMPLE=1/N``) is deterministic on the item
sequence number, so the consumer can re-derive a result's context
(:func:`ctx_for`) without any wire change on the result path.
"""

import atexit
import collections
import logging
import os
import threading
import time
import uuid

from petastorm_tpu.analysis.contracts import EVENT_NAMES  # noqa: F401
from petastorm_tpu.telemetry import knobs, spans
from petastorm_tpu.telemetry.recorder import export_chrome_trace, get_recorder

logger = logging.getLogger(__name__)

#: reserved kwarg the ventilator injects into sampled work items and every
#: pool flavor strips (and activates) before calling ``worker.process``
TRACE_CTX_KEY = '_trace_ctx'

TraceContext = collections.namedtuple(
    'TraceContext', ('trace_id', 'item_seq', 'epoch', 'shard'))

# knob caches (refresh_trace() re-reads); None = not yet resolved
_enabled = None
_stride = None
# per-process run id: part of every minted trace id, so two readers (or a
# rerun) in one process never collide
_run_id = uuid.uuid4().hex[:8]

_state = threading.local()     # .ctx / .track of the active item, if any


def trace_enabled():
    """True when ``PETASTORM_TPU_TRACE`` turns per-item tracing on."""
    global _enabled
    if _enabled is None:
        _enabled = knobs.is_enabled('PETASTORM_TPU_TRACE')
        if _enabled:
            _install_dump_hooks()
    return _enabled


def sample_stride():
    """N of ``PETASTORM_TPU_TRACE_SAMPLE=1/N`` (accepts a plain ``N``
    too): every N-th ventilated item is traced. Default 1 (every item)."""
    global _stride
    if _stride is None:
        raw = knobs.get_str('PETASTORM_TPU_TRACE_SAMPLE')
        stride = 1
        if raw:
            try:
                stride = int(raw.split('/', 1)[1] if '/' in raw else raw)
            except ValueError:
                logger.warning('Unparseable PETASTORM_TPU_TRACE_SAMPLE=%r; '
                               'tracing every item', raw)
            if stride < 1:
                stride = 1
        _stride = stride
    return _stride


def refresh_trace():
    """Re-read every trace knob (tests, long-lived processes flipping the
    env). Part of :func:`petastorm_tpu.telemetry.refresh`."""
    global _enabled, _stride
    _enabled = None
    _stride = None
    global _autodump_fired, _autodump_last_check
    _autodump_fired = False
    _autodump_last_check = 0.0
    spans.set_trace_hook(None)
    # refresh() is a main-thread call in real entry points: the chance to
    # (re)arm the SIGUSR1/atexit dump hooks for a just-set dump path
    _install_dump_hooks()


def _reset_for_tests():
    """Fresh run id + knob caches + deactivated span hook."""
    global _run_id
    refresh_trace()
    _run_id = uuid.uuid4().hex[:8]
    _state.ctx = None
    _state.track = None


# -- context mint / rederivation ---------------------------------------------


def _trace_id(item_seq, epoch):
    return '%s-e%s-i%s' % (_run_id, 0 if epoch is None else epoch, item_seq)


def mint(item_seq, epoch=None, shard=None):
    """Trace context for one ventilated item, or None when tracing is off
    or the item is not sampled. Called by the ventilator, consumer side."""
    if not trace_enabled():
        return None
    if item_seq % sample_stride():
        return None
    return TraceContext(_trace_id(item_seq, epoch), item_seq, epoch, shard)


def ctx_for(item_seq, epoch=None, shard=None):
    """Re-derive the context :func:`mint` produced for ``item_seq`` in the
    SAME process (sampling is deterministic on the sequence number, and
    the trace id is arithmetic over the process run id) — how the
    consumer tags ``queue_wait``/staging events with the trace id minted
    at ventilation without the result path carrying anything extra."""
    if item_seq is None:
        return None
    return mint(item_seq, epoch, shard)


def current_context():
    return getattr(_state, 'ctx', None)


def current_trace_id():
    ctx = current_context()
    return ctx.trace_id if ctx is not None else None


# -- activation ---------------------------------------------------------------


class _NoopActivation:
    """Shared do-nothing context manager for untraced items."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        return False


_NOOP_ACTIVATION = _NoopActivation()


class _Activation:
    __slots__ = ('_ctx', '_track', '_prev')

    def __init__(self, ctx, track):
        self._ctx = ctx
        self._track = track

    def __enter__(self):
        self._prev = (getattr(_state, 'ctx', None),
                      getattr(_state, 'track', None))
        _state.ctx = self._ctx
        _state.track = self._track if self._track is not None \
            else self._prev[1]
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        _state.ctx, _state.track = self._prev
        return False


class _Attempt(_Activation):
    """Activation that also records one ``attempt`` complete event — the
    per-worker span covering the whole ``worker.process`` call."""

    __slots__ = ('_t0',)

    def __enter__(self):
        super().__enter__()
        self._t0 = time.time()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.time() - self._t0
        ctx, track = self._ctx, _state.track
        super().__exit__(exc_type, exc_val, exc_tb)
        # worker rides in args too (not just the track label): consumers
        # of the event list — slowest_items, the benchmark's printout —
        # need it without reconstructing the track interning
        record_complete('attempt', self._t0, dur, ctx, track,
                        worker=track,
                        error=exc_type.__name__ if exc_type else None)
        return False


def activate(ctx, track=None):
    """Make ``ctx`` the thread's active trace context for the block: stage
    spans (io/decode/...) executed inside attach their events to it. A
    None ``ctx`` returns a shared no-op."""
    if ctx is None:
        return _NOOP_ACTIVATION
    _ensure_span_hook()
    return _Activation(ctx, track)


def attempt(ctx, worker_label):
    """:func:`activate` plus an ``attempt`` event spanning the block —
    what every pool flavor wraps ``worker.process`` in. ``worker_label``
    becomes the timeline track (one track per worker)."""
    if ctx is None:
        return _NOOP_ACTIVATION
    _ensure_span_hook()
    return _Attempt(ctx, worker_label)


# -- event recording ----------------------------------------------------------


def _ctx_args(ctx, extra):
    args = {'trace_id': ctx.trace_id, 'item': ctx.item_seq}
    if ctx.epoch is not None:
        args['epoch'] = ctx.epoch
    if ctx.shard is not None:
        args['shard'] = ctx.shard
    for key, value in extra.items():
        if value is not None:
            args[key] = value
    return args


def record_complete(name, wall_start, dur_s, ctx=None, track=None, **extra):
    """One Chrome 'X' (complete) event on ``ctx``'s trace. ``wall_start``
    is ``time.time()`` at the beginning; no-op without a context."""
    ctx = ctx if ctx is not None else current_context()
    if ctx is None:
        return
    if track is None:
        track = getattr(_state, 'track', None) or 'main'
    get_recorder().add({
        'name': name, 'ph': 'X', 'cat': 'petastorm_tpu',
        'ts': wall_start * 1e6, 'dur': dur_s * 1e6,
        'pid': os.getpid(), 'tid': track,
        'args': _ctx_args(ctx, extra),
    })


def record_instant(name, ctx, track, **extra):
    """One Chrome 'i' (instant) event — dispatcher lifecycle markers
    (dispatch / reventilate / done / duplicate_done)."""
    if ctx is None:
        return
    get_recorder().add({
        'name': name, 'ph': 'i', 's': 'p', 'cat': 'petastorm_tpu',
        'ts': time.time() * 1e6,
        'pid': os.getpid(), 'tid': track,
        'args': _ctx_args(ctx, extra),
    })


def _span_trace_hook(stage, elapsed_s):
    """Installed into :mod:`spans` while a trace context is active in this
    process: every canonical stage span also lands a trace event."""
    ctx = getattr(_state, 'ctx', None)
    if ctx is None:
        return
    record_complete(stage, time.time() - elapsed_s, elapsed_s, ctx)


_hook_installed = False


def _ensure_span_hook():
    global _hook_installed
    if not _hook_installed or spans._trace_hook is None:
        spans.set_trace_hook(_span_trace_hook)
        _hook_installed = True


# -- dumps --------------------------------------------------------------------


def dump_trace(path):
    """Export the process-wide flight recorder as Chrome trace-event JSON
    at ``path`` (``Reader.dump_trace`` / ``JaxLoader.dump_trace`` and the
    benchmark's ``--trace-out`` land here). Returns the event count."""
    count = export_chrome_trace(path)
    logger.info('Wrote %d trace event(s) to %s', count, path)
    return count


def _dump_path():
    return knobs.get_str('PETASTORM_TPU_TRACE_DUMP') or None


_atexit_installed = False
_signal_installed = False
_autodump_fired = False
_autodump_last_check = 0.0


def _dump_if_any(signum=None, frame=None):
    path = _dump_path()
    if path and len(get_recorder()):
        try:
            dump_trace(path)
        except Exception:  # noqa: BLE001 - a dump must never crash
            logger.warning('Trace dump to %s failed', path, exc_info=True)


def _install_dump_hooks():
    """Crash-dump plumbing, armed when ``PETASTORM_TPU_TRACE_DUMP`` names
    a path: an ``atexit`` dump plus a SIGUSR1 handler (dump NOW, without
    stopping the run — poke a live job with ``kill -USR1 <pid>``).

    Signal handlers can only be installed from the MAIN thread, and the
    first ``trace_enabled()`` evaluation usually happens on a ventilator
    or staging thread — so this runs once at module import (the package
    import is main-thread in every real entry point) and is retried from
    later main-thread calls; until it lands, an unhandled SIGUSR1 would
    KILL the process, which is why set-at-start is the documented
    contract for ``PETASTORM_TPU_TRACE_DUMP``."""
    global _atexit_installed, _signal_installed
    if _dump_path() is None:
        return
    if not _atexit_installed:
        _atexit_installed = True
        atexit.register(_dump_if_any)
    if not _signal_installed:
        try:
            import signal
            signal.signal(signal.SIGUSR1, _dump_if_any)
            _signal_installed = True
        except (ValueError, OSError, AttributeError):
            # not the main thread, or no SIGUSR1 on this platform: the
            # atexit dump still fires; retried on later main-thread calls
            logger.debug('SIGUSR1 trace-dump handler not installed yet')


_install_dump_hooks()


def autodump_windows():
    return knobs.get_int('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', 6, floor=1)


def maybe_autodump():
    """Dump the flight recorder once, automatically, when the stall
    attributor has flagged N consecutive producer-bound windows
    (``PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS``, default 6 ≈ 3s at the
    default window) — the "my TPU is idle" artifact captured from inside
    the run, without re-running. Armed only while tracing is enabled AND
    ``PETASTORM_TPU_TRACE_DUMP`` names a path; throttled to one
    windows-scan per second. Called from the reader's pull path."""
    global _autodump_fired, _autodump_last_check
    if _autodump_fired or not trace_enabled():
        return False
    path = _dump_path()
    if path is None:
        return False
    now = time.monotonic()
    if now - _autodump_last_check < 1.0:
        return False
    _autodump_last_check = now
    from petastorm_tpu.telemetry.stall import PRODUCER_BOUND, get_attributor
    need = autodump_windows()
    windows = get_attributor().windows(include_current=False)[-need:]
    if len(windows) < need or any(w['verdict'] != PRODUCER_BOUND
                                  for w in windows):
        return False
    _autodump_fired = True
    logger.warning('%d consecutive producer-bound windows: auto-dumping '
                   'trace to %s (the pipeline is input-bound; see '
                   'docs/troubleshoot.md)', need, path)
    try:
        dump_trace(path)
    except Exception:  # noqa: BLE001 - telemetry is advisory
        logger.warning('Trace auto-dump to %s failed', path, exc_info=True)
    return True
