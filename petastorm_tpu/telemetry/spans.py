"""Per-stage timing spans over the canonical pipeline stages.

``with span('decode'): ...`` accumulates, per stage, into the process-wide
registry: ``petastorm_tpu_stage_seconds_total{stage=...}`` (counter),
``petastorm_tpu_stage_calls_total{stage=...}`` (counter) and
``petastorm_tpu_stage_duration_seconds{stage=...}`` (histogram). Worker-side
spans (io/decode/filter/transform) record into the WORKER process's
registry and ride the pool's delta channel back to the consumer.

``PETASTORM_TPU_METRICS=0`` (or ``false``/``off``) compiles every span to a
shared no-op singleton — no clock reads, no dict lookups, no metric
updates — so the hot path pays one cached boolean check per span site
(docs/env_knobs.md; enforced by
``tests/test_telemetry.py::test_disabled_spans_are_noops``).
"""

import time

from petastorm_tpu.analysis.contracts import STAGES  # noqa: F401 - canonical
from petastorm_tpu.telemetry import knobs
from petastorm_tpu.telemetry.knobs import DISABLED_VALUES  # noqa: F401
from petastorm_tpu.telemetry.registry import get_registry, on_registry_reset

STAGE_SECONDS = 'petastorm_tpu_stage_seconds_total'
STAGE_CALLS = 'petastorm_tpu_stage_calls_total'
STAGE_DURATION = 'petastorm_tpu_stage_duration_seconds'

# resolved once (refresh_enabled() re-reads, for tests and long-lived
# processes that flip the knob); None = not yet resolved
_disabled = None


def metrics_disabled():
    """True when ``PETASTORM_TPU_METRICS`` disables telemetry."""
    global _disabled
    if _disabled is None:
        _disabled = knobs.is_disabled('PETASTORM_TPU_METRICS')
    return _disabled


def refresh_enabled():
    """Re-read ``PETASTORM_TPU_METRICS`` (next span sees the new value)."""
    global _disabled
    _disabled = None
    _stage_cache.clear()


class _NoopSpan:
    """Shared do-nothing context manager for disabled telemetry."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        return False


_NOOP_SPAN = _NoopSpan()

# Trace hook: None until per-item tracing activates a context in this
# process (tracing.py installs it lazily), after which every span exit also
# offers its (stage, elapsed) to the flight recorder. A module-global None
# check is the entire cost when tracing is off — the span hot path keeps
# its PR 3 shape (enforced by tests/test_tracing.py's overhead guard).
_trace_hook = None


def set_trace_hook(hook):
    global _trace_hook
    _trace_hook = hook

# stage -> (seconds counter, calls counter, duration histogram); caches the
# metric-object lookups so a span's enter/exit is clock reads + three adds.
# Invalidated on registry reset (hook below): cached objects of a replaced
# registry would otherwise keep absorbing spans invisibly.
_stage_cache = {}
on_registry_reset(_stage_cache.clear)


def _stage_metrics(stage):
    metrics = _stage_cache.get(stage)
    if metrics is None:
        registry = get_registry()
        metrics = (registry.counter(STAGE_SECONDS, stage=stage),
                   registry.counter(STAGE_CALLS, stage=stage),
                   registry.histogram(STAGE_DURATION, stage=stage))
        _stage_cache[stage] = metrics
    return metrics


class _Span:
    __slots__ = ('_stage', '_metrics', '_t0')

    def __init__(self, stage, metrics):
        self._stage = stage
        self._metrics = metrics

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        elapsed = time.perf_counter() - self._t0
        seconds, calls, duration = self._metrics
        seconds.inc(elapsed)
        calls.inc()
        duration.observe(elapsed)
        if _trace_hook is not None:
            _trace_hook(self._stage, elapsed)
        return False


def span(stage):
    """Context manager timing one ``stage`` occurrence.

    Stage names outside :data:`STAGES` are allowed (library extensions,
    tests) but the canonical names are what :func:`~petastorm_tpu.telemetry
    .pipeline_report` groups by. Returns the shared no-op singleton when
    telemetry is disabled."""
    if metrics_disabled():
        return _NOOP_SPAN
    return _Span(stage, _stage_metrics(stage))
