"""Critical-path engine: per-item lifelines, self vs overlapped time,
what-if projections.

The tracing layer (PR 4) records *where each row-group's time went*; this
module answers the question the tf.data paper (Murray et al., 2021) puts
at the center of pipeline tuning: *what would it be worth to fix*. A
stage that spends 10 s of wall time fully overlapped with decode costs
the epoch nothing — making it faster buys nothing — while 1 s of
critical-path self-time is 1 s off the epoch. The engine reconstructs
the delivered items' lifelines from the flight recorder's complete
events (ventilate → readahead_fetch → io → decode/decode_fused →
filter/transform → queue_wait → collate → h2d_dispatch → h2d_ready, plus
the write/mixture-plane stages), attributes every instant of the traced
span to exactly ONE active stage (a priority sweep: productive upstream
work wins over waits, so ``decode`` keeps its self-time while the
``queue_wait`` overlapping it reads as slack), and projects what-if
scenarios from the slack model::

    saving(stage, k x faster) = self_time(stage) * (1 - 1/k)

because only self-time is load-bearing — the overlapped share was
already hidden behind other work.

Three surfaces: ``pipeline_report()['critical_path']`` (the export
section), the obs server's ``/critpath`` route (the same analysis over
whatever the local recorder holds — a Reader process shows the read
plane, a JaxLoader process adds the staging stages, and the service
dispatcher — whose DONE-frame delta merges already fold worker-side
events into its recorder — shows the fleet-merged view), and
:func:`crosscheck_autotuner`, the decision-quality audit: the engine's
bottleneck verdict is compared against the staging autotuner's recent
threshold-heuristic decisions and the (dis)agreement is counted into
``petastorm_tpu_critpath_agreement_total{verdict=…}`` — evidence for
(not yet steering of) the PR 14/15 control loops.

Works only on what the recorder holds: ``PETASTORM_TPU_TRACE=1`` must
have been on, and sampling (``PETASTORM_TPU_TRACE_SAMPLE``) scales the
analysis the same way it scales recording cost.
"""

import logging

from petastorm_tpu.analysis.contracts import STAGES
from petastorm_tpu.telemetry.recorder import get_recorder
from petastorm_tpu.telemetry.registry import get_registry
from petastorm_tpu.telemetry.spans import metrics_disabled

logger = logging.getLogger(__name__)

#: decision-quality cross-check outcomes vs the staging autotuner
CRITPATH_AGREEMENT = 'petastorm_tpu_critpath_agreement_total'

#: sweep-line attribution priority, highest first: when several stages
#: are active over the same instant, the EARLIEST-listed one takes the
#: instant as self-time and the rest read as overlapped slack. Productive
#: compute outranks I/O (a fetch running under decode is the overlap
#: working as designed), I/O outranks staging bookkeeping, and the pure
#: waits (``queue_wait``, ``ventilate``) come last — they are never
#: load-bearing while anything else runs.
_PRIORITY = (
    'decode_fused', 'decode', 'late_materialize', 'transform', 'filter',
    'collate', 'pack', 'encode', 'write_flush', 'compact', 'cache_fill',
    'cache_hit_read', 'io', 'readahead_fetch', 'rowgroup_prune',
    'stage_fill', 'h2d_dispatch', 'h2d', 'h2d_ready', 'autotune',
    'ventilate', 'queue_wait',
)
_RANK = {stage: i for i, stage in enumerate(_PRIORITY)}
# any canonical stage missing from the explicit order sorts after it
_RANK.update({s: len(_PRIORITY) + i for i, s in enumerate(STAGES)
              if s not in _RANK})

#: compute stages deeper readahead can hide I/O behind (the bound of the
#: "readahead depth +4" scenario: prefetch converts blocking io into
#: overlapped time, but only while there is compute to hide it behind)
_COMPUTE_STAGES = ('decode', 'decode_fused', 'late_materialize',
                   'transform', 'filter', 'collate')

#: what-if speedup factor of the per-stage scenarios
_WHATIF_FACTOR = 2.0
_TOP_SCENARIOS = 4


def _stage_intervals(events):
    """``[(start_us, end_us, stage), ...]`` of every complete ('X') stage
    event. ``attempt`` and the lifecycle instants are skipped: an attempt
    envelopes the worker stages recorded inside it and would double-count
    every covered instant."""
    known = set(STAGES)
    intervals = []
    for event in events:
        if event.get('ph') != 'X':
            continue
        name = event.get('name')
        if name not in known:
            continue
        start = event.get('ts', 0.0)
        dur = event.get('dur', 0.0)
        if dur <= 0:
            continue
        intervals.append((start, start + dur, name))
    return intervals


def _sweep(intervals):
    """Priority sweep-line: per-stage ``{total_us, self_us}``. Between
    every pair of adjacent interval boundaries exactly one active stage —
    the highest-priority one — is charged the segment as self-time."""
    points = []
    totals = {}
    for start, end, stage in intervals:
        points.append((start, 1, stage))
        points.append((end, -1, stage))
        totals[stage] = totals.get(stage, 0.0) + (end - start)
    points.sort(key=lambda p: (p[0], p[1]))
    active = {}
    self_us = {}
    prev_t = None
    i = 0
    n = len(points)
    while i < n:
        t = points[i][0]
        if prev_t is not None and active and t > prev_t:
            winner = min(active, key=lambda s: _RANK.get(s, 10 ** 6))
            self_us[winner] = self_us.get(winner, 0.0) + (t - prev_t)
        while i < n and points[i][0] == t:
            _, delta, stage = points[i]
            count = active.get(stage, 0) + delta
            if count <= 0:
                active.pop(stage, None)
            else:
                active[stage] = count
            i += 1
        prev_t = t
    return totals, self_us


def _what_if(stages, span_s):
    """Slack-model projections, best first. Per-stage "k x faster"
    scenarios over the top self-time stages, plus the "readahead depth
    +4" overlap scenario (I/O self-time hidden behind the available
    compute self-time)."""
    scenarios = []
    by_self = sorted(stages.items(), key=lambda kv: -kv[1]['self_s'])
    for stage, info in by_self[:_TOP_SCENARIOS]:
        saving = info['self_s'] * (1.0 - 1.0 / _WHATIF_FACTOR)
        if saving <= 0:
            continue
        scenarios.append({
            'scenario': '%s %gx faster' % (stage, _WHATIF_FACTOR),
            'stage': stage,
            'factor': _WHATIF_FACTOR,
            'saving_s': round(saving, 6),
            'epoch_delta_pct': round(-100.0 * saving / span_s, 2),
        })
    io_self = stages.get('io', {}).get('self_s', 0.0)
    compute_self = sum(stages.get(s, {}).get('self_s', 0.0)
                       for s in _COMPUTE_STAGES)
    hideable = min(io_self, compute_self)
    if hideable > 0:
        scenarios.append({
            'scenario': 'readahead depth +4',
            'stage': 'io',
            'factor': None,
            'saving_s': round(hideable, 6),
            'epoch_delta_pct': round(-100.0 * hideable / span_s, 2),
        })
    scenarios.sort(key=lambda s: s['saving_s'], reverse=True)
    return scenarios


def analyze(events=None):
    """The critical-path report over ``events`` (default: the process
    flight recorder), or None when no stage events exist.

    ``stages`` maps each observed stage to its summed wall time
    (``total_s``), the share of the traced span where it was the
    highest-priority active work (``self_s``, the critical-path time),
    the remainder (``overlap_s``, slack hidden behind other stages), and
    ``self_share`` of the span. ``what_if`` ranks the slack-model
    scenarios; ``recommendation`` is the top one as a sentence.
    """
    if events is None:
        events = get_recorder().snapshot()
    intervals = _stage_intervals(events)
    if not intervals:
        return None
    totals, self_us = _sweep(intervals)
    span_us = (max(end for _, end, _ in intervals)
               - min(start for start, _, _ in intervals))
    span_s = max(span_us / 1e6, 1e-9)
    items = len({e['args'].get('trace_id') for e in events
                 if e.get('ph') == 'X' and isinstance(e.get('args'), dict)
                 and e['args'].get('trace_id')})
    stages = {}
    for stage, total in totals.items():
        self_s = self_us.get(stage, 0.0) / 1e6
        total_s = total / 1e6
        stages[stage] = {
            'total_s': round(total_s, 6),
            'self_s': round(self_s, 6),
            'overlap_s': round(max(total_s - self_s, 0.0), 6),
            'self_share': round(self_s / span_s, 4),
        }
    bottleneck = max(stages, key=lambda s: stages[s]['self_s'])
    what_if = _what_if(stages, span_s)
    recommendation = None
    if what_if:
        top = what_if[0]
        recommendation = '%s => epoch %+.1f%%' % (top['scenario'],
                                                  top['epoch_delta_pct'])
    return {
        'items': items,
        'events': len(intervals),
        'span_s': round(span_s, 6),
        'bottleneck': bottleneck,
        'stages': dict(sorted(stages.items(),
                              key=lambda kv: -kv[1]['self_s'])),
        'what_if': what_if,
        'recommendation': recommendation,
    }


def predict_speedup(stage, factor, events=None, report=None):
    """Projected epoch effect of ``stage`` becoming ``factor`` x faster
    (the ground-truth drill's entry point: inject a known slowdown, ask
    the model for the reverse projection, compare against the measured
    delta). Returns ``{'saving_s', 'predicted_span_s',
    'epoch_delta_pct'}`` or None when the stage never ran."""
    if report is None:
        report = analyze(events)
    if report is None or stage not in report['stages']:
        return None
    self_s = report['stages'][stage]['self_s']
    saving = self_s * (1.0 - 1.0 / float(factor))
    span = report['span_s']
    return {
        'saving_s': round(saving, 6),
        'predicted_span_s': round(span - saving, 6),
        'epoch_delta_pct': round(-100.0 * saving / span, 2),
    }


# -- decision-quality cross-check vs the staging autotuner --------------------

#: which stage territory each autotuner action treats as the bottleneck
#: (deepen/raise = the tuner believes that side is the wall) or as slack
#: (shed/lower/restore = the tuner believes that side has headroom)
_H2D_SIDE = frozenset(('h2d', 'h2d_ready', 'h2d_dispatch', 'stage_fill'))
_IO_SIDE = frozenset(('io', 'readahead_fetch'))
_ACTION_TERRITORY = {
    'deepen_slots': ('bottleneck', _H2D_SIDE),
    'deepen_prefetch': ('bottleneck', _H2D_SIDE),
    'raise_inflight': ('bottleneck', _H2D_SIDE),
    'deepen_readahead': ('bottleneck', _IO_SIDE),
    'shed_readahead': ('slack', _IO_SIDE),
    'lower_inflight': ('slack', _H2D_SIDE),
    'shed_decode_threads': ('slack',
                            frozenset(('decode', 'decode_fused', 'io'))),
    'restore_decode_threads': ('slack', frozenset()),
}


def crosscheck_autotuner(report=None, decisions=None):
    """Compare the engine's bottleneck verdict against the staging
    autotuner's recent threshold-heuristic decisions; count each
    (dis)agreement into ``petastorm_tpu_critpath_agreement_total``.

    A *bottleneck* action (deepen/raise) agrees when the critical-path
    bottleneck lies in the stage territory the action targets; a *slack*
    action (shed/lower/restore) agrees when it does NOT. The counts are
    evidence about the heuristics' decision quality — nothing is steered
    yet. Returns the per-decision verdict list (None when either side
    has nothing to say)."""
    import sys
    if report is None:
        report = analyze()
    if report is None:
        return None
    if decisions is None:
        autotune = sys.modules.get('petastorm_tpu.jax.autotune')
        if autotune is None:
            return None
        decisions = autotune.recent_decisions(10)
    if not decisions:
        return None
    bottleneck = report['bottleneck']
    verdicts = []
    for decision in decisions:
        territory = _ACTION_TERRITORY.get(decision.get('action'))
        if territory is None:
            continue
        mode, stage_set = territory
        in_territory = bottleneck in stage_set
        agree = in_territory if mode == 'bottleneck' else not in_territory
        verdict = 'agree' if agree else 'disagree'
        verdicts.append({'action': decision.get('action'),
                         'bottleneck': bottleneck, 'verdict': verdict})
        if not metrics_disabled():
            get_registry().counter(CRITPATH_AGREEMENT,
                                   verdict=verdict).inc()
    return verdicts or None


def critpath_section(events=None):
    """The ``pipeline_report()['critical_path']`` section: the analysis
    plus the autotuner cross-check summary — None when tracing never
    recorded a stage event, so untraced runs keep their report shape."""
    report = analyze(events)
    if report is None:
        return None
    verdicts = crosscheck_autotuner(report=report)
    if verdicts:
        agree = sum(1 for v in verdicts if v['verdict'] == 'agree')
        report['autotune_crosscheck'] = {
            'decisions': len(verdicts),
            'agree': agree,
            'disagree': len(verdicts) - agree,
        }
    return report
