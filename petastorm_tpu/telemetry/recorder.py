"""Flight recorder: a bounded, lock-light per-process trace-event buffer.

Every process on the pipeline (consumer, ZMQ decode workers, service
worker servers) holds one ring of recent trace events
(:func:`get_recorder`). Worker processes drain theirs into the metrics
delta frames that already ride the pool result channels
(:func:`~petastorm_tpu.telemetry.registry.dump_delta_frame`), so by the
time anyone asks for a dump the CONSUMER's ring holds the whole
distributed timeline — bounded, always-on once tracing is enabled, and
exportable after the fact: the "why was my TPU idle two minutes ago"
artifact without re-running anything.

Events are plain dicts already shaped like Chrome trace events
(https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU):
``{'name', 'ph', 'ts', 'dur', 'pid', 'tid', 'args'}`` with ``ts``/``dur``
in microseconds of wall time (``time.time()``, so events from different
hosts/processes land on one comparable timeline) and ``tid`` a TRACK LABEL
string (e.g. ``worker-3``/``consumer``); :func:`export_chrome_trace`
interns labels to integer tids and emits ``thread_name`` metadata, giving
Perfetto one named track per worker/stage.
"""

import collections
import json
import threading

#: default ring capacity (events per process); at ~10 events per row-group
#: this covers the most recent ~2k items — minutes of timeline at
#: production rates, a few MB of small dicts
DEFAULT_CAPACITY = 20000


class FlightRecorder:
    """Bounded ring of trace events.

    Lock-light by construction: ``deque.append`` with a ``maxlen`` is a
    single atomic operation under the GIL, so the hot path (``add``) takes
    no lock at all; only the cold paths (``drain``, ``snapshot``) lock to
    get a consistent cut against concurrent appends.
    """

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._events = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def add(self, event):
        self._events.append(event)

    def add_many(self, events):
        self._events.extend(events)

    def __len__(self):
        return len(self._events)

    def snapshot(self):
        """All buffered events, oldest first (the ring keeps them)."""
        with self._lock:
            return list(self._events)

    def drain(self):
        """Pop every buffered event (worker-side flush: the batch ships on
        the pool's delta channel and must not ship twice)."""
        with self._lock:
            events = list(self._events)
            self._events.clear()
        return events

    def clear(self):
        with self._lock:
            self._events.clear()


_global_lock = threading.Lock()
_global_recorder = None


def get_recorder():
    """The process-wide flight recorder trace events accumulate in."""
    global _global_recorder
    if _global_recorder is None:
        with _global_lock:
            if _global_recorder is None:
                _global_recorder = FlightRecorder()
    return _global_recorder


def reset_recorder():
    """Swap in a fresh process-wide recorder (test isolation only)."""
    global _global_recorder
    with _global_lock:
        _global_recorder = FlightRecorder()


# -- Chrome trace-event export ------------------------------------------------


def export_chrome_trace(path_or_file, events=None):
    """Write ``events`` (default: the process-wide recorder's snapshot) as
    Chrome trace-event JSON, viewable in Perfetto (ui.perfetto.dev) or
    ``chrome://tracing``.

    Track-label ``tid`` strings are interned to integers per ``pid`` and
    announced with ``thread_name`` metadata events, so the viewer shows one
    named track per worker/stage. Returns the number of data events
    written."""
    if events is None:
        events = get_recorder().snapshot()
    tids = {}          # (pid, label) -> int tid
    out = []
    for event in events:
        pid = event.get('pid', 0)
        label = str(event.get('tid', 'main'))
        tid = tids.get((pid, label))
        if tid is None:
            tid = tids[(pid, label)] = len(tids) + 1
        record = dict(event, pid=pid, tid=tid)
        out.append(record)
    meta = [{'name': 'thread_name', 'ph': 'M', 'pid': pid, 'tid': tid,
             'args': {'name': label}}
            for (pid, label), tid in sorted(tids.items(),
                                            key=lambda kv: kv[1])]
    doc = {'traceEvents': meta + out, 'displayTimeUnit': 'ms'}
    if hasattr(path_or_file, 'write'):
        json.dump(doc, path_or_file)
    else:
        with open(path_or_file, 'w') as f:
            json.dump(doc, f)
    return len(out)


def slowest_items(events=None, n=3):
    """The ``n`` traces with the largest summed worker-side duration —
    "which row-groups were slow", straight off the recorder.

    Sums ``dur`` over complete (``ph == 'X'``) ``attempt`` events per
    trace id (one per worker-side processing of one ventilated item);
    when no attempt events exist (e.g. thread-pool runs before any pool
    wiring) it falls back to summing every complete event of the trace.
    Returns ``[(trace_id, seconds, last_args), ...]`` slowest first."""
    if events is None:
        events = get_recorder().snapshot()
    totals = {}
    args_by_id = {}
    have_attempts = any(e.get('name') == 'attempt' and e.get('ph') == 'X'
                        for e in events)
    for event in events:
        if event.get('ph') != 'X':
            continue
        if have_attempts and event.get('name') != 'attempt':
            continue
        trace_id = (event.get('args') or {}).get('trace_id')
        if trace_id is None:
            continue
        totals[trace_id] = totals.get(trace_id, 0.0) + event.get('dur', 0.0)
        args_by_id[trace_id] = event.get('args') or {}
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])[:n]
    return [(tid, dur / 1e6, args_by_id[tid]) for tid, dur in ranked]
