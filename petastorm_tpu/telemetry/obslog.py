"""Black-box flight recorder: closed windows, anomalies and SLO verdicts
on disk, size-capped.

Every in-process observability surface — the rollup ring, the anomaly
ring, the Chrome-trace recorder — dies with the process: a crashed
daemon leaves NO record of the minutes before the crash, which is
exactly when the record matters. This module is the black box: when
``PETASTORM_TPU_OBS_LOG_DIR`` names a directory, the
:class:`~petastorm_tpu.telemetry.timeseries.ObsCollector` appends each
closed window (plus any anomalies it raised, the SLO verdicts and a
periodic critical-path digest) as one JSON line to ``obslog.jsonl``
there. The file is a two-slot size-capped ring: when the live file
crosses ``PETASTORM_TPU_OBS_LOG_MB`` (default 64) it rotates to
``obslog.jsonl.1`` (replacing the previous rotation), so disk use is
bounded at ~2x the cap no matter how long the daemon runs.

``tools/obs_replay.py`` renders the post-mortem — timeline, burn report,
critical-path summary — from these files after the process is gone.

One record per line: ``{'kind': 'window'|'anomaly'|'slo'|'critpath',
'ts': ..., ...payload}``. Best-effort by design: a full disk or an
unwritable directory degrades to a logged warning once, never an
exception on the sampler thread.
"""

import json
import logging
import os
import threading
import time

from petastorm_tpu.telemetry import knobs

logger = logging.getLogger(__name__)

_LOG_NAME = 'obslog.jsonl'
_DEFAULT_CAP_MB = 64


def log_dir():
    """The armed directory, or None (= flight recording off)."""
    return knobs.get_str('PETASTORM_TPU_OBS_LOG_DIR') or None


def cap_bytes():
    return knobs.get_int('PETASTORM_TPU_OBS_LOG_MB', _DEFAULT_CAP_MB,
                         floor=1) * 1024 * 1024


class ObsLogWriter:
    """Appender over the two-slot on-disk ring; one per process."""

    def __init__(self, directory, cap=None):
        self.directory = directory
        self.path = os.path.join(directory, _LOG_NAME)
        self._cap = cap or cap_bytes()
        self._lock = threading.Lock()
        self._size = None
        self._warned = False

    def append(self, kind, record):
        """Write one record; returns True when the line landed."""
        line = json.dumps(dict(record, kind=kind), sort_keys=True,
                          default=str)
        with self._lock:
            try:
                if self._size is None:
                    os.makedirs(self.directory, exist_ok=True)
                    self._size = (os.path.getsize(self.path)
                                  if os.path.exists(self.path) else 0)
                if self._size >= self._cap:
                    os.replace(self.path, self.path + '.1')
                    self._size = 0
                with open(self.path, 'a') as f:
                    f.write(line + '\n')
                self._size += len(line) + 1
                return True
            except OSError as e:
                if not self._warned:
                    self._warned = True
                    logger.warning('obs log %s unwritable (%s); flight '
                                   'recording degraded for this process',
                                   self.path, e)
                return False


def read_log(directory):
    """Every surviving record under ``directory``, oldest first (the
    rotated slot, then the live file) — the replay tool's input. Torn
    trailing lines (a crash mid-write) are skipped, not fatal."""
    records = []
    base = os.path.join(directory, _LOG_NAME)
    for path in (base + '.1', base):
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
        # the rotated slot strictly precedes the live file in time, and
        # within a file append order is time order — no sort needed
    return records


_writer_lock = threading.Lock()
_writer = None


def get_writer():
    """The process-wide writer when the knob arms a directory, else
    None. Re-resolved when the directory changes (tests, refresh)."""
    global _writer
    directory = log_dir()
    if directory is None:
        return None
    with _writer_lock:
        if _writer is None or _writer.directory != directory:
            _writer = ObsLogWriter(directory)
        return _writer


def append(kind, record):
    """Module-level convenience: append when armed, no-op otherwise."""
    writer = get_writer()
    if writer is None:
        return False
    if 'ts' not in record:
        record = dict(record, ts=time.time())
    return writer.append(kind, record)


def refresh_obslog():
    """Knob-refresh hook: pick up a changed directory/cap next append."""
    global _writer
    with _writer_lock:
        _writer = None


def _reset_for_tests():
    refresh_obslog()
