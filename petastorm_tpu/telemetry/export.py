"""Exporters: JSONL snapshots, Prometheus text format, pipeline report.

All three read a :class:`~petastorm_tpu.telemetry.registry.MetricsRegistry`
(the process-wide one by default) and never mutate it.
"""

import json
import time

from petastorm_tpu.telemetry.registry import get_registry
from petastorm_tpu.telemetry.spans import (
    STAGE_CALLS, STAGE_SECONDS, STAGES,
)

#: stall-verdict horizon in sampling windows (~30s at the 0.5s default):
#: recent enough that startup/idle phases age out of the verdict quickly
_VERDICT_WINDOWS = 60

# -- JSONL -------------------------------------------------------------------


def write_jsonl_snapshot(path_or_file, registry=None, extra=None):
    """Append one JSON line holding the registry's full state.

    Round-trip contract (``tests/test_telemetry.py``): the parsed line's
    ``counters``/``gauges``/``histograms`` equal ``registry.snapshot()``.
    ``extra`` (a dict) rides along under its own keys for run metadata
    (benchmark args, wall time); reserved keys are not overwritten.
    """
    registry = registry or get_registry()
    record = dict(extra or {})
    record.update(registry.snapshot())
    record.setdefault('ts', time.time())
    # structured anomaly events ride every snapshot line when any were
    # recorded (live-observability plane, telemetry/timeseries.py): the
    # counters alone say HOW MANY fired, the events say WHEN and WHY
    from petastorm_tpu.telemetry import timeseries
    events = timeseries.recent_anomalies()
    if events:
        record.setdefault('anomalies', events)
    line = json.dumps(record, sort_keys=True)
    if hasattr(path_or_file, 'write'):
        path_or_file.write(line + '\n')
    else:
        with open(path_or_file, 'a') as f:
            f.write(line + '\n')


def read_jsonl_snapshots(path):
    """Parse every snapshot line of a JSONL metrics file (oldest first)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# -- Prometheus text format --------------------------------------------------


def _metric_families(keys):
    """Group snapshot keys (``name`` / ``name{labels}``) by family name,
    preserving each family's series order."""
    families = {}
    for key in sorted(keys):
        name = key.split('{', 1)[0]
        families.setdefault(name, []).append(key)
    return families


def prometheus_text(registry=None):
    """Registry state in the Prometheus text exposition format: one
    ``# TYPE`` line per family, label values already escaped (the registry
    escapes at key-construction time), histograms with CUMULATIVE
    ``_bucket`` series (``le`` ascending through ``+Inf``), ``_sum`` and
    ``_count``."""
    registry = registry or get_registry()
    snap = registry.snapshot()
    lines = []
    for name, keys in _metric_families(snap['counters']).items():
        lines.append('# TYPE %s counter' % name)
        for key in keys:
            lines.append('%s %s' % (key, _fmt(snap['counters'][key])))
    for name, keys in _metric_families(snap['gauges']).items():
        lines.append('# TYPE %s gauge' % name)
        for key in keys:
            lines.append('%s %s' % (key, _fmt(snap['gauges'][key])))
    for name, keys in _metric_families(snap['histograms']).items():
        lines.append('# TYPE %s histogram' % name)
        for key in keys:
            state = snap['histograms'][key]
            cumulative = 0
            for bound, count in zip(state['buckets'] + [float('inf')],
                                    state['counts']):
                cumulative += count
                lines.append('%s %d' % (
                    _series(key, '_bucket', le=_le(bound)), cumulative))
            lines.append('%s %s' % (_series(key, '_sum'),
                                    _fmt(state['sum'])))
            lines.append('%s %d' % (_series(key, '_count'), state['count']))
    return '\n'.join(lines) + '\n'


def _le(bound):
    if bound == float('inf'):
        return '+Inf'
    text = repr(bound)
    return text[:-2] if text.endswith('.0') else text


def _fmt(value):
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _series(key, suffix, **extra_labels):
    """``name{labels}`` → ``name<suffix>{labels + extra}``."""
    if '{' in key:
        name, labels = key.split('{', 1)
        labels = labels[:-1]
    else:
        name, labels = key, ''
    for k, v in sorted(extra_labels.items()):
        pair = '%s="%s"' % (k, v)
        labels = '%s,%s' % (labels, pair) if labels else pair
    return '%s%s{%s}' % (name, suffix, labels) if labels \
        else '%s%s' % (name, suffix)


# -- pipeline report ---------------------------------------------------------


def _label_of(key, label):
    """Value of one label in a ``name{label="x",...}`` snapshot key, or
    None when the key does not carry it. Anchored on the preceding
    ``{``/``,`` so ``srckind`` never matches a lookup for ``kind``."""
    for marker in ('{%s="' % label, ',%s="' % label):
        i = key.find(marker)
        if i < 0:
            continue
        start = i + len(marker)
        j = key.find('"', start)
        return key[start:j] if j > 0 else None
    return None


def _stage_of(key):
    """Stage label value of a ``...{stage="x"}`` key, or None."""
    return _label_of(key, 'stage')


def pipeline_report(registry=None, wall_time_s=None, baseline=None,
                    attributor=None):
    """Per-stage time breakdown + stall attribution, the rendering the
    ISSUE's acceptance gate reads.

    :param wall_time_s: when given, per-stage ``share`` is seconds/wall and
        ``attributed_fraction`` says how much of the wall the named stages
        explain (the dummy-reader benchmark asserts ≥0.95). Without it,
        shares are relative to the summed stage time (worker stages run in
        parallel threads, so their sum can legitimately exceed any wall).
    :param baseline: an earlier ``registry.snapshot()``; stage seconds are
        reported as the increase since it (scoping a report to one
        measurement window).
    :param attributor: stall attributor to read windows from (default: the
        process-wide one).
    """
    from petastorm_tpu.telemetry.stall import get_attributor
    registry = registry or get_registry()
    attributor = attributor or get_attributor()
    seconds = registry.counters_with_prefix(STAGE_SECONDS)
    calls = registry.counters_with_prefix(STAGE_CALLS)
    base_seconds = (baseline or {}).get('counters', {})
    base_calls = base_seconds

    stages = {}
    for key, value in seconds.items():
        stage = _stage_of(key)
        if stage is None:
            continue
        value -= base_seconds.get(key, 0.0)
        stages[stage] = {'seconds': max(value, 0.0)}
    for key, value in calls.items():
        stage = _stage_of(key)
        if stage in stages:
            stages[stage]['calls'] = int(value - base_calls.get(key, 0))
    total = sum(s['seconds'] for s in stages.values())
    denominator = wall_time_s if wall_time_s else total
    for stage in stages.values():
        stage.setdefault('calls', 0)
        stage['share'] = (stage['seconds'] / denominator
                          if denominator else 0.0)

    producer_wait, consumer_wait = attributor.totals()
    report = {
        'stages': dict(sorted(stages.items(),
                              key=lambda kv: -kv[1]['seconds'])),
        'stage_order': list(STAGES),
        'total_stage_seconds': round(total, 6),
        'wall_time_s': wall_time_s,
        'attributed_fraction': (round(total / wall_time_s, 4)
                                if wall_time_s else None),
        'stall': {
            # lifetime clocks (include spin-up) ...
            'producer_wait_s': round(producer_wait, 6),
            'consumer_wait_s': round(consumer_wait, 6),
            # ... but the VERDICT covers only the recent window horizon:
            # the process-wide attributor has no first-delivery reset
            # (unlike JaxLoader's), and a startup's consumer waits would
            # otherwise read as 'producer-bound' for the whole run
            'verdict': attributor.verdict(last_n=_VERDICT_WINDOWS),
            'windows': attributor.windows()[-20:],
        },
    }
    overlap = _h2d_overlap_share(stages)
    if overlap is not None:
        report['h2d_overlap_share'] = overlap
    cache = _cache_section(registry)
    if cache is not None:
        report['cache'] = cache
    decoded = decoded_cache_section(registry, baseline=baseline,
                                    stages=stages)
    if decoded is not None:
        report['decoded_cache'] = decoded
    service = _service_section(registry)
    if service is not None:
        report['service'] = service
    pushdown = _pushdown_section(registry)
    if pushdown is not None:
        report['pushdown'] = pushdown
    readahead = _readahead_section(registry)
    if readahead is not None:
        report['readahead'] = readahead
    peer = _peer_cache_section(registry)
    if peer is not None:
        report['peer_cache'] = peer
    write = _write_section(registry)
    if write is not None:
        report['write'] = write
    pipesan = _sanitizer_section(registry)
    if pipesan is not None:
        report['pipesan'] = pipesan
    anomalies = _anomalies_section(registry)
    if anomalies is not None:
        report['anomalies'] = anomalies
    autotune = _staging_autotune_section(registry)
    if autotune is not None:
        report['staging_autotune'] = autotune
    critical = _critical_path_section()
    if critical is not None:
        report['critical_path'] = critical
    slo = _slo_section()
    if slo is not None:
        report['slo'] = slo
    return report


def _critical_path_section():
    """Critical-path engine analysis (telemetry/critpath.py) — present
    only when the flight recorder holds stage events (tracing was on),
    so untraced pipelines keep their report shape unchanged."""
    from petastorm_tpu.telemetry import recorder
    if not len(recorder.get_recorder()):
        return None
    from petastorm_tpu.telemetry import critpath
    return critpath.critpath_section()


def _slo_section():
    """SLO burn/budget accounting (telemetry/slo.py) — present only when
    ``PETASTORM_TPU_SLO`` arms a policy, so objective-less pipelines
    keep their report shape unchanged."""
    from petastorm_tpu.telemetry import slo
    return slo.slo_section()


def _h2d_overlap_share(stages):
    """Share of staging-engine time NOT spent blocked on an in-flight
    transfer (``h2d_ready``): 1.0 means every dispatched transfer landed
    while the consumer computed / the next slot filled — fully overlapped;
    low values mean the link itself is the wall. Present only when the
    arena ran (the stages exist)."""
    fill = stages.get('stage_fill', {}).get('seconds', 0.0)
    dispatch = stages.get('h2d_dispatch', {}).get('seconds', 0.0)
    ready = stages.get('h2d_ready', {}).get('seconds', 0.0)
    total = fill + dispatch + ready
    if not total:
        return None
    return round(1.0 - ready / total, 4)


def _cache_section(registry):
    from petastorm_tpu.cache import (
        CACHE_BYTES_EVICTED, CACHE_BYTES_WRITTEN, CACHE_EVICTIONS,
        CACHE_HITS, CACHE_MISSES, CACHE_SIZE_BYTES,
    )
    hits = registry.counter_value(CACHE_HITS)
    misses = registry.counter_value(CACHE_MISSES)
    if not hits and not misses:
        return None
    return {
        'hits': int(hits),
        'misses': int(misses),
        'evictions': int(registry.counter_value(CACHE_EVICTIONS)),
        'bytes_written': int(registry.counter_value(CACHE_BYTES_WRITTEN)),
        'bytes_evicted': int(registry.counter_value(CACHE_BYTES_EVICTED)),
        # one gauge series per process (pid label), because gauge merges
        # are last-writer-wins and interleaved worker updates would
        # flicker. Every process tracks the SAME shared cache directory
        # (each LocalDiskCache's running total covers the whole dir), so
        # the aggregate is the freshest estimate — the max — never a sum,
        # which would overcount by the process count.
        'size_bytes': int(max(
            registry.gauges_with_prefix(CACHE_SIZE_BYTES).values(),
            default=0)),
        'hit_rate': round(hits / (hits + misses), 4),
    }


def classify_cache_phase(stages, hits, misses):
    """'cache-bound' / 'decode-bound' / 'mixed' verdict for a pass over a
    decoded-row-group cache — the "epoch 2+ should be cache-bound"
    contract. Cache-bound means hits dominate (≥80%) AND the pass's
    decode-side time (io+decode+transform) no longer dominates its
    hit-serving time — i.e. the pipeline is reading materialized batches,
    not re-paying the 71% io+decode share. ``stages`` is a
    :func:`pipeline_report`-shaped per-stage dict (baseline-scoped when
    the report was)."""
    total = hits + misses
    if total <= 0:
        return None
    hit_rate = hits / total

    def _sec(stage):
        return stages.get(stage, {}).get('seconds', 0.0)

    decode_side = _sec('io') + _sec('decode') + _sec('transform')
    hit_side = _sec('cache_hit_read')
    if hit_rate >= 0.8 and (decode_side <= hit_side or decode_side < 0.05):
        return 'cache-bound'
    if hit_rate <= 0.2:
        return 'decode-bound'
    return 'mixed'


def decoded_cache_section(registry=None, baseline=None, stages=None):
    """Materialized decoded-row-group cache activity (None when the cache
    never ran), with the :func:`classify_cache_phase` verdict. ``baseline``
    (an earlier ``registry.snapshot()``) scopes the counters to one
    measurement window — pass the snapshot taken between epochs to ask
    "was THIS pass cache-bound?"."""
    from petastorm_tpu.materialized_cache import (
        DECODED_CACHE_BYTES_READ, DECODED_CACHE_BYTES_WRITTEN,
        DECODED_CACHE_COPY_READS, DECODED_CACHE_EVICTIONS,
        DECODED_CACHE_HITS, DECODED_CACHE_MEM_HITS, DECODED_CACHE_MISSES,
        DECODED_CACHE_MMAP_READS, DECODED_CACHE_SIZE_BYTES,
    )
    registry = registry or get_registry()
    base = (baseline or {}).get('counters', {})

    def value(name):
        return registry.counter_value(name) - base.get(name, 0)

    hits = value(DECODED_CACHE_HITS)
    misses = value(DECODED_CACHE_MISSES)
    if not hits and not misses:
        return None
    return {
        'hits': int(hits),
        'misses': int(misses),
        'mem_hits': int(value(DECODED_CACHE_MEM_HITS)),
        'evictions': int(value(DECODED_CACHE_EVICTIONS)),
        'bytes_written': int(value(DECODED_CACHE_BYTES_WRITTEN)),
        'bytes_read': int(value(DECODED_CACHE_BYTES_READ)),
        'mmap_reads': int(value(DECODED_CACHE_MMAP_READS)),
        'copy_reads': int(value(DECODED_CACHE_COPY_READS)),
        # per-process gauges over ONE shared directory: aggregate with
        # max (freshest estimate), never sum — same rule as the raw cache
        'size_bytes': int(max(
            registry.gauges_with_prefix(DECODED_CACHE_SIZE_BYTES).values(),
            default=0)),
        'hit_rate': round(hits / (hits + misses), 4),
        'verdict': classify_cache_phase(stages or {}, hits, misses),
    }


def _service_section(registry):
    """Disaggregated-fleet health, from the gauges/counters the service
    dispatcher mirrors into the registry — present only when a service
    pool ran in this process (a worker ever registered), so local-pool
    reports stay unchanged. Re-ventilation/dedupe make the exactly-once
    machinery's activity visible without reading dispatcher logs."""
    from petastorm_tpu.service.dispatcher import (
        SERVICE_DUPLICATE_DONE, SERVICE_ITEMS_ASSIGNED,
        SERVICE_ITEMS_PENDING, SERVICE_PLACEMENT_HITS,
        SERVICE_PLACEMENT_MISSES, SERVICE_POISONED, SERVICE_PREEMPTIONS,
        SERVICE_RETRIES, SERVICE_REVENTILATED, SERVICE_WORKERS_ALIVE,
        SERVICE_WORKERS_REGISTERED,
    )
    from petastorm_tpu.service.standby import (
        SERVICE_FAILOVERS, SERVICE_REPLICATION_LAG,
    )
    gauges = registry.gauges_with_prefix('petastorm_tpu_service_')
    if not gauges:
        return None
    placement_hits = registry.counter_value(SERVICE_PLACEMENT_HITS)
    placement_misses = registry.counter_value(SERVICE_PLACEMENT_MISSES)
    placed = placement_hits + placement_misses
    return {
        'workers_alive': int(registry.gauge_value(SERVICE_WORKERS_ALIVE)),
        'workers_registered': int(
            registry.gauge_value(SERVICE_WORKERS_REGISTERED)),
        'items_pending': int(registry.gauge_value(SERVICE_ITEMS_PENDING)),
        'items_assigned': int(registry.gauge_value(SERVICE_ITEMS_ASSIGNED)),
        'reventilated': int(registry.counter_value(SERVICE_REVENTILATED)),
        'duplicate_done': int(
            registry.counter_value(SERVICE_DUPLICATE_DONE)),
        'retried': int(registry.counter_value(SERVICE_RETRIES)),
        'poisoned': int(registry.counter_value(SERVICE_POISONED)),
        # high availability + QoS (docs/service.md): how many times THIS
        # process promoted a standby, how stale its mirror is, and what
        # the scheduler did about priorities and warm caches
        'failovers': int(registry.counter_value(SERVICE_FAILOVERS)),
        'replication_lag_s': round(
            registry.gauge_value(SERVICE_REPLICATION_LAG), 3),
        'preemptions': int(registry.counter_value(SERVICE_PREEMPTIONS)),
        'placement_hits': int(placement_hits),
        'placement_misses': int(placement_misses),
        'placement_hit_share': (round(placement_hits / placed, 4)
                                if placed else None),
    }


def _pushdown_section(registry):
    """Selective-read (query-shaped reads) activity: plan-time pruning
    from the consumer-local planner summary, late-materialized rows from
    the fleet-merged worker counters — present only when a predicate
    planner ever ran (or workers late-materialized), so predicate-free
    pipelines keep their report shape unchanged. ``declines`` carries
    the reasons pruning proved nothing (``arbitrary-predicate``,
    ``no-statistics``, ``low-selectivity``) — the "My selective read is
    still full-scan-priced" runbook in docs/troubleshoot.md reads them.
    """
    from petastorm_tpu import pushdown
    summary = pushdown.planner_summary()
    pruned = registry.counter_value(pushdown.ROWGROUPS_PRUNED)
    late = registry.counter_value(pushdown.LATE_MATERIALIZED_ROWS)
    if not summary['planner_runs'] and not pruned and not late:
        return None
    considered = summary['rowgroups_considered']
    return {
        'planner_runs': summary['planner_runs'],
        'rowgroups_considered': considered,
        'rowgroups_pruned': int(pruned),
        'rows_pruned': int(registry.counter_value(pushdown.ROWS_PRUNED)),
        'late_materialized_rows': int(late),
        # share of considered row-groups proven empty, from the LOCAL
        # planner's tallies (the registry counter can include other
        # processes' plans; mixing denominators would lie)
        'prune_share': (round(summary['rowgroups_pruned'] / considered, 4)
                        if considered else None),
        'declines': summary['declines'],
    }


def _readahead_section(registry):
    """Wire-speed I/O plane activity (petastorm_tpu/readahead.py) —
    present only when the plane ever served, missed or degraded (the
    counters are fleet-merged over the pool delta channels), so
    readahead-less pipelines keep their report shape unchanged. Pool
    occupancy/depth come from THIS process's live managers; the "Decode
    is waiting on storage (io-bound)" runbook in docs/troubleshoot.md
    reads the hit share, mean coalesced-read size and degrade reasons."""
    from petastorm_tpu import readahead
    hits = registry.counter_value(readahead.READAHEAD_HITS)
    misses = registry.counter_value(readahead.READAHEAD_MISSES)
    degraded = {}
    for key, value in registry.counters_with_prefix(
            readahead.READAHEAD_DEGRADED).items():
        reason = _label_of(key, 'reason') or 'unknown'
        degraded[reason] = degraded.get(reason, 0) + int(value)
    if not hits and not misses and not degraded:
        return None
    bytes_fetched = registry.counter_value(readahead.READAHEAD_BYTES)
    reads = registry.counter_value(readahead.READAHEAD_COALESCED_READS)
    used, budget = readahead.pool_status()
    return {
        'hits': int(hits),
        'misses': int(misses),
        'hit_share': (round(hits / (hits + misses), 4)
                      if hits or misses else None),
        'bytes_fetched': int(bytes_fetched),
        'coalesced_reads': int(reads),
        'mean_coalesced_bytes': (int(bytes_fetched / reads) if reads
                                 else None),
        'degraded': degraded,
        'depth': readahead.current_depth(),
        'pool_bytes': int(used),
        'pool_budget_bytes': int(budget),
    }


def _peer_cache_section(registry):
    """Fleet-wide decoded-cache tier activity (service/peer_cache.py) —
    present only when a peer fetch ever hit, missed or an evict hint
    shipped (the worker-side counters are fleet-merged over the DONE
    delta channels), so host-local pipelines keep their report shape.
    The "Warm dataset still decode-priced on a fleet" runbook in
    docs/troubleshoot.md reads the hit share and degrade reasons."""
    from petastorm_tpu.service import peer_cache
    hits = registry.counter_value(peer_cache.PEER_CACHE_HITS)
    misses = 0
    degraded = {}
    for key, value in registry.counters_with_prefix(
            peer_cache.PEER_CACHE_MISSES).items():
        reason = _label_of(key, 'reason') or 'unknown'
        degraded[reason] = degraded.get(reason, 0) + int(value)
        misses += int(value)
    hints = registry.counter_value(peer_cache.PEER_CACHE_EVICT_HINTS)
    if not hits and not misses and not hints:
        return None
    return {
        'hits': int(hits),
        'misses': int(misses),
        'hit_share': (round(hits / (hits + misses), 4)
                      if hits or misses else None),
        'bytes_fetched': int(
            registry.counter_value(peer_cache.PEER_CACHE_BYTES)),
        'degraded': degraded,
        'evict_hints': int(hints),
    }


def _write_section(registry):
    """Distributed write plane activity (petastorm_tpu/write/) — present
    only when this process (or its fleet, via the pool delta channels)
    wrote, committed or compacted, so read-only pipelines keep their
    report shape unchanged. The committed generation is a gauge: the
    latest manifest swap this process published."""
    from petastorm_tpu.write import compact, writer
    from petastorm_tpu.write import manifest as write_manifest
    rows = registry.counter_value(writer.WRITE_ROWS)
    commits = registry.counter_value(write_manifest.WRITE_COMMITS)
    compact_runs = registry.counter_value(compact.COMPACT_RUNS)
    if not rows and not commits and not compact_runs:
        return None
    return {
        'rows_written': int(rows),
        'bytes_written': int(registry.counter_value(writer.WRITE_BYTES)),
        'files_written': int(registry.counter_value(writer.WRITE_FILES)),
        'commits': int(commits),
        'generation': int(registry.gauge_value(
            write_manifest.MANIFEST_GENERATION) or 0),
        'compact_runs': int(compact_runs),
        'files_folded': int(registry.counter_value(
            compact.COMPACT_FILES_FOLDED)),
    }


def _sanitizer_section(registry):
    """pipesan runtime-sanitizer findings — present when the sanitizer is
    armed (``PETASTORM_TPU_SANITIZE=1``) or violations were recorded, so
    unarmed reports stay unchanged. ``recent`` carries the last few
    structured violations from the in-process ring (kind, detail, ts);
    the counters aggregate across the pool delta channels like every
    other metric."""
    from petastorm_tpu import sanitizer
    by_kind = {}
    for key, value in registry.counters_with_prefix(
            sanitizer.SANITIZER_VIOLATIONS).items():
        kind = _label_of(key, 'kind') or 'unknown'
        by_kind[kind] = by_kind.get(kind, 0) + int(value)
    total = sum(by_kind.values())
    enabled = sanitizer.sanitize_enabled()
    if not enabled and not total:
        return None
    return {
        'enabled': enabled,
        'violations': total,
        'by_kind': by_kind,
        'views_guarded': int(registry.counter_value(
            sanitizer.SANITIZER_VIEWS_GUARDED)),
        'canary_checks': int(registry.counter_value(
            sanitizer.SANITIZER_CANARY_CHECKS)),
        'recent': sanitizer.violations()[-5:],
    }


def _anomalies_section(registry):
    """Anomaly-detector findings (live observability plane) — present
    when events were ever recorded (counter includes fleet-aggregated
    worker events) or a collector samples in this process, so pipelines
    without the plane armed keep their report shape unchanged. ``recent``
    carries the last few structured events from the in-process ring,
    each naming its troubleshoot.md runbook."""
    from petastorm_tpu.telemetry import timeseries
    by_kind = {}
    for key, value in registry.counters_with_prefix(
            timeseries.ANOMALY_EVENTS).items():
        kind = _label_of(key, 'kind') or 'unknown'
        by_kind[kind] = by_kind.get(kind, 0) + int(value)
    recent = timeseries.recent_anomalies(5)
    if not by_kind and not recent and not timeseries.collector_running():
        return None
    return {
        'total': sum(by_kind.values()),
        'by_kind': by_kind,
        'recent': recent,
    }


def _staging_autotune_section(registry):
    """Staging-autotuner decisions (jax/autotune.py) — present only when
    the control loop ever adjusted something (counter includes
    fleet-aggregated remote decisions), so untouched pipelines keep
    their report shape unchanged. ``recent`` carries this process's
    last few structured decision entries; the counter is the fleet
    total by action. Only consulted when the autotune module is already
    loaded: decisions can only originate in a process running a jax
    loader, so a lean process (service worker, torch consumer) must
    never pay the jax-bridge import for a section that would be None."""
    import sys
    autotune = sys.modules.get('petastorm_tpu.jax.autotune')
    if autotune is None:
        return None
    by_action = {}
    for key, value in registry.counters_with_prefix(
            autotune.AUTOTUNE_DECISIONS).items():
        action = _label_of(key, 'action') or 'unknown'
        by_action[action] = by_action.get(action, 0) + int(value)
    recent = autotune.recent_decisions(10)
    if not by_action and not recent:
        return None
    return {
        'total': sum(by_action.values()),
        'by_action': by_action,
        'recent': recent,
    }


def format_pipeline_report(report):
    """Human-readable rendering of :func:`pipeline_report` (one stage per
    line, canonical pipeline order first, then any extra stages)."""
    lines = ['pipeline stages (share of %s):'
             % ('wall time' if report['wall_time_s'] else 'stage time')]
    ordered = [s for s in report['stage_order'] if s in report['stages']]
    ordered += [s for s in report['stages'] if s not in ordered]
    for stage in ordered:
        info = report['stages'][stage]
        lines.append('  %-10s %8.3fs  %5.1f%%  (%d calls)'
                     % (stage, info['seconds'], 100 * info['share'],
                        info['calls']))
    if report['wall_time_s']:
        lines.append('  attributed %5.1f%% of %.3fs wall'
                     % (100 * (report['attributed_fraction'] or 0.0),
                        report['wall_time_s']))
    if report.get('h2d_overlap_share') is not None:
        lines.append('  h2d overlap %5.1f%% (share of staging-engine time '
                     'not blocked on an in-flight transfer)'
                     % (100 * report['h2d_overlap_share']))
    stall = report['stall']
    lines.append('stall attribution: %s (producer_wait %.3fs, '
                 'consumer_wait %.3fs over %d window(s))'
                 % (stall['verdict'], stall['producer_wait_s'],
                    stall['consumer_wait_s'], len(stall['windows'])))
    if 'cache' in report:
        c = report['cache']
        lines.append('cache: %d hit / %d miss (%.1f%%), %d eviction(s), '
                     '%d B written, %d B evicted, %d B resident'
                     % (c['hits'], c['misses'], 100 * c['hit_rate'],
                        c['evictions'], c['bytes_written'],
                        c['bytes_evicted'], c['size_bytes']))
    if 'decoded_cache' in report:
        d = report['decoded_cache']
        lines.append('decoded cache: %s — %d hit / %d miss (%.1f%%, %d '
                     'from memory tier), %d mmap / %d copy column read(s), '
                     '%d B written, %d B read, %d eviction(s), %d B '
                     'resident'
                     % (d['verdict'] or 'idle', d['hits'], d['misses'],
                        100 * d['hit_rate'], d['mem_hits'],
                        d['mmap_reads'], d['copy_reads'],
                        d['bytes_written'], d['bytes_read'],
                        d['evictions'], d['size_bytes']))
    if 'service' in report:
        s = report['service']
        lines.append('service fleet: %d alive / %d registered worker(s), '
                     '%d pending / %d assigned item(s), %d re-ventilated, '
                     '%d duplicate completion(s) dropped, %d retried, '
                     '%d poisoned'
                     % (s['workers_alive'], s['workers_registered'],
                        s['items_pending'], s['items_assigned'],
                        s['reventilated'], s['duplicate_done'],
                        s.get('retried', 0), s.get('poisoned', 0)))
        ha_bits = []
        if s.get('failovers'):
            ha_bits.append('%d failover(s), replication lag %.3fs'
                           % (s['failovers'],
                              s.get('replication_lag_s') or 0.0))
        if s.get('preemptions'):
            ha_bits.append('%d preemption(s)' % s['preemptions'])
        if s.get('placement_hit_share') is not None:
            ha_bits.append('placement %d hit / %d miss (%.1f%%)'
                           % (s['placement_hits'], s['placement_misses'],
                              100 * s['placement_hit_share']))
        if ha_bits:
            lines.append('service HA/QoS: %s' % ', '.join(ha_bits))
    if 'pushdown' in report:
        p = report['pushdown']
        share = p['prune_share']
        declines = ', '.join('%s: %d' % (k, v)
                             for k, v in sorted(p['declines'].items()))
        lines.append('pushdown: %d/%d row-group(s) pruned%s (%d rows '
                     'skipped), %d row(s) late-materialized%s'
                     % (p['rowgroups_pruned'], p['rowgroups_considered'],
                        (' = %.1f%%' % (100 * share)
                         if share is not None else ''),
                        p['rows_pruned'], p['late_materialized_rows'],
                        (' — declines: %s' % declines) if declines else ''))
    if 'readahead' in report:
        r = report['readahead']
        reasons = ', '.join('%s: %d' % (k, v)
                            for k, v in sorted(r['degraded'].items()))
        lines.append('readahead: %d hit / %d miss%s, %d B over %d '
                     'coalesced read(s)%s, depth %d, pool %d/%d B%s'
                     % (r['hits'], r['misses'],
                        (' (%.1f%%)' % (100 * r['hit_share'])
                         if r['hit_share'] is not None else ''),
                        r['bytes_fetched'], r['coalesced_reads'],
                        (' (mean %d B)' % r['mean_coalesced_bytes']
                         if r['mean_coalesced_bytes'] is not None else ''),
                        r['depth'], r['pool_bytes'],
                        r['pool_budget_bytes'],
                        (' — degraded: %s' % reasons) if reasons else ''))
    if 'peer_cache' in report:
        p = report['peer_cache']
        reasons = ', '.join('%s: %d' % (k, v)
                            for k, v in sorted(p['degraded'].items()))
        lines.append('peer cache: %d hit / %d miss%s, %d B fetched from '
                     'peers, %d evict hint(s)%s'
                     % (p['hits'], p['misses'],
                        (' (%.1f%%)' % (100 * p['hit_share'])
                         if p['hit_share'] is not None else ''),
                        p['bytes_fetched'], p['evict_hints'],
                        (' — degraded: %s' % reasons) if reasons else ''))
    if 'write' in report:
        w = report['write']
        compact_bit = ''
        if w['compact_runs']:
            compact_bit = (', %d compaction run(s) folding %d file(s)'
                           % (w['compact_runs'], w['files_folded']))
        lines.append('write plane: %d row(s) / %d B in %d part file(s), '
                     '%d commit(s), generation %d%s'
                     % (w['rows_written'], w['bytes_written'],
                        w['files_written'], w['commits'], w['generation'],
                        compact_bit))
    if 'pipesan' in report:
        p = report['pipesan']
        kinds = ', '.join('%s: %d' % (k, v)
                          for k, v in sorted(p['by_kind'].items()))
        lines.append('pipesan: %s — %d violation(s)%s, %d view(s) forced '
                     'read-only, %d canary check(s)'
                     % ('armed' if p['enabled'] else 'off',
                        p['violations'],
                        (' (%s)' % kinds) if kinds else '',
                        p['views_guarded'], p['canary_checks']))
    if 'anomalies' in report:
        a = report['anomalies']
        kinds = ', '.join('%s: %d' % (k, v)
                          for k, v in sorted(a['by_kind'].items()))
        lines.append('anomalies: %d event(s)%s'
                     % (a['total'], (' (%s)' % kinds) if kinds else ''))
        for event in a['recent'][-3:]:
            lines.append('  %s at %.0f — %s'
                         % (event['kind'], event.get('ts') or 0.0,
                            event.get('runbook', '')))
    if 'staging_autotune' in report:
        t = report['staging_autotune']
        actions = ', '.join('%s: %d' % (k, v)
                            for k, v in sorted(t['by_action'].items()))
        lines.append('staging autotune: %d decision(s)%s'
                     % (t['total'], (' (%s)' % actions) if actions else ''))
        for entry in t['recent'][-3:]:
            detail = {k: v for k, v in entry.items()
                      if k not in ('action', 'ts')}
            lines.append('  %s — %s' % (entry['action'], detail))
    if 'critical_path' in report:
        c = report['critical_path']
        lines.append('critical path: bottleneck %s over %.3fs traced '
                     'span (%d item(s), %d stage event(s))'
                     % (c['bottleneck'], c['span_s'], c['items'],
                        c['events']))
        for stage, info in list(c['stages'].items())[:4]:
            lines.append('  %-14s self %8.3fs  overlapped %8.3fs'
                         % (stage, info['self_s'], info['overlap_s']))
        for scenario in c['what_if'][:3]:
            lines.append('  what-if: %s => epoch %+.1f%%'
                         % (scenario['scenario'],
                            scenario['epoch_delta_pct']))
        check = c.get('autotune_crosscheck')
        if check:
            lines.append('  autotuner cross-check: %d agree / %d '
                         'disagree over %d decision(s)'
                         % (check['agree'], check['disagree'],
                            check['decisions']))
    if 'slo' in report:
        for target in report['slo']['targets']:
            lines.append('slo %s %s %g: last %s, burn short %.2fx / '
                         'long %.2fx, budget %.0f%%%s'
                         % (target['target'], target['op'],
                            target['threshold'],
                            ('%.4g' % target['last_value'])
                            if target['last_value'] is not None else '-',
                            target['short_burn'], target['long_burn'],
                            100 * target['budget_remaining'],
                            ' — BREACHING' if target['breaching'] else ''))
    return '\n'.join(lines)
