"""Windowed time-series rollups + anomaly detection over the registry.

The live half of the telemetry subsystem (docs/telemetry.md, "Live
observability plane"). Everything telemetry exported before this module
was post-hoc — ``pipeline_report()`` at end of run, JSONL/Prometheus
*file* snapshots — while the operational questions ("did throughput just
collapse?", "is the fleet flapping between producer- and
consumer-bound?") need *windowed rates observable while the job runs*.
The tf.data service paper (Audibert et al., 2022) makes exactly this
case: disaggregated input processing is only operable with continuous
per-worker/per-job visibility.

Three pieces, all stdlib-only:

* :class:`WindowedRollup` — a bounded ring of fixed-width windows over
  the process-wide :class:`~petastorm_tpu.telemetry.registry
  .MetricsRegistry`. Each closed window carries per-counter **rates**,
  per-histogram **p50/p95/p99** (from the existing fixed buckets'
  count increments), current gauges, the window's producer/consumer wait
  deltas and the stall **verdict** they classify to. Because the pools
  merge remote worker deltas into the same registry (process-pool
  markers, service DONE messages), a consumer-side rollup absorbs the
  whole fleet's increments without any extra channel.
* :class:`ObsCollector` — the sampler: one daemon thread per process
  that closes a window every ``PETASTORM_TPU_OBS_WINDOW_SEC`` and feeds
  it to the detector. Created ONLY when the observability plane is armed
  (``PETASTORM_TPU_OBS_PORT`` set and metrics on): with the knob unset
  or ``PETASTORM_TPU_METRICS=0`` no thread ever starts
  (``tests/test_obs.py`` asserts this structurally).
* :class:`AnomalyDetector` — consumes the window stream and emits the
  canonical structured events of
  :data:`petastorm_tpu.analysis.contracts.ANOMALY_KINDS`
  (``throughput_collapse``, ``stall_flap``, ``queue_saturated``,
  ``heartbeat_gap``, ``h2d_starvation``), each naming its
  docs/troubleshoot.md runbook. Events land in a bounded in-process
  ring, the ``petastorm_tpu_anomaly_events_total{kind=…}`` counter (so
  worker-side events aggregate fleet-wide over the existing delta
  channels), ``pipeline_report()['anomalies']`` and the JSONL exporter.

:class:`HeartbeatSummarizer` is the thread-free sibling for service
worker servers: called once per heartbeat, it returns the worker's
headline counter rates since the previous call, so the dispatcher's
endpoint can serve a per-worker fleet breakdown without the worker
needing its own sampler thread.
"""

import collections
import logging
import os
import threading
import time

from petastorm_tpu.analysis.contracts import ANOMALY_KINDS
from petastorm_tpu.telemetry import knobs
from petastorm_tpu.telemetry.registry import get_registry, metric_key
from petastorm_tpu.telemetry.spans import (
    STAGE_CALLS, STAGE_SECONDS, metrics_disabled,
)
from petastorm_tpu.telemetry.stall import (
    CONSUMER_BOUND, PRODUCER_BOUND, classify_window,
)

logger = logging.getLogger(__name__)

#: anomaly events per kind; worker processes' increments ride the pool
#: delta channels, so the consumer-side counter is the fleet aggregate
ANOMALY_EVENTS = 'petastorm_tpu_anomaly_events_total'
#: rollup windows closed by this process's sampler (sampler liveness)
OBS_WINDOWS = 'petastorm_tpu_obs_windows_total'

_DEFAULT_WINDOW_SEC = 1.0
_DEFAULT_WINDOWS = 120

# the two stall wait-clock counters (telemetry/__init__.py defines the
# same literals; re-importing the package root here would be circular)
_PRODUCER_WAIT = 'petastorm_tpu_stall_producer_wait_seconds_total'
_CONSUMER_WAIT = 'petastorm_tpu_stall_consumer_wait_seconds_total'
# service fleet-health series (mirrored by the dispatcher; see
# service/dispatcher.py — canonical members of contracts.METRIC_NAMES)
_SERVICE_ALIVE = 'petastorm_tpu_service_workers_alive'
_SERVICE_REGISTERED = 'petastorm_tpu_service_workers_registered'
_SERVICE_REVENTILATED = 'petastorm_tpu_service_reventilated_total'

#: events kept in the in-process ring (oldest dropped)
_EVENT_RING_CAPACITY = 200

#: throughput proxy, in priority order: result pulls (one per row-group
#: batch reaching the consumer), then worker-side decode/io calls (the
#: only rate a worker-server process sees locally)
_THROUGHPUT_KEYS = (
    metric_key(STAGE_CALLS, {'stage': 'queue_wait'}),
    metric_key(STAGE_CALLS, {'stage': 'decode'}),
    metric_key(STAGE_CALLS, {'stage': 'io'}),
)


def window_sec():
    return knobs.get_float('PETASTORM_TPU_OBS_WINDOW_SEC',
                           _DEFAULT_WINDOW_SEC, floor=0.05)


def max_windows():
    return knobs.get_int('PETASTORM_TPU_OBS_WINDOWS', _DEFAULT_WINDOWS,
                         floor=2)


def obs_enabled():
    """The observability plane's arming condition: an
    ``PETASTORM_TPU_OBS_PORT`` value is present AND metrics are on."""
    return (not metrics_disabled()
            and knobs.get_str('PETASTORM_TPU_OBS_PORT') != '')


_H2D_READY_KEY = metric_key(STAGE_SECONDS, {'stage': 'h2d_ready'})


def h2d_ready_share(window):
    """Seconds-per-second one closed window spent blocked in the staging
    arena's ``h2d_ready`` gate — the h2d-starvation signal, defined ONCE
    here for both consumers: the anomaly detector's ``h2d_starvation``
    event and the staging autotuner's deepen policy
    (:mod:`petastorm_tpu.jax.autotune`)."""
    return window['rates'].get(_H2D_READY_KEY, 0.0)


_IO_SECONDS_KEY = metric_key(STAGE_SECONDS, {'stage': 'io'})


def io_wait_share(window):
    """Seconds-per-second one closed window spent inside the blocking
    ``io`` stage (fleet-merged: worker increments ride the pool delta
    channels, so the share can exceed 1.0 across parallel workers) —
    the io-starvation signal the staging autotuner's readahead-deepen
    policy reads, defined ONCE here next to its h2d sibling."""
    return window['rates'].get(_IO_SECONDS_KEY, 0.0)


# -- windowed rollup ----------------------------------------------------------


def _quantiles(buckets, count_deltas):
    """p50/p95/p99 upper-bound estimates from one window's per-bucket
    count increments (prometheus-style: the quantile is the bound of the
    bucket the cumulative count crosses in; the +Inf bucket clamps to the
    largest finite bound)."""
    total = sum(count_deltas)
    if total <= 0:
        return None
    out = {}
    for label, q in (('p50', 0.5), ('p95', 0.95), ('p99', 0.99)):
        target = q * total
        cumulative = 0
        for i, count in enumerate(count_deltas):
            cumulative += count
            if cumulative >= target:
                out[label] = buckets[min(i, len(buckets) - 1)]
                break
    return out


class WindowedRollup:
    """Bounded ring of fixed-width windows over registry snapshots.

    Feed it full ``registry.snapshot()`` dicts (:meth:`sample`); each
    call after the first closes one window holding the rates/quantiles/
    verdict of the interval since the previous sample. Thread-safe: the
    sampler thread writes, scrape handlers read.
    """

    def __init__(self, max_windows=_DEFAULT_WINDOWS):
        self._lock = threading.Lock()
        self._windows = collections.deque(maxlen=max_windows)
        self._prev = None
        self._prev_t = None
        self._prev_wall = None
        self._closed_total = 0

    def sample(self, snapshot, now=None, wall=None):
        """Close one window against the previous sample; the first call
        primes the baseline and returns None."""
        now = time.monotonic() if now is None else now
        wall = time.time() if wall is None else wall
        with self._lock:
            prev, prev_t, prev_wall = self._prev, self._prev_t, \
                self._prev_wall
            self._prev, self._prev_t, self._prev_wall = snapshot, now, wall
            if prev is None:
                return None
            dur = now - prev_t
            if dur <= 0:
                return None
            window = self._close(prev, snapshot, prev_wall, dur)
            self._windows.append(window)
            self._closed_total += 1
            return window

    @staticmethod
    def _close(prev, snap, start_wall, dur):
        prev_counters = prev.get('counters', {})
        rates = {}
        for key, value in snap.get('counters', {}).items():
            delta = value - prev_counters.get(key, 0.0)
            if delta > 0:
                rates[key] = round(delta / dur, 6)
        quantiles = {}
        prev_hists = prev.get('histograms', {})
        for key, state in snap.get('histograms', {}).items():
            base = prev_hists.get(key)
            if base is None:
                deltas = state['counts']
            elif len(base['counts']) == len(state['counts']):
                deltas = [a - b for a, b in zip(state['counts'],
                                                base['counts'])]
            else:
                continue  # bucket-layout drift: skip rather than corrupt
            q = _quantiles(state['buckets'], deltas)
            if q is not None:
                quantiles[key] = q
        producer_wait = max(
            0.0, snap.get('counters', {}).get(_PRODUCER_WAIT, 0.0)
            - prev_counters.get(_PRODUCER_WAIT, 0.0))
        consumer_wait = max(
            0.0, snap.get('counters', {}).get(_CONSUMER_WAIT, 0.0)
            - prev_counters.get(_CONSUMER_WAIT, 0.0))
        throughput = None
        for key in _THROUGHPUT_KEYS:
            if key in rates:
                throughput = rates[key]
                break
        return {
            'start': start_wall,
            'dur_s': round(dur, 4),
            'rates': rates,
            'quantiles': quantiles,
            'gauges': dict(snap.get('gauges', {})),
            'producer_wait_s': round(producer_wait, 6),
            'consumer_wait_s': round(consumer_wait, 6),
            'verdict': classify_window(producer_wait, consumer_wait, dur),
            'throughput': throughput,
        }

    def windows(self, last_n=None):
        with self._lock:
            out = list(self._windows)
        return out[-last_n:] if last_n is not None else out

    @property
    def closed_total(self):
        return self._closed_total


# -- anomaly events -----------------------------------------------------------


_events_lock = threading.Lock()
_events = collections.deque(maxlen=_EVENT_RING_CAPACITY)


def record_anomaly(kind, detail=None, window_start=None):
    """Record one structured anomaly event: bounded in-process ring +
    the ``petastorm_tpu_anomaly_events_total{kind=…}`` counter (which the
    pool delta channels aggregate fleet-wide). ``kind`` must be a member
    of :data:`~petastorm_tpu.analysis.contracts.ANOMALY_KINDS`; the
    event carries its runbook heading so an operator reading a raw
    JSONL/endpoint dump knows where to go next."""
    if kind not in ANOMALY_KINDS:
        raise ValueError('Unknown anomaly kind %r; register it in '
                         'analysis/contracts.py ANOMALY_KINDS' % (kind,))
    event = {
        'kind': kind,
        'ts': time.time(),
        'window_start': window_start,
        'detail': dict(detail or {}),
        'runbook': 'docs/troubleshoot.md — "%s"' % ANOMALY_KINDS[kind],
    }
    with _events_lock:
        _events.append(event)
    if not metrics_disabled():
        get_registry().counter(ANOMALY_EVENTS, kind=kind).inc()
    logger.warning('Pipeline anomaly %s: %s (see %s)', kind,
                   event['detail'], event['runbook'])
    from petastorm_tpu.telemetry import obslog
    if obslog.log_dir() is not None:
        # every anomaly source funnels through here (the detector, the
        # SLO plane, the service dispatcher), so this is the one spot
        # that guarantees the flight log sees them all; the log line's
        # 'kind' field is the record type, the anomaly's own kind moves
        # to 'anomaly'
        rec = dict(event)
        rec['anomaly'] = rec.pop('kind', None)
        obslog.append('anomaly', rec)
    return event


def recent_anomalies(last_n=20):
    """The most recent structured anomaly events (oldest first)."""
    with _events_lock:
        out = list(_events)
    return out[-last_n:]


def anomaly_counts():
    """``{kind: n}`` of ring-resident events (this process only; the
    registry counter holds the fleet-wide totals)."""
    counts = {}
    with _events_lock:
        for event in _events:
            counts[event['kind']] = counts.get(event['kind'], 0) + 1
    return counts


class AnomalyDetector:
    """Window-stream consumer emitting the canonical anomaly events.

    Detections (thresholds from knobs, docs/env_knobs.md):

    * ``throughput_collapse`` — the throughput proxy fell below
      ``PETASTORM_TPU_OBS_COLLAPSE_FRAC`` of its trailing mean for 2+
      consecutive windows while the consumer was still actively waiting
      (so a finished stream never reads as a collapse).
    * ``stall_flap`` — the per-window stall verdict flipped between
      producer- and consumer-bound ``PETASTORM_TPU_OBS_FLAP_FLIPS``+
      times within the recent horizon.
    * ``queue_saturated`` — producer wait held ≥
      ``PETASTORM_TPU_OBS_SATURATED_SHARE`` of 3 consecutive windows:
      the consumer is the wall and back-pressure has quiesced the
      producers.
    * ``heartbeat_gap`` — service workers fell out of the liveness
      window (``workers_alive`` < ``workers_registered``) or items were
      re-ventilated this window.
    * ``h2d_starvation`` — the staging arena spent ≥ the saturation
      share of 3 consecutive windows blocked in ``h2d_ready``: the
      host→device link itself is starving the device.

    Each detection is edge-triggered with hysteresis: one event when the
    condition establishes, re-armed only after it clears — a persistent
    condition cannot flood the ring.
    """

    _FLAP_HORIZON = 8
    _TRAILING = 6
    _CONSECUTIVE = 3
    _COLLAPSE_CONSECUTIVE = 2
    #: a collapse verdict needs a trailing mean at least this high
    #: (windows/sec) — idle pipelines have nothing to collapse from
    _MIN_THROUGHPUT = 1.0
    #: consumer must still be waiting this share of the window for a
    #: throughput drop to count as a collapse (vs a finished stream)
    _COLLAPSE_WAIT_SHARE = 0.05

    #: consecutive non-bound (balanced/idle) windows after which the
    #: flap horizon resets — without this, a frozen verdict deque would
    #: keep an old flap episode "active" across an arbitrarily long calm
    #: stretch and swallow the next genuine episode's edge
    _CALM_RESET = 4

    def __init__(self, emit=None):
        self._emit = emit or record_anomaly
        self.reload_thresholds()
        self._throughputs = collections.deque(maxlen=self._TRAILING)
        self._verdicts = collections.deque(maxlen=self._FLAP_HORIZON)
        self._sat_streak = 0
        self._h2d_streak = 0
        self._collapse_streak = 0
        self._calm_streak = 0
        self._active = set()

    def reload_thresholds(self):
        """Re-read the threshold knobs IN PLACE (``telemetry.refresh()``
        lands here): hysteresis/streak state survives, so a refresh
        mid-condition cannot re-fire an already-active anomaly."""
        self._collapse_frac = knobs.get_float(
            'PETASTORM_TPU_OBS_COLLAPSE_FRAC', 0.3, floor=0.01)
        self._saturated_share = knobs.get_float(
            'PETASTORM_TPU_OBS_SATURATED_SHARE', 0.5, floor=0.05)
        self._flap_flips = knobs.get_int(
            'PETASTORM_TPU_OBS_FLAP_FLIPS', 3, floor=2)

    def observe(self, window):
        """Feed one closed window; emits any newly-established anomaly
        events and returns them."""
        events = []
        dur = max(window.get('dur_s') or 0.0, 1e-9)
        events += self._check_saturation(window, dur)
        events += self._check_h2d(window, dur)
        events += self._check_collapse(window, dur)
        events += self._check_flap(window)
        events += self._check_heartbeat(window)
        return events

    # -- per-kind checks (edge-triggered via the _active set) ----------------

    def _fire(self, kind, window, active, detail):
        """Hysteresis core: emit only on the inactive→active edge."""
        if not active:
            self._active.discard(kind)
            return []
        if kind in self._active:
            return []
        self._active.add(kind)
        return [self._emit(kind, detail=detail,
                           window_start=window.get('start'))]

    def _check_saturation(self, window, dur):
        share = window.get('producer_wait_s', 0.0) / dur
        saturated = share >= self._saturated_share
        self._sat_streak = self._sat_streak + 1 if saturated else 0
        return self._fire(
            'queue_saturated', window,
            self._sat_streak >= self._CONSECUTIVE,
            {'producer_wait_share': round(share, 4),
             'threshold': self._saturated_share,
             'windows': self._sat_streak})

    def _check_h2d(self, window, dur):
        share = h2d_ready_share(window)  # seconds/sec
        starved = share >= self._saturated_share
        self._h2d_streak = self._h2d_streak + 1 if starved else 0
        return self._fire(
            'h2d_starvation', window,
            self._h2d_streak >= self._CONSECUTIVE,
            {'h2d_ready_share': round(share, 4),
             'threshold': self._saturated_share,
             'windows': self._h2d_streak})

    def _check_collapse(self, window, dur):
        throughput = window.get('throughput')
        trailing = list(self._throughputs)
        collapsed = False
        mean = 0.0
        if len(trailing) >= 3:
            mean = sum(trailing) / len(trailing)
            wait_share = window.get('consumer_wait_s', 0.0) / dur
            collapsed = (mean >= self._MIN_THROUGHPUT
                         and (throughput or 0.0)
                         < self._collapse_frac * mean
                         and wait_share >= self._COLLAPSE_WAIT_SHARE)
        self._collapse_streak = self._collapse_streak + 1 if collapsed \
            else 0
        events = self._fire(
            'throughput_collapse', window,
            self._collapse_streak >= self._COLLAPSE_CONSECUTIVE,
            {'throughput': round(throughput or 0.0, 3),
             'trailing_mean': round(mean, 3),
             'threshold_frac': self._collapse_frac})
        # collapsed windows stay OUT of the trailing mean — otherwise a
        # sustained collapse drags the baseline down to itself and the
        # condition self-clears while the pipeline is still stalled
        if throughput is not None and not collapsed:
            self._throughputs.append(throughput)
        return events

    def _check_flap(self, window):
        verdict = window.get('verdict')
        if verdict in (PRODUCER_BOUND, CONSUMER_BOUND):
            self._verdicts.append(verdict)
            self._calm_streak = 0
        else:
            # a sustained calm stretch ends the episode: drop the frozen
            # verdict history so the NEXT flap re-triggers as a fresh
            # inactive->active edge
            self._calm_streak += 1
            if self._calm_streak >= self._CALM_RESET:
                self._verdicts.clear()
        flips = sum(1 for a, b in zip(list(self._verdicts),
                                      list(self._verdicts)[1:])
                    if a != b)
        return self._fire(
            'stall_flap', window, flips >= self._flap_flips,
            {'flips': flips, 'horizon': len(self._verdicts),
             'threshold': self._flap_flips})

    def _check_heartbeat(self, window):
        gauges = window.get('gauges', {})
        alive = gauges.get(_SERVICE_ALIVE)
        registered = gauges.get(_SERVICE_REGISTERED, 0)
        reventilated = window['rates'].get(_SERVICE_REVENTILATED, 0.0)
        gap = bool(reventilated) or (alive is not None and registered
                                     and alive < registered)
        return self._fire(
            'heartbeat_gap', window, gap,
            {'workers_alive': alive, 'workers_registered': registered,
             'reventilated_per_s': round(reventilated, 3)})


# -- the sampler --------------------------------------------------------------


class ObsCollector:
    """One daemon sampler thread: snapshot → rollup window → detector.

    Because the snapshot reads the process-wide registry — which the
    pools' delta merges already fold remote worker increments into —
    each window absorbs the cross-process merges for free.
    """

    def __init__(self, window_s=None, windows=None, detector=None):
        self.window_s = window_s or window_sec()
        self.rollup = WindowedRollup(windows or max_windows())
        self.detector = detector or AnomalyDetector()
        self._stop = threading.Event()
        self._thread = None
        self._ticks = 0

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name='petastorm-tpu-obs-sampler')
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.window_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - observability is advisory
                logger.debug('Rollup tick failed', exc_info=True)

    #: one critical-path digest lands in the flight log every N ticks —
    #: the sweep over the recorder is the plane's priciest analysis and
    #: per-tick it would eat the <2% overhead budget the bench gates
    _CRITPATH_EVERY = 30

    def tick(self):
        """One sampling step (the thread's body; callable directly from
        tests). get_registry() is re-resolved per tick so a test-reset
        registry swap is picked up instead of sampling a dead one.

        Each closed window additionally flows through the SLO policy
        (when ``PETASTORM_TPU_SLO`` arms one) and — with
        ``PETASTORM_TPU_OBS_LOG_DIR`` set — into the on-disk black box:
        the window itself, any anomalies it raised, the SLO verdicts and
        a periodic critical-path digest."""
        from petastorm_tpu.telemetry import obslog, slo
        window = self.rollup.sample(get_registry().snapshot())
        if window is None:
            return None
        if not metrics_disabled():
            get_registry().counter(OBS_WINDOWS).inc()
        self.detector.observe(window)
        verdict = slo.observe_window(window)
        self._ticks += 1
        if obslog.log_dir() is not None:
            # anomalies (the detector's `events` included) reach the log
            # via record_anomaly itself — every source funnels there
            obslog.append('window', dict(window))
            if verdict is not None:
                obslog.append('slo', dict(verdict))
            if self._ticks % self._CRITPATH_EVERY == 0:
                from petastorm_tpu.telemetry import critpath
                digest = critpath.analyze()
                if digest is not None:
                    digest.pop('stages', None)
                    obslog.append('critpath', digest)
        return window

    def reload_config(self):
        """Re-read the window length and detector thresholds
        (``telemetry.refresh()`` lands here via ``refresh_obs``). The
        detector object is kept — its hysteresis/streak state must
        survive a knob refresh or an active condition would re-fire."""
        self.window_s = window_sec()
        self.detector.reload_thresholds()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_collector_lock = threading.Lock()
_collector = None


def ensure_collector():
    """Start the process-wide sampler if the plane is armed; returns the
    collector or None. The one constructor path — nothing else may start
    observability threads, which is what makes the disabled case
    structurally thread-free."""
    global _collector
    if not obs_enabled():
        return None
    if _collector is None:
        with _collector_lock:
            if _collector is None:
                collector = ObsCollector()
                collector.start()
                _collector = collector
    return _collector


def collector_running():
    return _collector is not None


def rollup_section(last_n=12):
    """The live rollup view the ``/report`` endpoint serves: headline
    (latest throughput/verdict, totals) plus the last ``last_n`` compact
    windows. None when no collector runs in this process."""
    collector = _collector
    if collector is None:
        return None
    windows = collector.rollup.windows()
    last = windows[-1] if windows else None
    headline = {
        'window_s': collector.window_s,
        'windows_sampled': collector.rollup.closed_total,
        'throughput_per_s': (last or {}).get('throughput'),
        'verdict': (last or {}).get('verdict'),
        'anomaly_counts': anomaly_counts(),
    }
    return {
        'window_s': collector.window_s,
        'headline': headline,
        'windows': windows[-last_n:],
    }


def refresh_obs():
    """Re-read every cached observability knob (hooked into
    ``telemetry.refresh()``): live collector reloads its window length
    and detector thresholds; arming/port changes take effect at the next
    mount (the HTTP server binds once per process)."""
    collector = _collector
    if collector is not None:
        collector.reload_config()
    from petastorm_tpu.telemetry import obslog, slo
    slo.refresh_slo()
    obslog.refresh_obslog()


def _reset_for_tests():
    """Stop the sampler and drop the event ring (test isolation only)."""
    global _collector
    with _collector_lock:
        collector, _collector = _collector, None
    if collector is not None:
        collector.stop()
    with _events_lock:
        _events.clear()


# -- worker-server heartbeat summaries ---------------------------------------


class HeartbeatSummarizer:
    """Thread-free per-worker rollup for the service heartbeat channel.

    A worker server calls :meth:`summary` once per heartbeat; the result
    (a small JSON-safe dict: pid/uptime + per-second rates of the
    counters that moved since the previous heartbeat + local anomaly
    counts) piggybacks on the HEARTBEAT frame, and the dispatcher's
    endpoint serves it as the per-worker fleet breakdown. No sampler
    thread is involved — the serve loop's own cadence is the window.
    """

    #: at most this many rate series ride one heartbeat (the busiest
    #: first); the wire frame stays O(1KB) regardless of label explosion
    _MAX_RATES = 24

    def __init__(self, worker_id=None):
        self._worker_id = worker_id
        self._t0 = time.monotonic()
        self._prev = None
        self._prev_t = None

    def summary(self, obs_port=None):
        out = {'pid': os.getpid(),
               'uptime_s': round(time.monotonic() - self._t0, 1)}
        if self._worker_id is not None:
            out['worker_id'] = self._worker_id
        if obs_port:
            out['obs_port'] = obs_port
        if metrics_disabled():
            return out
        # counters only — a full snapshot() would also lock-and-copy
        # every histogram's bucket state once per heartbeat for nothing
        counters = get_registry().counters_with_prefix('')
        now = time.monotonic()
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = counters, now
        if prev is not None and now > prev_t:
            dur = now - prev_t
            rates = {}
            for key, value in counters.items():
                delta = value - prev.get(key, 0.0)
                if delta > 0:
                    rates[key] = round(delta / dur, 4)
            if len(rates) > self._MAX_RATES:
                keep = sorted(rates, key=lambda k: -rates[k])
                rates = {k: rates[k] for k in keep[:self._MAX_RATES]}
            out['rates'] = rates
        counts = anomaly_counts()
        if counts:
            out['anomalies'] = counts
        return out
