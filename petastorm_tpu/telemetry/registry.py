"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Design constraints (docs/telemetry.md):

* **Hot-path cheap.** One ``threading.Lock`` per metric instance; an
  ``inc``/``observe`` is a lock round-trip plus an add — well under the
  per-call budget ``tests/test_telemetry.py::test_overhead_budget``
  enforces. Metric lookups (``registry.counter(...)``) are dict hits;
  callers on tight loops should still hold the returned object.
* **Thread-safe and pool-mergeable.** Worker processes keep their own
  process-wide registry and ship monotonic deltas back over the existing
  result channels (:meth:`MetricsRegistry.collect_delta` on the worker,
  :meth:`MetricsRegistry.merge_delta` on the consumer); deltas are plain
  dicts of primitives, so any codec the channel already uses can carry
  them.
* **Dependency-free.** stdlib only.
"""

import bisect
import threading

#: default histogram buckets (seconds): spans from ~0.1ms row-group ops to
#: multi-second stalls; the +Inf bucket is implicit.
DEFAULT_DURATION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def metric_key(name, labels=None):
    """Canonical string identity of a metric: ``name`` or
    ``name{k="v",...}`` with label keys sorted (promql-style). Used as the
    snapshot/delta dict key, so cross-process merges address the same
    series regardless of label insertion order."""
    if not labels:
        return name
    inner = ','.join('%s="%s"' % (k, _escape_label(str(v)))
                     for k, v in sorted(labels.items()))
    return '%s{%s}' % (name, inner)


def _escape_label(value):
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (value.replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


class Counter:
    """Monotonic float counter."""

    __slots__ = ('_value', '_lock')

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError('counters only go up; got %r' % (amount,))
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value


class Gauge:
    """Settable instantaneous value."""

    __slots__ = ('_value', '_lock')

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative-on-export, per-bucket counts
    internally; the +Inf bucket is the trailing slot)."""

    __slots__ = ('buckets', '_counts', '_sum', '_count', '_lock')

    def __init__(self, buckets=DEFAULT_DURATION_BUCKETS):
        buckets = tuple(float(b) for b in buckets)
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError('histogram buckets must be strictly ascending; '
                             'got %r' % (buckets,))
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def state(self):
        """``{'buckets': [...], 'counts': [...], 'sum': s, 'count': n}``
        (per-bucket counts, NOT cumulative — exporters cumulate)."""
        with self._lock:
            return {'buckets': list(self.buckets),
                    'counts': list(self._counts),
                    'sum': self._sum, 'count': self._count}


class MetricsRegistry:
    """Named metrics with optional labels, snapshot/delta/merge support."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._histograms = {}
        # per-key baselines for collect_delta (worker-side flush cursor)
        self._delta_counters = {}
        self._delta_histograms = {}
        self._delta_gauges = {}

    # -- metric accessors (create on first use) ------------------------------

    def counter(self, name, **labels):
        key = metric_key(name, labels)
        metric = self._counters.get(key)
        if metric is None:
            with self._lock:
                metric = self._counters.setdefault(key, Counter())
        return metric

    def gauge(self, name, **labels):
        key = metric_key(name, labels)
        metric = self._gauges.get(key)
        if metric is None:
            with self._lock:
                metric = self._gauges.setdefault(key, Gauge())
        return metric

    def histogram(self, name, buckets=DEFAULT_DURATION_BUCKETS, **labels):
        key = metric_key(name, labels)
        metric = self._histograms.get(key)
        if metric is None:
            with self._lock:
                metric = self._histograms.setdefault(key, Histogram(buckets))
        return metric

    # -- read access ----------------------------------------------------------

    def counter_value(self, name, **labels):
        metric = self._counters.get(metric_key(name, labels))
        return metric.value if metric is not None else 0.0

    def gauge_value(self, name, **labels):
        metric = self._gauges.get(metric_key(name, labels))
        return metric.value if metric is not None else 0.0

    def counters_with_prefix(self, prefix):
        """``{key: value}`` of every counter whose key starts with
        ``prefix`` (label'd series of one name share its prefix)."""
        return {k: c.value for k, c in list(self._counters.items())
                if k.startswith(prefix)}

    def gauges_with_prefix(self, prefix):
        return {k: g.value for k, g in list(self._gauges.items())
                if k.startswith(prefix)}

    def snapshot(self):
        """Full state as a JSON-serializable dict."""
        return {
            'counters': {k: c.value for k, c in list(self._counters.items())},
            'gauges': {k: g.value for k, g in list(self._gauges.items())},
            'histograms': {k: h.state()
                           for k, h in list(self._histograms.items())},
        }

    # -- cross-process aggregation -------------------------------------------

    def collect_delta(self):
        """Monotonic increments since the previous ``collect_delta`` call
        (worker-side flush). Counters/histograms ship increments; gauges
        ship their current value (last-writer-wins on merge). Returns None
        when nothing changed — callers piggybacking deltas on existing
        messages can skip the payload entirely."""
        delta = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for key, c in list(self._counters.items()):
            value = c.value
            base = self._delta_counters.get(key, 0.0)
            if value != base:
                delta['counters'][key] = value - base
                self._delta_counters[key] = value
        for key, h in list(self._histograms.items()):
            state = h.state()
            base = self._delta_histograms.get(key)
            if base is None or base['count'] != state['count']:
                if base is None:
                    inc = state
                else:
                    inc = {'buckets': state['buckets'],
                           'counts': [a - b for a, b
                                      in zip(state['counts'],
                                             base['counts'])],
                           'sum': state['sum'] - base['sum'],
                           'count': state['count'] - base['count']}
                delta['histograms'][key] = inc
                self._delta_histograms[key] = state
        for key, g in list(self._gauges.items()):
            value = g.value
            if self._delta_gauges.get(key) != value:
                delta['gauges'][key] = value
                self._delta_gauges[key] = value
        if not (delta['counters'] or delta['gauges'] or delta['histograms']):
            return None
        return delta

    def merge_delta(self, delta):
        """Fold a worker's :meth:`collect_delta` payload into this registry
        (consumer-side aggregate). Safe to call from any thread."""
        if not delta:
            return
        for key, inc in delta.get('counters', {}).items():
            self._counter_by_key(key).inc(inc)
        for key, value in delta.get('gauges', {}).items():
            self._gauge_by_key(key).set(value)
        for key, inc in delta.get('histograms', {}).items():
            hist = self._histogram_by_key(key, inc['buckets'])
            with hist._lock:
                if len(hist._counts) == len(inc['counts']):
                    hist._counts = [a + b for a, b
                                    in zip(hist._counts, inc['counts'])]
                    hist._sum += inc['sum']
                    hist._count += inc['count']
                # mismatched bucket layouts (config drift between worker
                # and consumer builds) drop the histogram increment rather
                # than corrupt the series; counters above still merged

    def _counter_by_key(self, key):
        metric = self._counters.get(key)
        if metric is None:
            with self._lock:
                metric = self._counters.setdefault(key, Counter())
        return metric

    def _gauge_by_key(self, key):
        metric = self._gauges.get(key)
        if metric is None:
            with self._lock:
                metric = self._gauges.setdefault(key, Gauge())
        return metric

    def _histogram_by_key(self, key, buckets):
        metric = self._histograms.get(key)
        if metric is None:
            with self._lock:
                metric = self._histograms.setdefault(
                    key, Histogram(tuple(buckets)))
        return metric


_global_lock = threading.Lock()
_global_registry = None
# callbacks run on reset_registry(): modules caching metric OBJECTS of the
# process-wide registry (spans' per-stage cache) register here so a swap
# can never leave them recording into the replaced instance
_reset_hooks = []


def on_registry_reset(hook):
    _reset_hooks.append(hook)


def get_registry():
    """The process-wide registry every pipeline layer records into. Worker
    processes each have their own (it is per-process by construction); the
    pools merge worker deltas back into the consumer process's one."""
    global _global_registry
    if _global_registry is None:
        with _global_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry


def reset_registry():
    """Swap in a fresh process-wide registry (test isolation only)."""
    global _global_registry
    with _global_lock:
        _global_registry = MetricsRegistry()
    for hook in _reset_hooks:
        hook()


def dump_delta_frame():
    """The process-wide registry's increments since the previous call,
    dill-framed for a pool's result channel (b'' when nothing changed).
    Telemetry must never fail a completion: errors degrade to b''. The one
    owner of delta framing — the process pool's markers and the service's
    DONE messages both call it.

    Per-item trace events piggyback here too: when the flight recorder
    holds events (worker-side stage/attempt events of traced items), the
    frame carries them under ``trace_events`` and the recorder is drained
    — the trace layer reuses the metrics' channel instead of adding one."""
    import dill
    try:
        delta = get_registry().collect_delta()
        from petastorm_tpu.telemetry.recorder import get_recorder
        recorder = get_recorder()
        if len(recorder):
            delta = delta or {'counters': {}, 'gauges': {},
                              'histograms': {}}
            delta['trace_events'] = recorder.drain()
        return dill.dumps(delta) if delta else b''
    except Exception:  # noqa: BLE001 - telemetry is advisory
        return b''


def load_delta_frame(frame):
    """Inverse of :func:`dump_delta_frame`; None for empty, undecodable,
    or non-delta-shaped frames (a dropped delta loses some gauge
    freshness, nothing more — it must never take a data channel down).

    The shape check is strict — EXACTLY the three delta keys (plus an
    optional ``trace_events`` LIST), the three all dicts, at least one of
    the fields non-empty — because the service dispatcher uses it to
    tell a metrics frame from a result frame sent by a pre-telemetry
    worker build (the wire has no version marker); a permissive check
    would let arbitrary pickled results masquerade as deltas and vanish."""
    if not frame:
        return None
    import dill
    try:
        delta = dill.loads(frame)
    except Exception:  # noqa: BLE001 - telemetry is advisory
        return None
    if not isinstance(delta, dict):
        return None
    base_keys = {'counters', 'gauges', 'histograms'}
    if set(delta) not in (base_keys, base_keys | {'trace_events'}):
        return None
    if not all(isinstance(delta[k], dict) for k in base_keys):
        return None
    if not isinstance(delta.get('trace_events', []), list):
        return None
    if not any(delta.values()):
        return None
    return delta


def merge_worker_delta(delta):
    """Consumer-side entry point for a delta that arrived over a pool's
    result channel: fold it into the process-wide registry AND replay its
    stall-wait increments into the process-wide attributor, so remote
    producer-side back-pressure participates in window classification.
    Never raises (telemetry is advisory; callers sit on data paths)."""
    if not delta:
        return
    try:
        _merge_worker_delta(delta)
    except Exception:  # noqa: BLE001 - telemetry is advisory
        import logging
        logging.getLogger(__name__).debug('Dropping unmergeable metrics '
                                          'delta', exc_info=True)


def _merge_worker_delta(delta):
    get_registry().merge_delta(delta)
    events = delta.get('trace_events')
    if events:
        # a remote worker's flight-recorder batch: fold it into THIS
        # process's recorder, where the whole distributed timeline
        # accumulates for export (dump_trace / --trace-out)
        from petastorm_tpu.telemetry.recorder import get_recorder
        get_recorder().add_many(e for e in events if isinstance(e, dict))
    counters = delta.get('counters', {})
    # import here: registry must stay importable before the package's
    # __init__ finishes binding the sibling modules
    from petastorm_tpu.telemetry import (
        STALL_CONSUMER_WAIT, STALL_PRODUCER_WAIT,
    )
    from petastorm_tpu.telemetry.stall import get_attributor
    producer = counters.get(STALL_PRODUCER_WAIT, 0.0)
    consumer = counters.get(STALL_CONSUMER_WAIT, 0.0)
    if producer > 0.0:
        get_attributor().note_producer_wait(producer)
    if consumer > 0.0:
        get_attributor().note_consumer_wait(consumer)
