"""Stall attribution: who is waiting on whom, per sampling window.

Two wait clocks cover every pipeline stall (the tf.data papers' framing):

* **consumer wait** — a consumer blocked pulling (reader ``get_results``,
  loader ``__next__``): the producer side is too slow → the window is
  **producer-bound** (input-bound).
* **producer wait** — a producer blocked pushing against back-pressure
  (pool publish against a full results queue, loader staging against a
  full prefetch queue, dispatcher backlogged behind a stalled consumer):
  the consumer side is too slow → the window is **consumer-bound**
  (compute-bound).

The attributor buckets both clocks into fixed wall-clock windows
(``PETASTORM_TPU_METRICS_WINDOW_S``, default 0.5s) and classifies each
closed window. Remote producers (process-pool / service workers)
participate through the registry delta merge
(:func:`~petastorm_tpu.telemetry.registry.merge_worker_delta` replays their
wait increments here).
"""

import collections
import threading
import time

from petastorm_tpu.telemetry import knobs

PRODUCER_BOUND = 'producer-bound'
CONSUMER_BOUND = 'consumer-bound'
BALANCED = 'balanced'

#: a window classifies only when total wait exceeds this share of it;
#: quieter windows are balanced (nobody meaningfully stalled)
_MIN_WAIT_SHARE = 0.02
#: dominance threshold: one side must hold >2/3 of the total wait
_DOMINANCE = 2.0 / 3.0

_DEFAULT_WINDOW_S = 0.5


def default_window_s():
    value = knobs.get_float('PETASTORM_TPU_METRICS_WINDOW_S',
                            _DEFAULT_WINDOW_S)
    return value if value > 0 else _DEFAULT_WINDOW_S


def classify_window(producer_wait_s, consumer_wait_s, window_s):
    """Verdict for one window's wait totals (see module docstring for the
    direction of each clock)."""
    total = producer_wait_s + consumer_wait_s
    if total < _MIN_WAIT_SHARE * window_s:
        return BALANCED
    if consumer_wait_s > _DOMINANCE * total:
        return PRODUCER_BOUND
    if producer_wait_s > _DOMINANCE * total:
        return CONSUMER_BOUND
    return BALANCED


class StallAttributor:
    """Wait-clock accumulator over fixed sampling windows.

    Thread-safe; every pipeline thread notes into the same instance. A
    window closes when a note (or an explicit :meth:`windows` read) crosses
    its wall-clock boundary; closed windows keep ``(start, producer_wait_s,
    consumer_wait_s, verdict)`` in a bounded deque.
    """

    def __init__(self, window_s=None, max_windows=240):
        self._window_s = window_s or default_window_s()
        self._lock = threading.Lock()
        self._windows = collections.deque(maxlen=max_windows)
        self._win_start = None
        self._producer_wait = 0.0
        self._consumer_wait = 0.0
        self._total_producer = 0.0
        self._total_consumer = 0.0

    @property
    def window_s(self):
        return self._window_s

    def note_producer_wait(self, seconds):
        self._note(seconds, producer=True)

    def note_consumer_wait(self, seconds):
        self._note(seconds, producer=False)

    def _note(self, seconds, producer):
        if seconds <= 0.0:
            return
        now = time.monotonic()
        with self._lock:
            self._roll(now)
            if producer:
                self._producer_wait += seconds
                self._total_producer += seconds
            else:
                self._consumer_wait += seconds
                self._total_consumer += seconds

    def _roll(self, now):
        if self._win_start is None:
            self._win_start = now
            return
        while now - self._win_start >= self._window_s:
            self._windows.append({
                'start': self._win_start,
                'producer_wait_s': self._producer_wait,
                'consumer_wait_s': self._consumer_wait,
                'verdict': classify_window(self._producer_wait,
                                           self._consumer_wait,
                                           self._window_s),
            })
            self._win_start += self._window_s
            self._producer_wait = 0.0
            self._consumer_wait = 0.0
            # long idle gap (paused training, eval phase): every window
            # past the deque's capacity is an all-zero 'balanced' that
            # would be appended only to be evicted — fast-forward instead
            # of spinning O(gap/window) iterations under the lock
            behind = int((now - self._win_start) / self._window_s)
            maxlen = self._windows.maxlen or behind
            if behind > maxlen:
                self._win_start += (behind - maxlen) * self._window_s

    def windows(self, include_current=True):
        """Closed windows (oldest first), optionally with the in-progress
        window appended (classified on its partial totals)."""
        now = time.monotonic()
        with self._lock:
            self._roll(now)
            out = list(self._windows)
            if include_current and self._win_start is not None and (
                    self._producer_wait or self._consumer_wait):
                out.append({
                    'start': self._win_start,
                    'producer_wait_s': self._producer_wait,
                    'consumer_wait_s': self._consumer_wait,
                    'verdict': classify_window(self._producer_wait,
                                               self._consumer_wait,
                                               self._window_s),
                })
        return out

    def totals(self):
        """Lifetime ``(producer_wait_s, consumer_wait_s)``."""
        with self._lock:
            return self._total_producer, self._total_consumer

    def verdict(self, last_n=None):
        """Aggregate verdict over the last ``last_n`` windows (all when
        None): classification of the summed wait clocks, which is robust to
        a single noisy window."""
        windows = self.windows()
        if last_n is not None:
            windows = windows[-last_n:]
        if not windows:
            return BALANCED
        producer = sum(w['producer_wait_s'] for w in windows)
        consumer = sum(w['consumer_wait_s'] for w in windows)
        return classify_window(producer, consumer,
                               self._window_s * len(windows))

    def reset(self):
        """Drop all windows and totals (new measurement pass)."""
        with self._lock:
            self._windows.clear()
            self._win_start = None
            self._producer_wait = self._consumer_wait = 0.0
            self._total_producer = self._total_consumer = 0.0


_global_lock = threading.Lock()
_global_attributor = None


def get_attributor():
    """The process-wide attributor the pools, reader and loader note into."""
    global _global_attributor
    if _global_attributor is None:
        with _global_lock:
            if _global_attributor is None:
                _global_attributor = StallAttributor()
    return _global_attributor


def reset_attributor():
    """Swap in a fresh process-wide attributor (test isolation only)."""
    global _global_attributor
    with _global_lock:
        _global_attributor = StallAttributor()
