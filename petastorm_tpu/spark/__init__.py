"""DataFrame → cached-Parquet → loader converters
(reference: ``petastorm/spark/``)."""

from petastorm_tpu.spark.spark_dataset_converter import (  # noqa: F401
    DatasetConverter, SparkDatasetConverter, make_dataframe_converter,
    make_spark_converter,
)
