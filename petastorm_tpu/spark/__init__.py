"""DataFrame → cached-Parquet → loader converters
(reference: ``petastorm/spark/``)."""

from petastorm_tpu.spark.spark_dataset_converter import (  # noqa: F401
    DatasetConverter, SparkDatasetConverter, check_dataset_file_median_size,
    make_dataframe_converter, make_spark_converter, spark_unify_float_precision,
    spark_vectors_to_arrays, wait_file_available,
)
