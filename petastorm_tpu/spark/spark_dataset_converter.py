"""DataFrame converters: materialize a dataframe once, read it many times.

Re-design of ``petastorm/spark/spark_dataset_converter.py``: the reference
converts a Spark DataFrame into a cached Parquet copy and hands out
TF/Torch loaders over it (``:162-293``). Here the same converter surface
exists in two flavors:

* :func:`make_dataframe_converter` — **Spark-free**: pandas DataFrames /
  pyarrow Tables, materialized with pyarrow. The primary path on a TPU VM.
* :func:`make_spark_converter` — Spark parity, lazily importing pyspark;
  the cached copy is written by Spark executors, everything downstream is
  shared with the Spark-free flavor.

Shared semantics with the reference: content-addressed cache hits (plan /
content fingerprint → same cached copy, ``:498-506``), atexit cleanup of
cached copies (``:587``), converters expose ``make_tf_dataset`` /
``make_torch_dataloader`` (+ TPU-native ``make_jax_loader``) and
``delete()``.
"""

import atexit
import hashlib
import logging
import os
import threading
import time
import uuid

logger = logging.getLogger(__name__)

_CACHE_REGISTRY = {}
_CACHE_LOCK = threading.Lock()

#: Spark conf key for the parent cache dir (reference: ``:170``)
PARENT_CACHE_DIR_URL_CONF = 'petastorm.spark.converter.parentCacheDirUrl'

#: eventual-consistency wait bound (reference: ``:595``)
FILE_AVAILABILITY_WAIT_TIMEOUT_S = 30
#: files below this median trigger the repartition advisory (``:624-627``)
RECOMMENDED_FILE_SIZE_BYTES = 50 * 1024 * 1024


def wait_file_available(url_or_path_list, fs=None, timeout_s=None,
                        poll_interval_s=0.1):
    """Block until every materialized file is visible, or raise.

    Guards readers against eventually-consistent stores (S3-style) where a
    just-written object may not list/stat yet (reference
    ``spark_dataset_converter.py:595-621``). Paths are polled concurrently;
    a file still absent after ``timeout_s`` raises :class:`RuntimeError`
    naming the stragglers.

    :param fs: optional fsspec filesystem; resolved from the URLs when
        omitted (injectable for tests and for pre-resolved callers).
    :param timeout_s: wait bound for the WHOLE call (one shared deadline,
        not per file); defaults to the module's
        ``FILE_AVAILABILITY_WAIT_TIMEOUT_S`` read at call time.
    """
    from concurrent.futures import ThreadPoolExecutor
    if timeout_s is None:
        timeout_s = FILE_AVAILABILITY_WAIT_TIMEOUT_S
    urls = list(url_or_path_list)
    if not urls:
        return
    if fs is None:
        from petastorm_tpu.fs import get_filesystem_and_path_or_paths
        fs, paths = get_filesystem_and_path_or_paths(urls)
    else:
        paths = urls

    # one deadline for the call: with more paths than pool slots, a
    # per-task deadline starting at task RUN time would stack up to
    # (paths/slots) x timeout of total blocking
    deadline = time.monotonic() + timeout_s

    def _wait(path):
        while True:
            # drop fsspec's listing/dircache first: on caching filesystems
            # (s3fs, gcsfs) the first miss would otherwise be re-served
            # from cache forever, defeating the poll
            invalidate = getattr(fs, 'invalidate_cache', None)
            if invalidate is not None:
                invalidate()
            if fs.exists(path):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_interval_s)

    with ThreadPoolExecutor(max_workers=min(64, len(paths))) as pool:
        results = list(pool.map(_wait, paths))
    failed = [u for u, ok in zip(urls, results) if not ok]
    if failed:
        raise RuntimeError(
            'Timeout while waiting for materialized files to appear: %s. '
            'Check that the dataframe write succeeded.' % ', '.join(failed))


def check_dataset_file_median_size(url_or_path_list, fs=None):
    """Advise on under-sized Parquet files; returns the median byte size.

    A median part-file below ~50 MB wastes reader parallelism on open/footer
    overhead (reference ``spark_dataset_converter.py:624-640``, which only
    checked local paths; fsspec ``size`` makes this store-agnostic). The
    advisory is a warning, never an error.
    """
    from concurrent.futures import ThreadPoolExecutor
    urls = list(url_or_path_list)
    if len(urls) < 2:
        return None
    if fs is None:
        from petastorm_tpu.fs import get_filesystem_and_path_or_paths
        fs, paths = get_filesystem_and_path_or_paths(urls)
    else:
        paths = urls
    # size() is one round trip per file on object stores; fetch them
    # concurrently so the advisory costs ~one round trip, not N
    with ThreadPoolExecutor(max_workers=min(64, len(paths))) as pool:
        sizes = sorted(pool.map(fs.size, paths))
    median = sizes[len(sizes) // 2]  # the larger one on a tie
    if median < RECOMMENDED_FILE_SIZE_BYTES:
        logger.warning(
            'The median parquet file size %d B (< 50 MB) is small; total '
            '%d B over %d files. Repartition/coalesce the dataframe to '
            'fewer, larger files for better read performance (first file: '
            '%s).', median, sum(sizes), len(sizes), urls[0])
    return median


class DatasetConverter:
    """A materialized (cached) Parquet copy of a dataframe, with loader
    factories over it."""

    def __init__(self, cache_dir_url, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.dataset_size = dataset_size
        self._deleted = False

    def __len__(self):
        return self.dataset_size

    # -- loader factories ----------------------------------------------------

    def make_tf_dataset(self, batch_size=32, num_epochs=1, **reader_kwargs):
        """Context manager yielding a ``tf.data.Dataset`` over the copy."""
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        converter = self

        class _Ctx:
            def __enter__(self):
                self._reader = make_batch_reader(converter.cache_dir_url,
                                                 num_epochs=num_epochs,
                                                 **reader_kwargs)
                dataset = make_petastorm_dataset(self._reader)
                return dataset.unbatch().batch(batch_size)

            def __exit__(self, exc_type, exc_val, exc_tb):
                self._reader.stop()
                self._reader.join()

        return _Ctx()

    def make_torch_dataloader(self, batch_size=32, num_epochs=1,
                              loader_kwargs=None, **reader_kwargs):
        """Context manager yielding a
        :class:`~petastorm_tpu.pytorch.BatchedDataLoader` over the copy."""
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        converter = self

        class _Ctx:
            def __enter__(self):
                reader = make_batch_reader(converter.cache_dir_url,
                                           num_epochs=num_epochs,
                                           **reader_kwargs)
                try:
                    self._loader = BatchedDataLoader(reader,
                                                     batch_size=batch_size,
                                                     **(loader_kwargs or {}))
                except Exception:
                    # loader construction failed: __exit__ will never run,
                    # so the live reader (pool already started) must be
                    # stopped here or its workers leak
                    reader.stop()
                    reader.join()
                    raise
                return self._loader

            def __exit__(self, exc_type, exc_val, exc_tb):
                self._loader.reader.stop()
                self._loader.reader.join()

        return _Ctx()

    def make_jax_loader(self, batch_size=32, **loader_kwargs):
        """A :class:`~petastorm_tpu.jax.JaxLoader` over the copy — the
        TPU-native consumer the reference has no analogue of."""
        from petastorm_tpu.jax import make_jax_loader
        return make_jax_loader(self.cache_dir_url, batch_size=batch_size,
                               **loader_kwargs)

    # -- lifecycle -----------------------------------------------------------

    def delete(self):
        """Remove the cached copy now (idempotent)."""
        if self._deleted:
            return
        from petastorm_tpu.fs import get_filesystem_and_path_or_paths
        fs, path = get_filesystem_and_path_or_paths(self.cache_dir_url)
        try:
            fs.rm(path, recursive=True)
        except Exception:  # noqa: BLE001 - already gone / perms
            logger.warning('Failed to delete cached dataset %s',
                           self.cache_dir_url, exc_info=True)
        with _CACHE_LOCK:
            for key, converter in list(_CACHE_REGISTRY.items()):
                if converter is self:
                    del _CACHE_REGISTRY[key]
        self._deleted = True


class SparkDatasetConverter(DatasetConverter):
    """Name parity with the reference's converter class (``:162``)."""


def make_dataframe_converter(df, parent_cache_dir_url, compression=None,
                             rowgroup_size_rows=10000, dtype=None):
    """Materialize a pandas DataFrame or pyarrow Table into a cached Parquet
    copy and return a :class:`DatasetConverter`.

    Cache hits are content-addressed: the same data + parent dir reuses the
    existing copy instead of re-materializing.

    :param dtype: ``'float32'``/``'float64'`` unifies floating-point
        columns (scalars and lists) to that precision before writing — the
        reference converter's ``dtype`` behavior (``:524-543``; it defaults
        to float32 there, the natural feed precision for bf16 TPU models).
        None (default) preserves the input precision.
    """
    import pyarrow as pa

    table = (pa.Table.from_pandas(df, preserve_index=False)
             if not isinstance(df, pa.Table) else df)
    table = _cast_table_precision(table, dtype)
    fingerprint = _table_fingerprint(table, parent_cache_dir_url)
    with _CACHE_LOCK:
        cached = _CACHE_REGISTRY.get(fingerprint)
    if cached is not None:
        logger.info('Converter cache hit: reusing %s', cached.cache_dir_url)
        return cached

    cache_url = '%s/%s' % (parent_cache_dir_url.rstrip('/'),
                           'ds-%s' % uuid.uuid4().hex[:16])
    path = _write_table(table, cache_url, compression, rowgroup_size_rows)
    wait_file_available([path], fs=_cache_fs(cache_url))
    converter = SparkDatasetConverter(cache_url, table.num_rows)
    with _CACHE_LOCK:
        _CACHE_REGISTRY[fingerprint] = converter
    atexit.register(converter.delete)
    return converter


def make_spark_converter(df, parent_cache_dir_url=None, compression=None,
                         rowgroup_size_mb=32, dtype='float32'):
    """Spark-parity converter (requires pyspark; reference ``:646-706``):
    the DataFrame is materialized by Spark into the parent cache dir (from
    the argument or the ``petastorm.spark.converter.parentCacheDirUrl``
    Spark conf). Before writing, ML vector columns become plain arrays and
    floating-point columns unify to ``dtype`` (reference ``:524-557``;
    default float32, like the reference). After writing, the materialized
    files are awaited (eventual-consistency stores) and the median file
    size advisory runs (``:595-640``)."""
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            'make_spark_converter requires pyspark; on TPU VMs prefer '
            'make_dataframe_converter (pandas/pyarrow, no Spark)') from e

    spark = df.sparkSession
    if parent_cache_dir_url is None:
        parent_cache_dir_url = spark.conf.get(PARENT_CACHE_DIR_URL_CONF, None)
    if not parent_cache_dir_url:
        raise ValueError(
            'parent_cache_dir_url must be given or set via the %r Spark conf'
            % PARENT_CACHE_DIR_URL_CONF)

    df = spark_vectors_to_arrays(df, dtype or 'float64')
    df = spark_unify_float_precision(df, dtype)

    fingerprint = hashlib.sha1(
        (parent_cache_dir_url + df._jdf.queryExecution().analyzed().toString())
        .encode('utf-8')).hexdigest()
    with _CACHE_LOCK:
        cached = _CACHE_REGISTRY.get(fingerprint)
    if cached is not None:
        return cached

    cache_url = '%s/%s' % (parent_cache_dir_url.rstrip('/'),
                           'ds-%s' % uuid.uuid4().hex[:16])
    writer = df.write
    if compression is not None:
        writer = writer.option('compression', compression)
    writer.option('parquet.block.size',
                  rowgroup_size_mb * 1024 * 1024).parquet(cache_url)
    _await_and_advise(spark, cache_url)
    converter = SparkDatasetConverter(cache_url, df.count())
    with _CACHE_LOCK:
        _CACHE_REGISTRY[fingerprint] = converter
    atexit.register(converter.delete)
    return converter


def spark_vectors_to_arrays(df, dtype='float64', vector_to_array=None):
    """Spark ML/MLlib vector columns → plain ``array<dtype>`` columns.

    Parquet (and every consumer downstream of it) has no notion of the
    ``VectorUDT`` struct encoding, so vectors must flatten before
    materialization (reference ``spark_dataset_converter.py:546-557``).
    Dispatch is by type name, not isinstance, so the logic is testable with
    a duck-typed dataframe when pyspark is absent.

    :param vector_to_array: injectable for tests; defaults to
        ``pyspark.ml.functions.vector_to_array``.
    """
    vector_cols = [f.name for f in df.schema
                   if type(f.dataType).__name__ == 'VectorUDT']
    if not vector_cols:
        return df
    if vector_to_array is None:
        from pyspark.ml.functions import vector_to_array
    for name in vector_cols:
        df = df.withColumn(name, vector_to_array(df[name], dtype))
    return df


def spark_unify_float_precision(df, dtype):
    """Cast float scalars/arrays to ``dtype`` ('float32'/'float64'/None).

    Reference ``spark_dataset_converter.py:524-543``: training feeds want
    one precision (float32 for bf16/f32 TPU models), not whatever mix the
    upstream ETL produced. None disables the cast. Uses ``typeName()``
    dispatch + string cast targets, so a duck-typed dataframe exercises it
    without pyspark.
    """
    if dtype is None:
        return df
    if dtype not in ('float32', 'float64'):
        raise ValueError("dtype must be 'float32', 'float64' or None; "
                         'got %r' % (dtype,))
    source, target = (('double', 'float') if dtype == 'float32'
                      else ('float', 'double'))
    converted = []
    for field in df.schema:
        data_type = field.dataType
        if data_type.typeName() == source:
            df = df.withColumn(field.name, df[field.name].cast(target))
            converted.append(field.name)
        elif (data_type.typeName() == 'array'
              and data_type.elementType.typeName() == source):
            df = df.withColumn(field.name,
                               df[field.name].cast('array<%s>' % target))
            converted.append(field.name)
    if converted:
        logger.warning('Converting floating-point columns %s to %s',
                       converted, dtype)
    return df


def _await_and_advise(spark, cache_url):
    """Post-materialization: wait for the written part files to be visible
    and run the median-size advisory over them.

    The file inventory comes from ``spark.read.parquet(url).inputFiles()``
    — a fresh Spark read of the just-committed dataset, exactly the
    reference's source (``:700-703``). Spark's commit protocol makes that
    index complete once the write returns; the wait then covers the
    remaining hazard on eventually-consistent stores: a file that is
    INDEXED but whose object is not yet individually visible to readers
    (list-after-write vs read-after-write consistency lag)."""
    try:
        file_urls = sorted(spark.read.parquet(cache_url).inputFiles())
    except Exception:  # noqa: BLE001 - advisory must never break the write
        logger.warning('Could not enumerate the materialized files of %s '
                       'from Spark metadata; skipping the availability '
                       'wait and size advisory', cache_url, exc_info=True)
        return
    parquet_urls = [u for u in file_urls if u.endswith('.parquet')]
    if not parquet_urls:
        return
    fs = _cache_fs(cache_url)
    from petastorm_tpu.fs import get_dataset_path
    paths = [get_dataset_path(u) for u in parquet_urls]
    wait_file_available(paths, fs=fs)
    check_dataset_file_median_size(paths, fs=fs)


# -- internals ---------------------------------------------------------------

def _table_fingerprint(table, parent_url):
    h = hashlib.sha1()
    h.update(parent_url.encode('utf-8'))
    h.update(str(table.schema).encode('utf-8'))
    h.update(str(table.num_rows).encode('utf-8'))
    # hash FULL buffer content: a prefix would collide for tables that
    # differ only in later rows and silently reuse a stale cached copy.
    # The chunk offset/length must participate too: zero-copy slices of one
    # parent share identical buffers and differ only in their view window.
    for column in table.columns:
        for chunk in column.chunks:
            h.update(b'%d:%d;' % (chunk.offset, len(chunk)))
            for buf in chunk.buffers():
                if buf is not None:
                    h.update(memoryview(buf))
    return h.hexdigest()


def _cache_fs(cache_url):
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    return get_filesystem_and_path_or_paths(cache_url)[0]


def _cast_table_precision(table, dtype):
    """Arrow-side equivalent of the reference's float-precision unification
    (``:524-543``): float scalars and list<float> columns cast to
    ``dtype``; other columns untouched."""
    if dtype is None:
        return table
    if dtype not in ('float32', 'float64'):
        raise ValueError("dtype must be 'float32', 'float64' or None; "
                         'got %r' % (dtype,))
    import pyarrow as pa
    source, target = ((pa.float64(), pa.float32()) if dtype == 'float32'
                      else (pa.float32(), pa.float64()))
    fields = []
    changed = []
    for field in table.schema:
        if field.type == source:
            fields.append(field.with_type(target))
            changed.append(field.name)
        elif (pa.types.is_list(field.type)
              and field.type.value_type == source):
            fields.append(field.with_type(pa.list_(target)))
            changed.append(field.name)
        else:
            fields.append(field)
    if not changed:
        return table
    logger.warning('Converting floating-point columns %s to %s', changed,
                   dtype)
    return table.cast(pa.schema(fields))


def _write_table(table, cache_url, compression, rowgroup_size_rows):
    import pyarrow.parquet as pq

    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    fs, path = get_filesystem_and_path_or_paths(cache_url)
    fs.makedirs(path, exist_ok=True)
    part_path = os.path.join(path, 'part-00000.parquet')
    with fs.open(part_path, 'wb') as f:
        pq.write_table(table, f, compression=compression or 'snappy',
                       row_group_size=rowgroup_size_rows)
    return part_path
