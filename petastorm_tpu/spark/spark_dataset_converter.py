"""DataFrame converters: materialize a dataframe once, read it many times.

Re-design of ``petastorm/spark/spark_dataset_converter.py``: the reference
converts a Spark DataFrame into a cached Parquet copy and hands out
TF/Torch loaders over it (``:162-293``). Here the same converter surface
exists in two flavors:

* :func:`make_dataframe_converter` — **Spark-free**: pandas DataFrames /
  pyarrow Tables, materialized with pyarrow. The primary path on a TPU VM.
* :func:`make_spark_converter` — Spark parity, lazily importing pyspark;
  the cached copy is written by Spark executors, everything downstream is
  shared with the Spark-free flavor.

Shared semantics with the reference: content-addressed cache hits (plan /
content fingerprint → same cached copy, ``:498-506``), atexit cleanup of
cached copies (``:587``), converters expose ``make_tf_dataset`` /
``make_torch_dataloader`` (+ TPU-native ``make_jax_loader``) and
``delete()``.
"""

import atexit
import hashlib
import logging
import os
import threading
import uuid

logger = logging.getLogger(__name__)

_CACHE_REGISTRY = {}
_CACHE_LOCK = threading.Lock()

#: Spark conf key for the parent cache dir (reference: ``:170``)
PARENT_CACHE_DIR_URL_CONF = 'petastorm.spark.converter.parentCacheDirUrl'


class DatasetConverter:
    """A materialized (cached) Parquet copy of a dataframe, with loader
    factories over it."""

    def __init__(self, cache_dir_url, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.dataset_size = dataset_size
        self._deleted = False

    def __len__(self):
        return self.dataset_size

    # -- loader factories ----------------------------------------------------

    def make_tf_dataset(self, batch_size=32, num_epochs=1, **reader_kwargs):
        """Context manager yielding a ``tf.data.Dataset`` over the copy."""
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        converter = self

        class _Ctx:
            def __enter__(self):
                self._reader = make_batch_reader(converter.cache_dir_url,
                                                 num_epochs=num_epochs,
                                                 **reader_kwargs)
                dataset = make_petastorm_dataset(self._reader)
                return dataset.unbatch().batch(batch_size)

            def __exit__(self, exc_type, exc_val, exc_tb):
                self._reader.stop()
                self._reader.join()

        return _Ctx()

    def make_torch_dataloader(self, batch_size=32, num_epochs=1,
                              loader_kwargs=None, **reader_kwargs):
        """Context manager yielding a
        :class:`~petastorm_tpu.pytorch.BatchedDataLoader` over the copy."""
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        converter = self

        class _Ctx:
            def __enter__(self):
                reader = make_batch_reader(converter.cache_dir_url,
                                           num_epochs=num_epochs,
                                           **reader_kwargs)
                try:
                    self._loader = BatchedDataLoader(reader,
                                                     batch_size=batch_size,
                                                     **(loader_kwargs or {}))
                except Exception:
                    # loader construction failed: __exit__ will never run,
                    # so the live reader (pool already started) must be
                    # stopped here or its workers leak
                    reader.stop()
                    reader.join()
                    raise
                return self._loader

            def __exit__(self, exc_type, exc_val, exc_tb):
                self._loader.reader.stop()
                self._loader.reader.join()

        return _Ctx()

    def make_jax_loader(self, batch_size=32, **loader_kwargs):
        """A :class:`~petastorm_tpu.jax.JaxLoader` over the copy — the
        TPU-native consumer the reference has no analogue of."""
        from petastorm_tpu.jax import make_jax_loader
        return make_jax_loader(self.cache_dir_url, batch_size=batch_size,
                               **loader_kwargs)

    # -- lifecycle -----------------------------------------------------------

    def delete(self):
        """Remove the cached copy now (idempotent)."""
        if self._deleted:
            return
        from petastorm_tpu.fs import get_filesystem_and_path_or_paths
        fs, path = get_filesystem_and_path_or_paths(self.cache_dir_url)
        try:
            fs.rm(path, recursive=True)
        except Exception:  # noqa: BLE001 - already gone / perms
            logger.warning('Failed to delete cached dataset %s',
                           self.cache_dir_url, exc_info=True)
        with _CACHE_LOCK:
            for key, converter in list(_CACHE_REGISTRY.items()):
                if converter is self:
                    del _CACHE_REGISTRY[key]
        self._deleted = True


class SparkDatasetConverter(DatasetConverter):
    """Name parity with the reference's converter class (``:162``)."""


def make_dataframe_converter(df, parent_cache_dir_url, compression=None,
                             rowgroup_size_rows=10000):
    """Materialize a pandas DataFrame or pyarrow Table into a cached Parquet
    copy and return a :class:`DatasetConverter`.

    Cache hits are content-addressed: the same data + parent dir reuses the
    existing copy instead of re-materializing.
    """
    import pyarrow as pa

    table = (pa.Table.from_pandas(df, preserve_index=False)
             if not isinstance(df, pa.Table) else df)
    fingerprint = _table_fingerprint(table, parent_cache_dir_url)
    with _CACHE_LOCK:
        cached = _CACHE_REGISTRY.get(fingerprint)
    if cached is not None:
        logger.info('Converter cache hit: reusing %s', cached.cache_dir_url)
        return cached

    cache_url = '%s/%s' % (parent_cache_dir_url.rstrip('/'),
                           'ds-%s' % uuid.uuid4().hex[:16])
    _write_table(table, cache_url, compression, rowgroup_size_rows)
    converter = SparkDatasetConverter(cache_url, table.num_rows)
    with _CACHE_LOCK:
        _CACHE_REGISTRY[fingerprint] = converter
    atexit.register(converter.delete)
    return converter


def make_spark_converter(df, parent_cache_dir_url=None, compression=None,
                         rowgroup_size_mb=32):
    """Spark-parity converter (requires pyspark; reference ``:646-706``):
    the DataFrame is materialized by Spark into the parent cache dir (from
    the argument or the ``petastorm.spark.converter.parentCacheDirUrl``
    Spark conf), with float-precision and vector→array handling left to the
    caller's select."""
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            'make_spark_converter requires pyspark; on TPU VMs prefer '
            'make_dataframe_converter (pandas/pyarrow, no Spark)') from e

    spark = df.sparkSession
    if parent_cache_dir_url is None:
        parent_cache_dir_url = spark.conf.get(PARENT_CACHE_DIR_URL_CONF, None)
    if not parent_cache_dir_url:
        raise ValueError(
            'parent_cache_dir_url must be given or set via the %r Spark conf'
            % PARENT_CACHE_DIR_URL_CONF)

    fingerprint = hashlib.sha1(
        (parent_cache_dir_url + df._jdf.queryExecution().analyzed().toString())
        .encode('utf-8')).hexdigest()
    with _CACHE_LOCK:
        cached = _CACHE_REGISTRY.get(fingerprint)
    if cached is not None:
        return cached

    cache_url = '%s/%s' % (parent_cache_dir_url.rstrip('/'),
                           'ds-%s' % uuid.uuid4().hex[:16])
    writer = df.write
    if compression is not None:
        writer = writer.option('compression', compression)
    writer.option('parquet.block.size',
                  rowgroup_size_mb * 1024 * 1024).parquet(cache_url)
    converter = SparkDatasetConverter(cache_url, df.count())
    with _CACHE_LOCK:
        _CACHE_REGISTRY[fingerprint] = converter
    atexit.register(converter.delete)
    return converter


# -- internals ---------------------------------------------------------------

def _table_fingerprint(table, parent_url):
    h = hashlib.sha1()
    h.update(parent_url.encode('utf-8'))
    h.update(str(table.schema).encode('utf-8'))
    h.update(str(table.num_rows).encode('utf-8'))
    # hash FULL buffer content: a prefix would collide for tables that
    # differ only in later rows and silently reuse a stale cached copy.
    # The chunk offset/length must participate too: zero-copy slices of one
    # parent share identical buffers and differ only in their view window.
    for column in table.columns:
        for chunk in column.chunks:
            h.update(b'%d:%d;' % (chunk.offset, len(chunk)))
            for buf in chunk.buffers():
                if buf is not None:
                    h.update(memoryview(buf))
    return h.hexdigest()


def _write_table(table, cache_url, compression, rowgroup_size_rows):
    import pyarrow.parquet as pq

    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    fs, path = get_filesystem_and_path_or_paths(cache_url)
    fs.makedirs(path, exist_ok=True)
    with fs.open(os.path.join(path, 'part-00000.parquet'), 'wb') as f:
        pq.write_table(table, f, compression=compression or 'snappy',
                       row_group_size=rowgroup_size_rows)
