"""TransformSpec: user transforms executed on decode workers, outside jit.

Parity with ``petastorm/transform.py:27-89``. The callable runs host-side on a
pool worker (row dict for ``make_reader``, pandas DataFrame for
``make_batch_reader``); it is explicitly *not* traced by XLA — device-side
per-batch transforms belong in :mod:`petastorm_tpu.ops`.
"""

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec:
    """Describes a worker-side transform and its effect on the schema.

    :param func: callable applied to each row dict (row readers) or to a whole
        row-group pandas DataFrame (batch readers). May be None for pure
        schema edits (field removal/selection).
    :param edit_fields: list of ``UnischemaField`` (or 4-tuples
        ``(name, numpy_dtype, shape, nullable)``) added/replaced by the
        transform.
    :param removed_fields: list of field names deleted by the transform.
    :param selected_fields: if not None, exactly these field names remain,
        in this order (mutually exclusive with removed_fields).
    :param cacheable: whether the materialized decoded cache
        (``cache_type='decoded'``) may cache this transform's output.
        The cache keys a transform by its *code* — it cannot tell a
        random crop from a deterministic resize, and caching a
        STOCHASTIC transform would silently replay epoch 1's
        augmentations forever. ``False``: never cache (the required
        marking for random augmentation). ``True``: explicitly
        deterministic — cacheable everywhere. ``None`` (default):
        cacheable when the reader *explicitly* requested the decoded
        cache, but NOT under the implicit fleet-wide
        ``PETASTORM_TPU_DECODED_CACHE=1`` upgrade — an operator flipping
        that knob must not silently freeze pre-existing jobs' transforms
        whose determinism nobody ever declared.
    """

    def __init__(self, func=None, edit_fields=None, removed_fields=None,
                 selected_fields=None, cacheable=None):
        if removed_fields and selected_fields:
            raise ValueError('removed_fields and selected_fields are mutually exclusive')
        self.func = func
        self.edit_fields = [self._as_field(f) for f in (edit_fields or [])]
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        self.cacheable = cacheable

    @staticmethod
    def _as_field(f):
        if isinstance(f, UnischemaField):
            return f
        name, numpy_dtype, shape, nullable = f
        return UnischemaField(name, numpy_dtype, shape, None, nullable)

    def __call__(self, data):
        return self.func(data) if self.func is not None else data


def transform_schema(schema, transform_spec):
    """Apply a TransformSpec's declarative edits to a schema.

    Reference: ``petastorm/transform.py:60-89``.
    """
    edited = dict(schema.fields)
    for f in transform_spec.edit_fields:
        edited[f.name] = f
    for name in transform_spec.removed_fields:
        edited.pop(name, None)
    if transform_spec.selected_fields is not None:
        missing = [n for n in transform_spec.selected_fields if n not in edited]
        if missing:
            raise ValueError('selected_fields not present after edits: %s' % missing)
        ordered = [edited[n] for n in transform_spec.selected_fields]
    else:
        ordered = list(edited.values())
    return Unischema('%s_transformed' % schema._name, ordered)
