"""NGram: sliding-window sequence readout over timestamp-sorted rows.

Re-design of ``petastorm/ngram.py`` for the column-major worker: instead of
sliding a window over a list of row dicts, window admission is computed
**vectorized on the timestamp column** (a cumulative count of delta-threshold
violations makes every window's validity an O(1) lookup), and only surviving
windows materialize per-timestep namedtuples. Semantics parity:

* ``fields``: ``{timestep(int): [UnischemaField | regex str]}``; window length
  is ``max(keys) - min(keys) + 1`` (``ngram.py:127-132``); keys may have gaps
  (the in-between timesteps carry no fields but still consume a row).
* ``delta_threshold``: max allowed gap between *consecutive* rows inside a
  window (inclusive), measured on ``timestamp_field`` (``ngram.py:178-193``).
* ``timestamp_overlap=False``: windows may not share timestamps — a window is
  admitted only if it starts strictly after the previous admitted window's end
  (``ngram.py:248-253``).
* Rows must already be sorted by timestamp within the row-group; unsorted data
  raises ``NotImplementedError`` (``ngram.py:243-246``). Windows never cross
  row-group boundaries (``ngram.py:85-91``).
"""

import numbers

import numpy as np

from petastorm_tpu.unischema import UnischemaField, match_unischema_fields


class NGram:
    """Sliding-window readout: each emitted item is
    ``{timestep: namedtuple-of-fields-at-that-timestep}``."""

    def __init__(self, fields, delta_threshold, timestamp_field,
                 timestamp_overlap=True):
        self._validate(fields, delta_threshold, timestamp_field, timestamp_overlap)
        self._fields = fields
        self._delta_threshold = delta_threshold
        self._timestamp_field = timestamp_field
        self.timestamp_overlap = timestamp_overlap

    # -- construction --------------------------------------------------------

    @staticmethod
    def _validate(fields, delta_threshold, timestamp_field, timestamp_overlap):
        if not isinstance(fields, dict) or not fields:
            raise ValueError('fields must be a non-empty dict of '
                             '{timestep: [field|regex]}')
        for key, value in fields.items():
            if not isinstance(key, numbers.Integral):
                raise ValueError('fields keys must be integers; got %r' % (key,))
            if not isinstance(value, list):
                raise ValueError('Each fields value must be a list of unischema '
                                 'fields / regular expressions')
            for f in value:
                if not isinstance(f, (UnischemaField, str)):
                    raise ValueError('All field values must be UnischemaField '
                                     'or regular expression strings')
        if not isinstance(delta_threshold, numbers.Number) or \
                isinstance(delta_threshold, bool):
            raise ValueError('delta_threshold must be a number')
        if not isinstance(timestamp_field, (UnischemaField, str)):
            raise ValueError('timestamp_field must be a UnischemaField or a '
                             'regular expression string')
        if not isinstance(timestamp_overlap, bool):
            raise ValueError('timestamp_overlap must be a bool')

    @property
    def length(self):
        return max(self._fields) - min(self._fields) + 1

    @property
    def fields(self):
        return self._fields

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def timestamp_field(self):
        return self._timestamp_field

    def resolve_regex_field_names(self, schema):
        """Replace regex strings in ``fields``/``timestamp_field`` with the
        matching :class:`UnischemaField` objects (``ngram.py:195-205``)."""
        self._fields = {k: self._convert_fields(schema, v)
                        for k, v in self._fields.items()}
        ts = self._convert_fields(schema, [self._timestamp_field])
        if len(ts) != 1:
            raise ValueError('timestamp_field must match exactly one unischema '
                             'field; matched %d' % len(ts))
        self._timestamp_field = ts[0]

    @staticmethod
    def _convert_fields(schema, field_list):
        regexes = [f for f in field_list if isinstance(f, str)]
        fields = [f for f in field_list if isinstance(f, UnischemaField)]
        if len(fields) + len(regexes) != len(field_list):
            raise ValueError('fields/timestamp_field entries must be '
                             'UnischemaField objects or regex strings')
        return fields + match_unischema_fields(schema, regexes)

    # -- schema queries ------------------------------------------------------

    def get_field_names_at_timestep(self, timestep):
        if timestep not in self._fields:
            return []
        return [f.name for f in self._fields[timestep]]

    def get_schema_at_timestep(self, schema, timestep):
        # Memoized per (schema, timestep): the consumer calls this once per
        # yielded window, and view construction iterates the whole schema.
        cache = self.__dict__.setdefault('_view_cache', {})
        key = (id(schema), timestep)
        view = cache.get(key)
        if view is None:
            names = set(self.get_field_names_at_timestep(timestep))
            view = schema.create_schema_view(
                [schema.fields[n] for n in schema.fields if n in names])
            cache[key] = view
            # hold the schema so its id() stays unique while cached
            self.__dict__.setdefault('_view_cache_schemas', []).append(schema)
        return view

    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop('_view_cache', None)
        state.pop('_view_cache_schemas', None)
        return state

    def get_field_names_at_all_timesteps(self):
        """Union of fields over all timesteps plus the timestamp field (the
        timestamp is always loaded so window admission can be evaluated)."""
        fields = {f for flist in self._fields.values() for f in flist}
        fields.add(self._timestamp_field)
        return list(fields)

    # -- window formation ----------------------------------------------------

    def form_ngram(self, batch, schema):
        """All admitted windows of a decoded column batch.

        Windows are ``{timestep: {field: value}}`` plain dicts — NOT
        namedtuples — so they cross the process pool's pickle boundary
        (dynamically-created namedtuple classes don't); the consumer
        converts via :meth:`make_namedtuple`, mirroring the reference's
        worker-publishes-dicts design (``py_dict_reader_worker.py:91``).

        :param batch: a :class:`~petastorm_tpu.arrow_worker.ColumnBatch` whose
            columns include the timestamp field.
        :param schema: the loaded :class:`Unischema` (field-name source).
        :return: list of ``{timestep: dict}`` dicts.
        """
        ts_name = self._ts_name()
        ts = np.asarray(batch.columns[ts_name])
        n = int(ts.shape[0])
        L = self.length
        if n < L:
            return []
        if np.any(ts[1:] < ts[:-1]):
            raise NotImplementedError(
                'NGram assumes data sorted by the %s field within each '
                'row-group, which is not the case' % ts_name)
        # valid_start[i] ⇔ no delta violation inside rows [i, i+L).
        if L > 1:
            violations = (np.diff(ts) > self._delta_threshold).astype(np.int64)
            cum = np.concatenate([[0], np.cumsum(violations)])
            valid_start = (cum[L - 1:] - cum[:n - L + 1]) == 0
        else:
            valid_start = np.ones(n, dtype=bool)

        starts = np.flatnonzero(valid_start)
        if not self.timestamp_overlap:
            kept = []
            prev_end_ts = None
            for i in starts:
                if prev_end_ts is not None and ts[i] <= prev_end_ts:
                    continue
                kept.append(i)
                prev_end_ts = ts[i + L - 1]
            starts = kept

        base = min(self._fields)
        ts_names = {k: list(self.get_schema_at_timestep(schema, k).fields)
                    for k in self._fields}
        windows = []
        for i in starts:
            window = {}
            for key in self._fields:
                offset = int(i) + (key - base)
                window[key] = {name: batch.columns[name][offset]
                               for name in ts_names[key]}
            windows.append(window)
        return windows

    def make_namedtuple(self, schema, ngram_as_dicts):
        """``{timestep: dict}`` → ``{timestep: namedtuple}`` using the schema
        view at each timestep (``ngram.py:272-295``)."""
        out = {}
        for timestep, row in ngram_as_dicts.items():
            view = self.get_schema_at_timestep(schema, timestep)
            out[timestep] = view.make_namedtuple(**row)
        return out

    def _ts_name(self):
        ts = self._timestamp_field
        return ts.name if isinstance(ts, UnischemaField) else ts

    # -- comparison ----------------------------------------------------------

    def __eq__(self, other):
        if not isinstance(other, NGram):
            return NotImplemented
        if set(self._fields) != set(other._fields):
            return False
        return all(set(self._fields[k]) == set(other._fields[k])
                   for k in self._fields)

    def __ne__(self, other):
        return not self == other
