"""petastorm_tpu — a TPU-native Parquet data access framework.

A from-scratch re-design of the capabilities of petastorm (see SURVEY.md) for
JAX/TPU: Unischema + codecs over Parquet, a materialization write path, a
row-group-ventilating batched read path, and bridges to JAX (sharded
``jax.Array`` loaders), tf.data and PyTorch.

Public API parity target: ``petastorm/__init__.py:15-17`` exports exactly
``make_reader``, ``make_batch_reader``, ``TransformSpec`` and
``NoDataAvailableError``; this package adds ``make_jax_loader`` as the
TPU-native entry point.
"""

from petastorm_tpu.errors import NoDataAvailableError  # noqa: F401
from petastorm_tpu.transform import TransformSpec  # noqa: F401

__version__ = '0.1.0'


def make_reader(*args, **kwargs):
    from petastorm_tpu.reader import make_reader as _make_reader
    return _make_reader(*args, **kwargs)


def make_batch_reader(*args, **kwargs):
    from petastorm_tpu.reader import make_batch_reader as _make_batch_reader
    return _make_batch_reader(*args, **kwargs)


def make_jax_loader(*args, **kwargs):
    from petastorm_tpu.jax import make_jax_loader as _make_jax_loader
    return _make_jax_loader(*args, **kwargs)
