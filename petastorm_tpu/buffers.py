"""Shuffling buffers: decorrelation stage between row-group reads and batches.

Re-design of ``petastorm/reader_impl/shuffling_buffer.py`` (row-level) and
``pytorch_shuffling_buffer.py`` (batched tensors). The TPU-first change: the
**batched, column-major buffers are the primary implementation** — contiguous
preallocated numpy column buffers with vectorized random retrieval — because
they feed the JAX device stage and the Torch bridge directly; the row-level
buffers remain for the row-at-a-time API.

Contract (shared by all flavors, reference ``shuffling_buffer.py:22-72``):
``can_add`` → ``add_many(items)``, ``can_retrieve`` → ``retrieve()``,
``finish()`` when upstream is exhausted, then drain until ``size == 0``.
"""

from abc import ABCMeta, abstractmethod
from collections import deque

import numpy as np


class ShufflingBufferBase(metaclass=ABCMeta):
    """Row-level buffer contract."""

    @abstractmethod
    def add_many(self, items):
        """Store items; only legal while ``can_add``."""

    @abstractmethod
    def retrieve(self):
        """Return one item; only legal while ``can_retrieve``."""

    @abstractmethod
    def finish(self):
        """Upstream exhausted: everything buffered becomes retrievable."""

    @property
    @abstractmethod
    def can_add(self):
        """True when the buffer will accept more items."""

    @property
    @abstractmethod
    def can_retrieve(self):
        """True when retrieve() would return an item."""

    @property
    @abstractmethod
    def size(self):
        """Number of buffered items."""


class NoopShufflingBuffer(ShufflingBufferBase):
    """FIFO pass-through (reference: ``shuffling_buffer.py:75-100``)."""

    def __init__(self):
        self._items = deque()
        self._done = False

    def add_many(self, items):
        if not self.can_add:
            raise RuntimeError('add_many called on a finished buffer')
        self._items.extend(items)

    def retrieve(self):
        return self._items.popleft()

    def finish(self):
        self._done = True

    @property
    def can_add(self):
        return not self._done

    @property
    def can_retrieve(self):
        return len(self._items) > 0

    @property
    def size(self):
        return len(self._items)


class RandomShufflingBuffer(ShufflingBufferBase):
    """Uniform-random retrieval with swap-remove
    (reference: ``shuffling_buffer.py:103-180``).

    :param shuffling_buffer_capacity: soft fill target; ``can_add`` turns
        False at this size, but one ``add_many`` may overshoot up to
        ``extra_capacity`` (callers add whole row-groups at once).
    :param min_after_retrieve: retrieval blocks until this many items are
        buffered (decorrelation floor), except after :meth:`finish`.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve=0,
                 extra_capacity=0, seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve (%d) must not exceed the '
                             'buffer capacity (%d)'
                             % (min_after_retrieve, shuffling_buffer_capacity))
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._items = []
        self._done = False
        self._rng = np.random.RandomState(seed)

    def add_many(self, items):
        if not self.can_add:
            raise RuntimeError('add_many called on a full or finished buffer')
        self._items.extend(items)

    def retrieve(self):
        if not self.can_retrieve:
            raise RuntimeError('retrieve called but can_retrieve is False')
        idx = self._rng.randint(len(self._items))
        # swap-remove: O(1), order irrelevant in a shuffling buffer
        self._items[idx], self._items[-1] = self._items[-1], self._items[idx]
        return self._items.pop()

    def finish(self):
        self._done = True

    @property
    def can_add(self):
        return not self._done and len(self._items) < self._capacity

    @property
    def can_retrieve(self):
        if self._done:
            return len(self._items) > 0
        # >= (not >): capacity == min_after_retrieve is a legal config and
        # must not deadlock the add-while-can_add/retrieve-while-can_retrieve
        # driving loop.
        return len(self._items) >= max(1, self._min_after_retrieve)

    @property
    def size(self):
        return len(self._items)


class BatchedShufflingBufferBase(metaclass=ABCMeta):
    """Column-major buffer contract: items are ``{name: ndarray}`` dicts of
    equal leading dimension; retrieval returns fixed-size batches.

    Reference: ``pytorch_shuffling_buffer.py:22-84`` — but numpy column
    buffers instead of torch tensors, so the same implementation feeds JAX
    staging, the Torch bridge (via ``torch.from_numpy`` zero-copy), and TF.
    """

    def __init__(self, batch_size):
        self.batch_size = batch_size

    @abstractmethod
    def add_many(self, columns):
        """Append a column-dict chunk."""

    @abstractmethod
    def retrieve(self):
        """Return a ``{name: ndarray}`` batch with ``batch_size`` rows."""

    @abstractmethod
    def finish(self):
        """Upstream exhausted; remaining rows become retrievable (the final
        batch may be short)."""

    @property
    @abstractmethod
    def can_add(self):
        """True when the buffer will accept more chunks."""

    @property
    @abstractmethod
    def can_retrieve(self):
        """True when retrieve() would return a batch."""

    @property
    @abstractmethod
    def size(self):
        """Buffered row count."""


class BatchedNoopShufflingBuffer(BatchedShufflingBufferBase):
    """Order-preserving re-batcher: chunks in, fixed batches out
    (reference: ``pytorch_shuffling_buffer.py:111-159``)."""

    def __init__(self, batch_size):
        super().__init__(batch_size)
        self._chunks = deque()
        self._size = 0
        self._done = False

    def add_many(self, columns):
        if not self.can_add:
            raise RuntimeError('add_many called on a finished buffer')
        n = _leading_dim(columns)
        if n == 0:
            return
        self._chunks.append(columns)
        self._size += n

    def retrieve_parts(self):
        """One batch as a LIST of column-dict parts (views/whole chunks,
        no concatenation): consumers that copy into a preallocated
        destination — the JAX staging arena — skip the intermediate
        concatenated batch allocation entirely."""
        if not self.can_retrieve:
            raise RuntimeError('retrieve called but can_retrieve is False')
        want = min(self.batch_size, self._size)
        parts = []
        got = 0
        while got < want:
            chunk = self._chunks[0]
            n = _leading_dim(chunk)
            take = min(n, want - got)
            if take == n:
                parts.append(self._chunks.popleft())
            else:
                parts.append({k: v[:take] for k, v in chunk.items()})
                self._chunks[0] = {k: v[take:] for k, v in chunk.items()}
            got += take
        self._size -= want
        return parts

    def retrieve(self):
        parts = self.retrieve_parts()
        if len(parts) == 1:
            return parts[0]
        return {k: _concat([p[k] for p in parts]) for k in parts[0]}

    def finish(self):
        self._done = True

    @property
    def can_add(self):
        return not self._done

    @property
    def can_retrieve(self):
        return self._size >= self.batch_size or (self._done and self._size > 0)

    @property
    def size(self):
        return self._size


class BatchedRandomShufflingBuffer(BatchedShufflingBufferBase):
    """Uniform-random fixed-size batches out of a contiguous column buffer.

    Columns are preallocated to ``capacity + extra_capacity`` rows on first
    add; retrieval gathers ``batch_size`` random rows and compacts the holes
    with tail rows — all vectorized (reference keeps torch tensors and slices
    a randperm, ``pytorch_shuffling_buffer.py:162-291``).
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve,
                 batch_size, extra_capacity=0, seed=None):
        super().__init__(batch_size)
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve (%d) must not exceed the '
                             'buffer capacity (%d)'
                             % (min_after_retrieve, shuffling_buffer_capacity))
        if batch_size > shuffling_buffer_capacity:
            raise ValueError('batch_size (%d) must not exceed the buffer '
                             'capacity (%d)'
                             % (batch_size, shuffling_buffer_capacity))
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._buffers = None
        self._size = 0
        self._done = False
        self._rng = np.random.RandomState(seed)

    def _ensure_buffers(self, columns):
        if self._buffers is not None:
            return
        cap = self._capacity + self._extra_capacity
        self._buffers = {}
        for name, arr in columns.items():
            arr = np.asarray(arr)
            self._buffers[name] = np.empty((cap,) + arr.shape[1:], dtype=arr.dtype)

    def add_many(self, columns):
        if not self.can_add:
            raise RuntimeError('add_many called on a full or finished buffer')
        columns = {k: np.asarray(v) for k, v in columns.items()}
        n = _leading_dim(columns)
        if n == 0:
            return
        self._ensure_buffers(columns)
        if self._size + n > next(iter(self._buffers.values())).shape[0]:
            raise RuntimeError(
                'Chunk of %d rows overflows the shuffling buffer (capacity %d '
                '+ extra %d, size %d); raise extra_capacity to at least the '
                'row-group size' % (n, self._capacity, self._extra_capacity,
                                    self._size))
        for name, arr in columns.items():
            buf = self._buffers[name]
            # Widen the buffer when a later chunk needs a wider dtype (e.g.
            # '<U3' → '<U10', int32 → int64): plain assignment would silently
            # truncate/wrap instead.
            promoted = np.promote_types(buf.dtype, arr.dtype) \
                if buf.dtype != arr.dtype else buf.dtype
            if promoted != buf.dtype:
                buf = buf.astype(promoted)
                self._buffers[name] = buf
            buf[self._size:self._size + n] = arr
        self._size += n

    def retrieve(self):
        if not self.can_retrieve:
            raise RuntimeError('retrieve called but can_retrieve is False')
        k = min(self.batch_size, self._size)
        sel = self._rng.choice(self._size, size=k, replace=False)
        # fancy indexing already allocates fresh arrays — no copy needed
        batch = {name: buf[sel] for name, buf in self._buffers.items()}
        self._compact(sel, k)
        self._size -= k
        return batch

    def _compact(self, sel, k):
        """Backfill the vacated slots with surviving tail rows (vectorized
        swap-remove): holes below the new size get the non-selected rows
        living at or above it."""
        new_size = self._size - k
        sel_mask = np.zeros(self._size, dtype=bool)
        sel_mask[sel] = True
        holes = np.flatnonzero(sel_mask[:new_size])
        movers = np.flatnonzero(~sel_mask[new_size:]) + new_size
        for buf in self._buffers.values():
            buf[holes] = buf[movers]

    def finish(self):
        self._done = True

    @property
    def can_add(self):
        return not self._done and self._size < self._capacity

    @property
    def can_retrieve(self):
        if self._done:
            return self._size > 0
        return self._size >= max(self.batch_size, self._min_after_retrieve)

    @property
    def size(self):
        return self._size


def _leading_dim(columns):
    return len(next(iter(columns.values())))


def _concat(arrays):
    if arrays[0].dtype == object:
        out = np.empty(sum(len(a) for a in arrays), dtype=object)
        pos = 0
        for a in arrays:
            out[pos:pos + len(a)] = a
            pos += len(a)
        return out
    return np.concatenate(arrays)
