"""Statistics-driven row-group pruning: the selective-read planner.

At production scale most traffic is selective — eval slices, per-user
shards, rejection-sampled RL batches — yet a predicate read used to
ventilate **every** row-group, decode it whole, and drop the rows after
the fact: full-scan price for an index-shaped question. This module is
the plan-time third of the selective-read fast path (ROADMAP
"Query-shaped reads"; the tabular-preprocessing study, PAPERS.md arxiv
2409.14912, locates the next order of magnitude for selective workloads
exactly here):

* **before ventilation** the planner reads each parquet file's footer —
  one footer read per *file*, memoized process-wide per file identity
  (size + mtime) so repeat readers over the same dataset pay zero
  footer I/O — and proves row-groups empty against the predicate from
  the per-row-group column statistics (min/max/null_count);
* proven-empty row-groups **never reach the worker pool**: the Reader
  treats their work items as completed-with-zero-rows (the ventilator
  skips them every epoch, checkpoint/resume accounting counts them
  consumed), so sharding, in-flight bounds and exactly-once delivery
  are untouched;
* everything uncertain is **kept**: a failed footer read, a column
  without statistics, an incomparable type, an arbitrary predicate — a
  wrong prune would silently lose rows, so the planner only ever prunes
  what the statistics *prove* empty. `PETASTORM_TPU_PUSHDOWN=0` turns
  the whole planner off (the comparison oracle the exact-parity tests
  read against).

What the prover understands (everything else is `arbitrary-predicate`):

* :class:`~petastorm_tpu.filters.FiltersPredicate` — exact interval
  logic per DNF clause. Equality/range/``in`` terms prune on the
  non-null min/max alone (a null cell — None for object columns, NaN
  for numeric ones — can never compare true there); the negative terms
  ``!=``/``not in`` additionally require a null-free row-group, because
  numeric nulls decode to NaN and ``NaN != value`` IS true at worker
  evaluation.
* :class:`~petastorm_tpu.predicates.in_set` — interval logic over the
  value set, **null-safe**: ``in_set`` is a plain membership test, so
  ``None`` in the value set *does* match null rows and a row-group with
  ``null_count > 0`` (or an unknown null count) is then never pruned.
* :class:`~petastorm_tpu.predicates.in_reduce` — ``all``: pruned when
  any prunable child proves the row-group empty; ``any``: pruned only
  when every child is prunable and proves it empty.

The worker-side two-thirds live in
:meth:`petastorm_tpu.arrow_worker.RowGroupWorker._load_rowgroup`
(projection pushdown + late materialization, the ``late_materialize``
stage) and the decoded cache's predicate-digest keying. Planner
decisions surface as ``pipeline_report()['pushdown']`` and the
``petastorm_tpu_rowgroups_pruned_total`` / ``..._rows_pruned_total``
counters; the "My selective read is still full-scan-priced" runbook in
docs/troubleshoot.md reads the decline reasons recorded here.
"""

import logging
import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

from petastorm_tpu import faults
from petastorm_tpu import filters as _filters
from petastorm_tpu.predicates import in_reduce, in_set
from petastorm_tpu.telemetry import get_registry, knobs, metrics_disabled

logger = logging.getLogger(__name__)

#: registry counters (docs/telemetry.md metric reference). Pruning
#: happens in the consumer process (Reader construction); the
#: late-materialization counter is incremented worker-side
#: (arrow_worker) and rides the pool delta channels like every metric.
ROWGROUPS_PRUNED = 'petastorm_tpu_rowgroups_pruned_total'
ROWS_PRUNED = 'petastorm_tpu_rows_pruned_total'
LATE_MATERIALIZED_ROWS = 'petastorm_tpu_late_materialized_rows_total'

#: decline reasons recorded in the planner summary
#: (``pipeline_report()['pushdown']['declines']``; see the
#: full-scan-priced runbook in docs/troubleshoot.md). Units differ by
#: reason: ``arbitrary-predicate`` and ``low-selectivity`` count planner
#: RUNS, ``no-statistics`` counts ROW-GROUPS kept for lack of usable
#: statistics (missing column stats or a failed footer read).
DECLINE_ARBITRARY = 'arbitrary-predicate'
DECLINE_NO_STATS = 'no-statistics'
DECLINE_LOW_SELECTIVITY = 'low-selectivity'

#: process-wide footer-stats memo: (dataset url, file path, size-mtime
#: fingerprint) -> per-row-group [(col stats, num_rows), ...]. Bounded
#: FIFO so long-lived multi-dataset processes cannot grow it without
#: limit; a rewritten file changes its fingerprint and misses.
_FOOTER_CACHE_MAX_FILES = 4096
_footer_cache_lock = threading.Lock()
_footer_cache = OrderedDict()

_summary_lock = threading.Lock()


def _fresh_summary():
    return {'planner_runs': 0, 'rowgroups_considered': 0,
            'rowgroups_pruned': 0, 'rows_pruned': 0, 'declines': {}}


_summary = _fresh_summary()


def pushdown_enabled():
    """Plan-time pruning gate. ``PETASTORM_TPU_PUSHDOWN=0`` turns the
    WHOLE selective-read fast path off (this planner and the worker's
    late-materialization shape — the decode-everything-then-filter
    exact-parity oracle); ``PETASTORM_TPU_PUSHDOWN_PRUNE=0`` turns off
    only this planner, keeping late materialization — the attribution
    rung the bench's ``selective_read`` section measures. Read at Reader
    construction, never on the hot path, so deliberately cache-free."""
    return (not knobs.is_disabled('PETASTORM_TPU_PUSHDOWN')
            and not knobs.is_disabled('PETASTORM_TPU_PUSHDOWN_PRUNE'))


def fullscan_oracle():
    """True when ``PETASTORM_TPU_PUSHDOWN=0`` demands the worker's
    decode-everything-then-filter oracle shape (read every column, decode
    every row, filter the decoded arrays after the fact) — the
    comparison baseline for exact-parity tests and the bench's
    full-scan-priced rung. Never the production path."""
    return knobs.is_disabled('PETASTORM_TPU_PUSHDOWN')


def planner_summary():
    """Consumer-local planner activity: runs, row-groups considered /
    pruned, and decline reasons — the ``pushdown`` report section's
    plan-time half (the registry counters are its fleet-merged half)."""
    with _summary_lock:
        out = dict(_summary)
        out['declines'] = dict(_summary['declines'])
        return out


def reset_for_tests():
    """Fresh planner summary + footer memo (test isolation only)."""
    global _summary
    with _summary_lock:
        _summary = _fresh_summary()
    with _footer_cache_lock:
        _footer_cache.clear()


def _note_run(considered, pruned=0, rows=0, declines=None):
    with _summary_lock:
        _summary['planner_runs'] += 1
        _summary['rowgroups_considered'] += considered
        _summary['rowgroups_pruned'] += pruned
        _summary['rows_pruned'] += rows
        for reason, count in (declines or {}).items():
            if count:
                _summary['declines'][reason] = \
                    _summary['declines'].get(reason, 0) + count


# -- footer statistics index -------------------------------------------------


class StatsIndex:
    """Per-file parquet footer statistics, fetched lazily and in
    parallel (``PETASTORM_TPU_PUSHDOWN_WORKERS`` threads), memoized
    process-wide by file identity. One footer read per *file*, never per
    row-group; a file whose footer fails to load yields None and every
    one of its row-groups is conservatively kept.

    Each memoized row-group entry also carries the exact **byte ranges**
    of its column chunks (``{root column: [(offset, length), ...]}``) —
    the readahead plane (:mod:`petastorm_tpu.readahead`) plans its
    coalesced prefetch reads from the same one-footer-read-per-file memo
    the pruning planner already pays for."""

    def __init__(self, dataset_info):
        self._info = dataset_info
        self._per_file = {}

    def prefetch(self, paths):
        todo = sorted(set(paths) - set(self._per_file))
        if not todo:
            return
        workers = knobs.get_int('PETASTORM_TPU_PUSHDOWN_WORKERS', 8, floor=1)
        with ThreadPoolExecutor(max_workers=min(workers, len(todo))) as ex:
            for path, stats in zip(todo, ex.map(self._load, todo)):
                self._per_file[path] = stats

    def get(self, path, row_group):
        """``(column stats dict, num_rows)`` for one row-group, or None
        when statistics are unavailable for its file."""
        stats = self._per_file.get(path)
        if stats is None or row_group >= len(stats):
            return None
        cols, num_rows, _ = stats[row_group]
        return cols, num_rows

    def get_ranges(self, path, row_group):
        """``{root column: [(byte offset, length), ...]}`` of one
        row-group's column chunks, or None when the footer was
        unreadable — the readahead plane's range planner."""
        stats = self._per_file.get(path)
        if stats is None or row_group >= len(stats):
            return None
        return stats[row_group][2]

    def _load(self, path):
        key = None
        fingerprint = self._fingerprint(path)
        if fingerprint is not None:
            key = (str(self._info.url), path, fingerprint)
            with _footer_cache_lock:
                if key in _footer_cache:
                    _footer_cache.move_to_end(key)
                    return _footer_cache[key]
        stats = self._read_footer(path)
        if stats is not None and key is not None:
            with _footer_cache_lock:
                _footer_cache[key] = stats
                while len(_footer_cache) > _FOOTER_CACHE_MAX_FILES:
                    _footer_cache.popitem(last=False)
        return stats

    def _fingerprint(self, path):
        """File identity for the memo — the decoded cache's size+mtime
        rule (:func:`~petastorm_tpu.materialized_cache.
        dataset_file_fingerprint`, the ONE owner of that logic), except
        its path-only ``'nostat'`` fallback becomes None here: rather
        than risking stale statistics, an unidentifiable file simply
        skips memoization."""
        from petastorm_tpu.materialized_cache import dataset_file_fingerprint
        fingerprint = dataset_file_fingerprint(self._info, path)
        return None if fingerprint == 'nostat' else fingerprint

    def _read_footer(self, path):
        import pyarrow.parquet as pq
        try:
            # same faultpoint as the worker's row-group read: a chaos
            # spec can fail footer reads (match=#footer) and the planner
            # must degrade to unpruned reads, never to a wrong answer
            if faults.ARMED:
                faults.fault_hit('io.read', key='%s#footer' % path)
            with self._info.fs.open(path, 'rb') as f:
                meta = pq.ParquetFile(f).metadata
        except Exception:  # noqa: BLE001 - degrade to unpruned, loudly
            logger.warning('pushdown: failed to read parquet footer of %s; '
                           'its row-groups will not be pruned', path,
                           exc_info=True)
            return None
        out = []
        for rg in range(meta.num_row_groups):
            row_group = meta.row_group(rg)
            cols = {}
            ranges = {}
            for ci in range(row_group.num_columns):
                col = row_group.column(ci)
                name = col.path_in_schema.split('.')[0]
                # chunk byte range for the readahead plane: the chunk
                # starts at its first page (the dictionary page when one
                # exists, else the first data page) and spans its total
                # compressed size
                starts = [offset for offset
                          in (col.dictionary_page_offset,
                              col.data_page_offset)
                          if offset is not None]
                if starts and col.total_compressed_size:
                    ranges.setdefault(name, []).append(
                        (int(min(starts)),
                         int(col.total_compressed_size)))
                st = col.statistics
                if st is None or not st.has_min_max:
                    continue
                null_count = (int(st.null_count) if st.has_null_count
                              else None)
                cols[name] = (st.min, st.max, null_count)
            out.append((cols, int(row_group.num_rows), ranges))
        return out


# -- the prover --------------------------------------------------------------


class _Ctx:
    """One row-group's evidence: hive partition values (exact) and
    footer column statistics (min/max over the NON-null values +
    null_count; None when the footer was unreadable). ``missing`` is set
    by any term that wanted statistics and found none — the
    ``no-statistics`` decline evidence."""

    __slots__ = ('partition_values', 'stats', 'missing', '_schema')

    def __init__(self, piece, stats, stored_schema):
        self.partition_values = piece.partition_values
        self.stats = stats
        self.missing = False
        self._schema = stored_schema

    def typed(self, col):
        from petastorm_tpu.arrow_worker import typed_partition_value
        field = (self._schema.fields.get(col)
                 if self._schema is not None else None)
        return typed_partition_value(field, self.partition_values.get(col))

    def column_stats(self, col):
        if self.stats is None:
            self.missing = True
            return None
        st = self.stats.get(col)
        if st is None:
            self.missing = True
        return st


def _may_have_nulls(null_count):
    return null_count is None or null_count > 0


def _negative_op_unprovable(lo, hi, null_count):
    """True when a ``!=``/``not in`` term cannot be proven empty from
    these statistics. Two NaN-shaped holes make the negative ops
    special: (a) a NULL cell decodes to NaN in numeric columns, and (b)
    a STORED float NaN is excluded from pyarrow's min/max statistics
    without counting as a null — and ``NaN != value`` / ``NaN not in
    values`` are TRUE at worker evaluation. So the negative ops demand a
    provably null-free row-group AND non-float statistics (float stats
    can never prove the absence of a stored NaN cell)."""
    return (_may_have_nulls(null_count)
            or isinstance(lo, float) or isinstance(hi, float))


def _term_provably_empty(term, ctx):
    """True when NO row of the row-group can satisfy one DNF term.

    Null handling is op-specific because null CELLS are not uniform at
    worker evaluation time: object/string columns decode nulls to None
    (which ``filters._eval_term`` rejects under every op), but NUMERIC
    columns decode nulls to NaN — and ``NaN != value`` / ``NaN not in
    values`` are TRUE in both the scalar and the vectorized worker
    paths. So the equality/range/``in`` ops (where a None or NaN cell
    can never compare true) prune on the non-null min/max alone, while
    the negative ops ``!=``/``not in`` additionally require a provably
    NaN-free row-group (:func:`_negative_op_unprovable`: null-free AND
    non-float statistics, since a stored float NaN is invisible to
    min/max without counting as a null) — without that guard a
    ``[5, null, 5]`` group would be pruned against ``!= 5`` while the
    oracle delivers its NaN row (silent row loss; regression-tested).
    Anything incomparable keeps the row-group.
    """
    col, op, value = term
    if col in ctx.partition_values:
        try:
            return not _filters._eval_term(op, ctx.typed(col), value)
        except TypeError:
            return False  # incomparable: the worker's exact eval decides
    st = ctx.column_stats(col)
    if st is None:
        return False
    lo, hi, null_count = st
    try:
        if op in ('=', '=='):
            return not bool(lo <= value <= hi)
        if op == '!=':
            return bool(lo == hi == value) \
                and not _negative_op_unprovable(lo, hi, null_count)
        if op == '<':
            return not bool(lo < value)
        if op == '>':
            return not bool(hi > value)
        if op == '<=':
            return not bool(lo <= value)
        if op == '>=':
            return not bool(hi >= value)
        if op == 'in':
            # None members skipped: a None VALUE matches neither a None
            # nor a NaN cell under `in` (equality compares false)
            return not any(v is not None and bool(lo <= v <= hi)
                           for v in value)
        if op == 'not in':
            return (bool(lo == hi) and lo in set(value)
                    and not _negative_op_unprovable(lo, hi, null_count))
    except TypeError:
        return False  # e.g. str filter against int statistics
    return False


def _compile_clauses(clauses):
    """Prover for DNF clauses: the row-group is empty iff EVERY
    OR-clause is empty, and an AND-clause is empty iff ANY of its terms
    provably matches nothing."""
    fields = {t[0] for clause in clauses for t in clause}

    def prove(ctx):
        return all(any(_term_provably_empty(t, ctx) for t in clause)
                   for clause in clauses)

    return prove, fields


def _compile_in_set(field, values):
    """Prover for :class:`~petastorm_tpu.predicates.in_set` — the
    null-safety satellite lives here: ``in_set`` is plain membership, so
    ``None`` in the value set MATCHES null rows, and a row-group whose
    column may hold nulls is then never prunable by min/max alone."""
    matches_null = any(v is None for v in values)

    def prove(ctx):
        if field in ctx.partition_values:
            try:
                return ctx.typed(field) not in values
            except TypeError:
                return False
        st = ctx.column_stats(field)
        if st is None:
            return False
        lo, hi, null_count = st
        if matches_null and _may_have_nulls(null_count):
            return False
        try:
            return not any(v is not None and bool(lo <= v <= hi)
                           for v in values)
        except TypeError:
            return False

    return prove, {field}


def _compile(predicate):
    """Predicate tree → ``(prove_empty(ctx), fields)`` or None when the
    tree holds no component the statistics prover understands."""
    if isinstance(predicate, _filters.FiltersPredicate):
        return _compile_clauses(predicate.clauses)
    if isinstance(predicate, in_set):
        return _compile_in_set(predicate.field, predicate.values)
    if isinstance(predicate, in_reduce):
        children = [_compile(p) for p in predicate.predicates]
        if predicate.reduce_func is all:
            # AND: empty when ANY prunable child proves it empty;
            # arbitrary children simply cannot contribute evidence
            usable = [c for c in children if c is not None]
            if not usable:
                return None

            def prove_all(ctx):
                return any(prove(ctx) for prove, _ in usable)

            return prove_all, set().union(*(f for _, f in usable))
        if predicate.reduce_func is any:
            # OR: empty only when EVERY child is prunable and empty
            if not children or any(c is None for c in children):
                return None

            def prove_any(ctx):
                return all(prove(ctx) for prove, _ in children)

            return prove_any, set().union(*(f for _, f in children))
    return None


# -- the planner -------------------------------------------------------------


class PushdownPlan:
    """One Reader construction's pruning decision: ``kept``/``pruned``
    are piece indices (``pruned`` PROVABLY deliver zero rows),
    ``rows_pruned`` the skipped row count from the footers, ``decline``
    the reason nothing could be pruned (None when pruning ran)."""

    __slots__ = ('kept', 'pruned', 'rows_pruned', 'considered',
                 'no_stats_rowgroups', 'decline')

    def __init__(self, kept, pruned, rows_pruned, considered,
                 no_stats_rowgroups, decline):
        self.kept = kept
        self.pruned = pruned
        self.rows_pruned = rows_pruned
        self.considered = considered
        self.no_stats_rowgroups = no_stats_rowgroups
        self.decline = decline


def plan_rowgroup_pruning(dataset_info, pieces, piece_indices,
                          predicate=None, clauses=None, stored_schema=None):
    """Prove row-groups empty against a predicate before any of them is
    ventilated. Pass either a predicate tree (``predicate=``) or
    already-normalized DNF ``clauses`` (the ``filters=`` kwarg path).
    Conservative everywhere: only PROVABLY empty row-groups land in
    ``plan.pruned``; callers treat them as completed-with-zero-rows.
    """
    piece_indices = list(piece_indices)
    considered = len(piece_indices)
    if clauses is not None:
        compiled = _compile_clauses(clauses)
    else:
        compiled = _compile(predicate)
    if compiled is None:
        _note_run(considered, declines={DECLINE_ARBITRARY: 1})
        return PushdownPlan(kept=piece_indices, pruned=[], rows_pruned=0,
                            considered=considered, no_stats_rowgroups=0,
                            decline=DECLINE_ARBITRARY)
    prove, fields = compiled

    index = StatsIndex(dataset_info)
    stat_paths = {pieces[i].path for i in piece_indices
                  if any(f not in pieces[i].partition_values
                         for f in fields)}
    index.prefetch(stat_paths)

    kept, pruned = [], []
    rows_pruned = 0
    no_stats = 0
    for i in piece_indices:
        piece = pieces[i]
        entry = index.get(piece.path, piece.row_group)
        cols, num_rows = entry if entry is not None else (None, 0)
        ctx = _Ctx(piece, cols, stored_schema)
        if prove(ctx):
            pruned.append(i)
            rows_pruned += num_rows
        else:
            kept.append(i)
            if ctx.missing:
                no_stats += 1

    declines = {}
    if no_stats:
        declines[DECLINE_NO_STATS] = no_stats
    if not pruned and not no_stats:
        # statistics were usable everywhere and still proved nothing
        # empty: the predicate matches every row-group's range — the
        # runbook's "low selectivity at row-group granularity" case
        declines[DECLINE_LOW_SELECTIVITY] = 1
    _note_run(considered, pruned=len(pruned), rows=rows_pruned,
              declines=declines)
    if pruned and not metrics_disabled():
        registry = get_registry()
        registry.counter(ROWGROUPS_PRUNED).inc(len(pruned))
        if rows_pruned:
            registry.counter(ROWS_PRUNED).inc(rows_pruned)
    if pruned:
        logger.debug('pushdown: pruned %d/%d row-group(s) (%d rows) '
                     'against the predicate', len(pruned), considered,
                     rows_pruned)
    return PushdownPlan(kept=kept, pruned=pruned, rows_pruned=rows_pruned,
                        considered=considered, no_stats_rowgroups=no_stats,
                        decline=None)


__all__ = ['PushdownPlan', 'StatsIndex', 'plan_rowgroup_pruning',
           'planner_summary', 'pushdown_enabled', 'reset_for_tests']
