"""PyTorch bridge: petastorm_tpu readers → torch tensor batches.

Re-design of ``petastorm/pytorch.py``. The torch-specific parts keep parity
— dtype sanitization (``pytorch.py:41-71``), Decimal-tolerant collation
(``:74-101``), a row ``DataLoader`` and a faster ``BatchedDataLoader`` with
optional in-memory epoch replay (``:259-407``) — but both loaders sit on the
framework's shared column-major shuffling buffers (:mod:`petastorm_tpu.buffers`)
and convert numpy → torch zero-copy at the boundary, instead of maintaining a
separate torch-tensor buffer implementation.
"""

import collections.abc
import decimal

import numpy as np
import torch

from petastorm_tpu.buffers import (
    BatchedNoopShufflingBuffer, BatchedRandomShufflingBuffer,
    NoopShufflingBuffer, RandomShufflingBuffer,
)

from petastorm_tpu.ragged import (
    RAGGED_MESSAGE as _RAGGED_MESSAGE,
    STRING_MESSAGE as _STRING_MESSAGE,
    reject_object_column as _reject_object_column,
)

# numpy dtypes torch cannot hold → nearest widening torch-compatible dtype
# (reference: ``pytorch.py:41-71``).
_TORCH_PROMOTIONS = {
    np.dtype(np.uint16): np.int32,
    np.dtype(np.uint32): np.int64,
    np.dtype(np.uint64): np.int64,
}


def _sanitize_pytorch_types(row_as_dict):
    """In-place dtype promotion for values torch rejects; None and strings
    raise (the reference's contract, ``pytorch.py:65-71``)."""
    for name, value in row_as_dict.items():
        if value is None:
            raise TypeError('Field %r is None: nullable fields must be '
                            'filled or filtered before torch collation' % name)
        if isinstance(value, np.ndarray):
            if value.dtype in _TORCH_PROMOTIONS:
                row_as_dict[name] = value.astype(_TORCH_PROMOTIONS[value.dtype])
            elif value.dtype.kind in 'US':
                raise TypeError(_STRING_MESSAGE % name)
        elif isinstance(value, np.generic):
            dt = np.dtype(value.dtype)
            if dt in _TORCH_PROMOTIONS:
                row_as_dict[name] = np.asarray(
                    value, dtype=_TORCH_PROMOTIONS[dt])
            elif dt.kind in 'US':
                raise TypeError(_STRING_MESSAGE % name)
        elif isinstance(value, str):
            raise TypeError(_STRING_MESSAGE % name)


def decimal_friendly_collate(batch):
    """``torch.utils.data.default_collate`` that passes Decimals through as
    lists (reference: ``pytorch.py:74-101``)."""
    if isinstance(batch[0], decimal.Decimal):
        return list(batch)
    if isinstance(batch[0], collections.abc.Mapping):
        out = {}
        for key in batch[0]:
            values = [d[key] for d in batch]
            if (isinstance(values[0], np.ndarray)
                    and len({v.shape for v in values
                             if isinstance(v, np.ndarray)}) > 1):
                # pre-empt default_collate's opaque 'stack expects each
                # tensor to be equal size' with the field name + remedies
                raise TypeError(_RAGGED_MESSAGE % key)
            out[key] = decimal_friendly_collate(values)
        return out
    if isinstance(batch[0], tuple) and hasattr(batch[0], '_fields'):
        return type(batch[0])(*(decimal_friendly_collate(samples)
                                for samples in zip(*batch)))
    if isinstance(batch[0], collections.abc.Sequence) and \
            not isinstance(batch[0], (str, bytes)):
        return [decimal_friendly_collate(samples)
                for samples in zip(*batch)]
    return torch.utils.data.default_collate(batch)


class LoaderBase:
    """Iteration state machine shared by both loaders: a loader is an
    iterable that restarts its reader on re-iteration (reference:
    ``pytorch.py:104-129``)."""

    def __init__(self, reader):
        self.reader = reader
        self._in_iter = None

    def __iter__(self):
        if self._in_iter is not None and self._in_iter:
            raise RuntimeError('Loader is already being iterated')
        if self._in_iter is not None:
            self._on_reiterate()
        self._in_iter = True
        try:
            yield from self._iter_impl()
        finally:
            self._in_iter = False

    def _on_reiterate(self):
        self.reader.reset()

    def __len__(self):
        raise TypeError('Loader length is data-dependent and unknown')

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.reader.stop()
        self.reader.join()

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()


class DataLoader(LoaderBase):
    """Row-at-a-time loader: rows from ``make_reader`` → collated batches.

    :param reader: a row reader (``make_reader``).
    :param batch_size: rows per emitted batch.
    :param collate_fn: batch-of-dicts → tensors
        (default :func:`decimal_friendly_collate`).
    :param shuffling_queue_capacity: >0 enables a row-level
        :class:`RandomShufflingBuffer` of that capacity.
    """

    def __init__(self, reader, batch_size=1,
                 collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, seed=None):
        super().__init__(reader)
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed
        self._epoch = 0

    def _make_buffer(self):
        if self.shuffling_queue_capacity > 0:
            # seed offset by epoch: a constant seed would replay the same
            # "random" order every epoch
            seed = None if self._seed is None else self._seed + self._epoch
            return RandomShufflingBuffer(
                self.shuffling_queue_capacity,
                min_after_retrieve=self.shuffling_queue_capacity // 2,
                seed=seed)
        return NoopShufflingBuffer()

    def _iter_impl(self):
        buf = self._make_buffer()
        self._epoch += 1
        acc = []
        for row in self.reader:
            row_dict = row._asdict()
            _sanitize_pytorch_types(row_dict)
            buf.add_many([row_dict])
            while buf.can_retrieve:
                acc.append(buf.retrieve())
                if len(acc) == self.batch_size:
                    yield self.collate_fn(acc)
                    acc = []
        buf.finish()
        while buf.can_retrieve:
            acc.append(buf.retrieve())
            if len(acc) == self.batch_size:
                yield self.collate_fn(acc)
                acc = []
        if acc:
            yield self.collate_fn(acc)


class BatchedDataLoader(LoaderBase):
    """Column-batch loader: ``make_batch_reader`` row-groups → fixed-size
    torch batches with no per-row python work (reference qualitative claim:
    'significantly higher throughput', ``README.rst:240``).

    :param transform_fn: ``{name: np.ndarray} → {name: tensor}`` applied per
        emitted batch (default: zero-copy ``torch.as_tensor`` per column).
    :param inmemory_cache_all: buffer the whole first epoch in RAM and replay
        it (reshuffled per epoch when shuffling is on) for later epochs —
        the reader is read exactly once (reference: ``pytorch.py:344-407``).
    """

    def __init__(self, reader, batch_size=1, transform_fn=None,
                 shuffling_queue_capacity=0, seed=None,
                 inmemory_cache_all=False, keep_fields=None):
        super().__init__(reader)
        if inmemory_cache_all and getattr(reader, 'num_epochs', None) != 1:
            # A multi-epoch (or infinite) reader would fill the cache with
            # duplicated rows — epoch replay comes from RAM, not the reader
            # (reference guard: ``pytorch.py:344-353``). Fails CLOSED: a
            # reader that doesn't declare num_epochs is treated as unknown
            # and rejected.
            raise ValueError('inmemory_cache_all requires a reader with '
                             'num_epochs=1; further epochs replay from RAM')
        self.batch_size = batch_size
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed
        self._inmemory_cache_all = inmemory_cache_all
        self._cache = [] if inmemory_cache_all else None
        self._cache_complete = False
        self._keep_fields = keep_fields
        self._epoch = 0
        self.transform_fn = transform_fn or self._default_transform

    def _on_reiterate(self):
        # Replay epochs come from the RAM cache; only touch the reader while
        # it is still the data source.
        if not self._cache_complete:
            self.reader.reset()

    @staticmethod
    def _default_transform(columns):
        return {name: torch.as_tensor(arr) for name, arr in columns.items()}

    def _make_buffer(self, epoch):
        seed = None if self._seed is None else self._seed + epoch
        if self.shuffling_queue_capacity > 0:
            return BatchedRandomShufflingBuffer(
                self.shuffling_queue_capacity,
                min_after_retrieve=self.shuffling_queue_capacity // 2,
                batch_size=self.batch_size,
                extra_capacity=self.shuffling_queue_capacity, seed=seed)
        return BatchedNoopShufflingBuffer(self.batch_size)

    def _column_chunks(self):
        """Chunks from the reader (first epoch) or the RAM cache (replay).

        Cached arrays are defensively copied in both directions: the default
        transform is zero-copy ``torch.as_tensor``, so without copies an
        in-place tensor op (``batch['x'] -= mean``) would silently rewrite
        the RAM cache and corrupt every later epoch.
        """
        if self._cache_complete:
            for chunk in self._cache:
                yield {k: v.copy() for k, v in chunk.items()}
            return
        for batch in self.reader:
            columns = batch._asdict()
            if self._keep_fields is not None:
                keep = set(self._keep_fields)
                columns = {k: v for k, v in columns.items() if k in keep}
            for name, arr in columns.items():
                if isinstance(arr, np.ndarray) and arr.dtype in _TORCH_PROMOTIONS:
                    columns[name] = arr.astype(_TORCH_PROMOTIONS[arr.dtype])
                elif isinstance(arr, np.ndarray) and arr.dtype.kind == 'O':
                    _reject_object_column(name, arr)
                elif isinstance(arr, np.ndarray) and arr.dtype.kind in 'US':
                    raise TypeError(_STRING_MESSAGE % name)
            if self._cache is not None:
                self._cache.append({k: v.copy() for k, v in columns.items()})
            yield columns
        if self._cache is not None:
            self._cache_complete = True

    def _iter_impl(self):
        if self._cache is not None and not self._cache_complete:
            # A partial cache from an interrupted first epoch would replay
            # duplicated rows; every reader-fed pass rebuilds it from scratch.
            self._cache = []
        buf = self._make_buffer(self._epoch)
        for columns in self._column_chunks():
            buf.add_many(columns)
            while buf.can_retrieve:
                yield self.transform_fn(buf.retrieve())
        buf.finish()
        while buf.can_retrieve:
            yield self.transform_fn(buf.retrieve())
        self._epoch += 1
