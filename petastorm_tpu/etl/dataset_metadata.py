"""Dataset materialization and footer metadata (read+write).

Re-design of ``petastorm/etl/dataset_metadata.py`` without Spark:

* The writer is pyarrow-based (:class:`DatasetWriter` / :func:`write_dataset`)
  with hive partitioning and bounded row-group sizes; a Spark job can still be
  wrapped with :func:`materialize_dataset` exactly like the reference
  (``dataset_metadata.py:52``) — the context manager only owns the metadata
  footer, not the data write.
* The schema is stored as **versioned JSON** under ``petastorm_tpu.unischema.v1``
  (the reference pickles it, ``dataset_metadata.py:194-205``). Legacy pickled
  schemas written by the reference are still readable
  (:mod:`petastorm_tpu.etl.legacy`).
* Row-group discovery keeps the reference's 3-way fallback
  (``dataset_metadata.py:244-296``): footer key → ``_metadata`` summary →
  per-file footer scan.
"""

import collections
import itertools
import json
import logging
import posixpath
import threading
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from urllib.parse import quote, unquote

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.telemetry import span
from petastorm_tpu.unischema import Unischema, dict_to_encoded_row

logger = logging.getLogger(__name__)

# Versioned JSON footer keys written by this framework.
UNISCHEMA_KEY = b'petastorm_tpu.unischema.v1'
ROW_GROUPS_PER_FILE_KEY = b'petastorm_tpu.num_row_groups_per_file.v1'

# Keys written by the reference implementation (read-compat only;
# ``petastorm/etl/dataset_metadata.py:34-35``).
LEGACY_UNISCHEMA_KEY = b'dataset-toolkit.unischema.v1'
LEGACY_ROW_GROUPS_PER_FILE_KEY = b'dataset-toolkit.num_row_groups_per_file.v1'

_SUMMARY_FILES = ('_metadata', '_common_metadata')
# Row-group size bound used by the Spark converter (reference default:
# ``spark_dataset_converter.py:43``); pass to DatasetWriter(rowgroup_size_mb=...).
DEFAULT_ROW_GROUP_SIZE_MB = 32


class RowGroupPiece:
    """One unit of ventilated work: a single row-group of a single file."""

    __slots__ = ('path', 'row_group', 'partition_values')

    def __init__(self, path, row_group, partition_values=None):
        self.path = path
        self.row_group = row_group
        self.partition_values = partition_values or {}

    def __repr__(self):
        return 'RowGroupPiece(%r, rg=%d)' % (self.path, self.row_group)

    def __eq__(self, other):
        return (isinstance(other, RowGroupPiece)
                and (self.path, self.row_group) == (other.path, other.row_group))

    def __hash__(self):
        return hash((self.path, self.row_group))


def _parse_hive_partitions(relpath):
    """Extract ``{key: value}`` from hive-style ``key=value`` directories.

    Values are URL-unquoted, symmetric with the writer's escaping (and with
    Spark/Hive, which percent-encode special characters in partition values).
    """
    parts = {}
    for segment in relpath.split('/')[:-1]:
        if '=' in segment:
            key, _, value = segment.partition('=')
            parts[key] = unquote(value)
    return parts


class ParquetDatasetInfo:
    """Resolved view of a parquet dataset directory on any fsspec filesystem.

    Replaces the reference's use of the (long-removed) legacy
    ``pq.ParquetDataset`` pieces API with an explicit file inventory +
    hive-partition parse. Paths are stored fs-relative (no scheme).
    """

    def __init__(self, dataset_url_or_urls, storage_options=None, validate=True,
                 filesystem=None):
        self.url = dataset_url_or_urls
        fs, path_or_paths = get_filesystem_and_path_or_paths(
            dataset_url_or_urls, storage_options, filesystem=filesystem)
        self.fs = fs
        if isinstance(path_or_paths, list):
            self.root_path = posixpath.dirname(path_or_paths[0])
            self.file_paths = sorted(path_or_paths)
        else:
            self.root_path = path_or_paths
            self.file_paths = self._discover_files(fs, path_or_paths)
        if validate and not self.file_paths:
            raise MetadataError('No parquet files found under %r' % (dataset_url_or_urls,))
        self._common_metadata = _UNSET
        self._metadata = _UNSET
        self._schema = None
        self._lock = threading.Lock()

    def __getstate__(self):
        # Ships across the process-pool spawn boundary: drop the lock and the
        # cached pyarrow metadata objects (re-read lazily in the worker).
        state = self.__dict__.copy()
        del state['_lock']
        state['_common_metadata'] = _UNSET
        state['_metadata'] = _UNSET
        state['_schema'] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        # Pickle does not preserve identity of the module-level _UNSET
        # sentinel, so the unpickled values would fail the `is _UNSET`
        # checks and the lazy properties would return a meaningless _Unset
        # instance. Re-point them at this process's sentinel.
        self._common_metadata = _UNSET
        self._metadata = _UNSET

    @staticmethod
    def _discover_files(fs, root):
        if fs.isfile(root):
            return [root]
        # A committed manifest (written by petastorm_tpu.write) is the
        # dataset truth: its file list is a single atomic snapshot, so a
        # reader racing a concurrent writer/compaction never sees a torn
        # mix of old and new part files the directory walk would.
        from petastorm_tpu.write import manifest as write_manifest
        try:
            committed = write_manifest.load(fs, root.rstrip('/'))
        except write_manifest.ManifestError as e:
            logger.warning('Ignoring unreadable dataset manifest: %s', e)
            committed = None
        if committed is not None:
            return sorted(write_manifest.committed_paths(
                committed, root.rstrip('/')))
        files = []
        root_norm = root.rstrip('/')
        for path in fs.find(root):
            rel = posixpath.relpath(path, root_norm)
            # Skip hidden/metadata entries anywhere in the relative path, so
            # e.g. Spark's _temporary/.../part-*.parquet never counts as data.
            segments = rel.split('/')
            if any(seg.startswith(('.', '_')) for seg in segments):
                continue
            if segments[-1].endswith('.crc'):
                continue
            files.append(path)
        return sorted(files)

    # -- footers ------------------------------------------------------------

    def _read_summary(self, name):
        path = posixpath.join(self.root_path, name)
        try:
            if not self.fs.exists(path):
                return None
        except (OSError, ValueError):
            return None
        with self.fs.open(path, 'rb') as f:
            return pq.read_metadata(f)

    @property
    def common_metadata(self):
        with self._lock:
            if self._common_metadata is _UNSET:
                self._common_metadata = self._read_summary('_common_metadata')
            return self._common_metadata

    @property
    def summary_metadata(self):
        with self._lock:
            if self._metadata is _UNSET:
                self._metadata = self._read_summary('_metadata')
            return self._metadata

    @property
    def arrow_schema(self):
        """Physical arrow schema (from the first data file's footer)."""
        if self._schema is None:
            with self.fs.open(self.file_paths[0], 'rb') as f:
                self._schema = pq.read_schema(f)
        return self._schema

    def relpath(self, path):
        rel = posixpath.relpath(path, self.root_path)
        return rel

    def partition_values_for(self, path):
        return _parse_hive_partitions(self.relpath(path))

    @property
    def partition_keys(self):
        keys = []
        for path in self.file_paths:
            for k in self.partition_values_for(path):
                if k not in keys:
                    keys.append(k)
        return keys

    def open(self, path):
        return self.fs.open(path, 'rb')


class _Unset:
    pass


_UNSET = _Unset()


# ---------------------------------------------------------------------------
# Row-group discovery (read side)
# ---------------------------------------------------------------------------

def count_rows(dataset_info_or_url, storage_options=None,
               footer_scan_workers=8):
    """Total row count of a dataset from parquet FOOTERS only — one
    metadata read per file, no data pages touched.

    The reference's converter carries an explicit ``dataset_size`` it got
    from Spark (``spark_dataset_converter.py:646-706``); for an existing
    store this answers the same "``len(dataset)``" question directly.
    """
    info = (dataset_info_or_url
            if isinstance(dataset_info_or_url, ParquetDatasetInfo)
            else ParquetDatasetInfo(dataset_info_or_url, storage_options))
    # summary-first, like load_row_groups' 3-way fallback: one already
    # -cached read answers it on stores with a _metadata file
    summary = info.summary_metadata
    if summary is not None and summary.num_rows:
        return summary.num_rows
    if not info.file_paths:
        return 0

    def rows_in(path):
        with info.open(path) as f:
            return pq.read_metadata(f).num_rows

    with ThreadPoolExecutor(max_workers=min(footer_scan_workers,
                                            len(info.file_paths))) as pool:
        return sum(pool.map(rows_in, info.file_paths))


def load_row_groups(dataset_info, footer_scan_workers=8):
    """Enumerate all row-groups of a dataset as :class:`RowGroupPiece` list.

    3-way fallback, mirroring ``petastorm/etl/dataset_metadata.py:244-353``:
    footer metadata key (ours or the reference's) → ``_metadata`` summary file
    → parallel footer scan of every data file. Piece order is sorted by path
    then row-group index so epochs are reproducible.
    """
    counts = _row_group_counts_from_common_metadata(dataset_info)
    if counts is None:
        counts = _row_group_counts_from_summary(dataset_info)
    if counts is None:
        counts = _row_group_counts_from_footers(dataset_info, footer_scan_workers)

    pieces = []
    for path in dataset_info.file_paths:
        rel = dataset_info.relpath(path)
        if rel not in counts:
            raise MetadataError('No row-group count recorded for file %r' % rel)
        partitions = dataset_info.partition_values_for(path)
        for rg in range(counts[rel]):
            pieces.append(RowGroupPiece(path, rg, partitions))
    return pieces


def _row_group_counts_from_common_metadata(dataset_info):
    cm = dataset_info.common_metadata
    if cm is None or cm.metadata is None:
        return None
    meta = cm.metadata
    raw = meta.get(ROW_GROUPS_PER_FILE_KEY) or meta.get(LEGACY_ROW_GROUPS_PER_FILE_KEY)
    if raw is None:
        return None
    return {k: int(v) for k, v in json.loads(raw.decode('utf-8')).items()}


def _row_group_counts_from_summary(dataset_info):
    summary = dataset_info.summary_metadata
    if summary is None or summary.num_row_groups == 0:
        return None
    counts = {}
    for i in range(summary.num_row_groups):
        file_path = summary.row_group(i).column(0).file_path
        if not file_path:
            return None
        counts[file_path] = counts.get(file_path, 0) + 1
    return counts


def _row_group_counts_from_footers(dataset_info, workers):
    def count(path):
        with dataset_info.open(path) as f:
            return dataset_info.relpath(path), pq.read_metadata(f).num_row_groups

    with ThreadPoolExecutor(max_workers=workers) as pool:
        return dict(pool.map(count, dataset_info.file_paths))


# ---------------------------------------------------------------------------
# Schema recovery
# ---------------------------------------------------------------------------

def get_schema(dataset_info):
    """Load the Unischema stored in the dataset footer.

    Reads our JSON format first, then falls back to depickling a
    reference-written schema (``dataset_metadata.py:356-385``).
    """
    cm = dataset_info.common_metadata
    if cm is None or cm.metadata is None:
        raise MetadataError(
            'Could not find _common_metadata file for %r. Use materialize_dataset '
            'or the petastorm-tpu-generate-metadata CLI to add petastorm metadata '
            'to an existing dataset.' % dataset_info.url)
    meta = cm.metadata
    if UNISCHEMA_KEY in meta:
        return Unischema.from_json_dict(json.loads(meta[UNISCHEMA_KEY].decode('utf-8')))
    if LEGACY_UNISCHEMA_KEY in meta:
        from petastorm_tpu.etl.legacy import depickle_legacy_unischema
        return depickle_legacy_unischema(meta[LEGACY_UNISCHEMA_KEY])
    raise MetadataError('_common_metadata of %r carries no unischema entry'
                        % dataset_info.url)


def get_schema_from_dataset_url(dataset_url_or_urls, storage_options=None):
    """Unischema of the dataset at a URL (``dataset_metadata.py:388-407``)."""
    return get_schema(ParquetDatasetInfo(dataset_url_or_urls, storage_options))


def infer_or_load_unischema(dataset_info):
    """Stored Unischema if present, else inferred from the parquet schema.

    Reference: ``dataset_metadata.py:410-417``.
    """
    try:
        return get_schema(dataset_info)
    except MetadataError:
        logger.info('Dataset %s has no petastorm metadata; inferring schema from '
                    'the parquet footer', dataset_info.url)
        # one pass over the paths serves both the key list (dict order =
        # first-seen order, same as partition_keys) and the type inference
        partition_types = _infer_partition_types(dataset_info)
        return Unischema.from_arrow_schema(
            dataset_info.arrow_schema,
            partition_columns=list(partition_types),
            partition_types=partition_types)


def _infer_partition_types(dataset_info):
    """Numpy dtype per hive partition key, inferred from observed values.

    Hive paths carry values as strings; like Spark's partition discovery,
    all-integer values become int64 and all-float values float64, so typed
    data (and predicates/filters over it) round-trip instead of degrading
    to path strings.
    """
    observed = {}
    for path in dataset_info.file_paths:
        for key, value in dataset_info.partition_values_for(path).items():
            observed.setdefault(key, set()).add(value)

    def dtype_of(values):
        # cast with the TARGET numpy dtype so inference can never promise a
        # type the read path's conversion would then overflow on
        for dtype in (np.int64, np.float64):
            try:
                for v in values:
                    dtype(v)
                return dtype
            except (TypeError, ValueError, OverflowError):
                continue
        return np.str_

    return {key: dtype_of(values) for key, values in observed.items()}


# ---------------------------------------------------------------------------
# Footer metadata write
# ---------------------------------------------------------------------------

def add_to_dataset_metadata(dataset_info, key, value):
    """Merge one ``key: value`` entry into the dataset's ``_common_metadata``.

    Equivalent of ``petastorm/utils.py:88-132`` on the modern pyarrow API.
    """
    update_dataset_metadata(dataset_info, {key: value})


def update_dataset_metadata(dataset_info, entries):
    """Merge ``entries`` (a dict) into ``_common_metadata`` in ONE write.

    Existing entries are preserved; the base schema comes from the existing
    summary file or the first data file's footer. A single read-modify-write
    cycle regardless of how many keys are stamped, so readers racing a
    writer never observe a partially-stamped footer and remote filesystems
    pay one round trip.
    """
    cm = dataset_info.common_metadata
    if cm is not None:
        base_schema = cm.schema.to_arrow_schema()
        existing = dict(cm.metadata or {})
    else:
        base_schema = dataset_info.arrow_schema
        existing = dict(base_schema.metadata or {})
    for key, value in entries.items():
        existing[key if isinstance(key, bytes) else key.encode()] = (
            value if isinstance(value, bytes) else value.encode())
    schema = base_schema.with_metadata(existing)
    path = posixpath.join(dataset_info.root_path, '_common_metadata')
    with dataset_info.fs.open(path, 'wb') as f:
        pq.write_metadata(schema, f)
    # Drop any stale checksum left by other writers (``utils.py:125-132``).
    crc = posixpath.join(dataset_info.root_path, '._common_metadata.crc')
    try:
        if dataset_info.fs.exists(crc):
            dataset_info.fs.rm(crc)
    except (OSError, ValueError):
        pass
    # Invalidate the cached footer.
    dataset_info._common_metadata = _UNSET


def _write_dataset_footer(dataset_url, schema, storage_options=None):
    info = ParquetDatasetInfo(dataset_url, storage_options)
    counts_json = json.dumps(
        _row_group_counts_from_footers(info, workers=8)).encode('utf-8')
    entries = {
        ROW_GROUPS_PER_FILE_KEY: counts_json,
        UNISCHEMA_KEY: json.dumps(schema.to_json_dict()).encode('utf-8'),
    }
    # Best-effort write-side interop: also stamp the reference's pickled
    # schema key (+ its row-group count key) so a genuine petastorm install
    # can open datasets written by this framework. Codecs with no reference
    # equivalent (none today) would make this a JSON-only dataset.
    try:
        from petastorm_tpu.etl.legacy import pickle_unischema_for_reference
        entries[LEGACY_UNISCHEMA_KEY] = pickle_unischema_for_reference(schema)
        entries[LEGACY_ROW_GROUPS_PER_FILE_KEY] = counts_json
    except MetadataError as e:
        logger.debug('Not writing reference-compatible schema pickle: %s', e)
    update_dataset_metadata(info, entries)


@contextmanager
def materialize_dataset(dataset_url, schema, row_group_size_mb=None,
                        storage_options=None, spark=None):
    """Context manager that adds petastorm_tpu metadata after a dataset write.

    Drop-in analogue of the reference context manager
    (``etl/dataset_metadata.py:52-133``): run any parquet-producing job in the
    body (a :class:`DatasetWriter`, a Spark write, ...) and the footer
    (`_common_metadata` with schema JSON + row-group counts) is written on
    exit. ``spark``/``row_group_size_mb`` are accepted for signature
    compatibility; when a SparkSession is passed, the parquet block size conf
    is set for the duration of the body.
    """
    conf_was_set = False
    saved_conf = None
    if spark is not None and row_group_size_mb:
        hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
        saved_conf = hadoop_conf.get('parquet.block.size')
        conf_was_set = True
        hadoop_conf.setInt('parquet.block.size', row_group_size_mb * 1024 * 1024)
    try:
        yield
    finally:
        if conf_was_set:
            hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
            if saved_conf is not None:
                hadoop_conf.set('parquet.block.size', saved_conf)
            else:
                hadoop_conf.unset('parquet.block.size')
    _write_dataset_footer(normalize_dir_url(dataset_url), schema, storage_options)


# ---------------------------------------------------------------------------
# Spark-free writer
# ---------------------------------------------------------------------------

class DatasetWriter:
    """Writes encoded rows into one or more parquet files with hive partitioning.

    This is the pyarrow replacement for the reference's Spark write
    (``rdd.map(dict_to_spark_row).write.parquet``, SURVEY.md §3.3): rows are
    codec-encoded with :func:`dict_to_encoded_row`, buffered, and flushed as
    parquet row-groups of ``rowgroup_size_rows`` rows.
    """

    def __init__(self, dataset_url, schema, rowgroup_size_rows=1000,
                 partition_by=(), file_prefix='part', storage_options=None,
                 rowgroup_size_mb=None, compression='auto',
                 workers_count=None, sort_by=None, filesystem=None):
        """``workers_count``: >1 encodes :meth:`write_row_dicts` batches in
        a thread pool (codec encode — jpeg/png via cv2, ``np.save`` — is
        the write path's CPU cost and releases the GIL), the first-party
        stand-in for the reference's Spark-executor-parallel write
        (``etl/dataset_metadata.py:52``). Row order is preserved.
        ``None``/0/1 encode serially.

        ``sort_by``: name of a column the caller promises to feed in
        non-decreasing order. The promise is stamped into each file's
        footer as parquet sorted-column metadata and (with the footer
        statistics this writer always emits) is what lets pushdown prune
        row-groups on range predicates over that column. Order is the
        caller's contract — the writer does not re-sort."""
        self.schema = schema
        self._compression = compression
        self._workers_count = int(workers_count or 0)
        self._encode_pool = None
        self.rowgroup_size_rows = rowgroup_size_rows
        self.rowgroup_size_bytes = (rowgroup_size_mb * 1024 * 1024
                                    if rowgroup_size_mb else None)
        self.partition_by = tuple(partition_by)
        self.sort_by = sort_by
        if sort_by is not None and sort_by not in {f.name for f in schema}:
            raise ValueError('sort_by column %r is not in the schema' % sort_by)
        self._url = normalize_dir_url(dataset_url)
        self._file_prefix = file_prefix
        self.fs, self.root_path = get_filesystem_and_path_or_paths(
            self._url, storage_options, filesystem=filesystem)
        self.fs.makedirs(self.root_path, exist_ok=True)
        self._arrow_schema = self._storage_schema()
        self._writers = {}
        self._buffers = {}
        self._buffer_bytes = {}
        self._file_seq = 0
        self._files_written = 0
        self._rows_written = 0
        #: paths of every parquet file this writer has CLOSED (fully
        #: written) — the distributed plane renames these into place
        self.paths_written = []

    def _storage_schema(self):
        fields = [pa.field(f.name, f.arrow_storage_type(), nullable=True)
                  for f in self.schema if f.name not in self.partition_by]
        return pa.schema(fields)

    def _resolve_compression(self):
        """``'auto'`` → per-column: NONE for codec-compressed cells (jpeg,
        png, npz are incompressible — snappy would burn CPU on both the
        write and every read for ~0% size win), SNAPPY elsewhere. Any other
        value passes through to pyarrow unchanged."""
        if self._compression != 'auto':
            return self._compression
        from petastorm_tpu.codecs import (
            CompressedImageCodec, CompressedNdarrayCodec,
        )
        per_column = {}
        for f in self.schema:
            if f.name in self.partition_by:
                continue
            # pyarrow matches parquet COLUMN PATHS, not field names: a
            # list-typed column's leaf is '<name>.list.element' and a plain
            # '<name>' key would silently fall to dict-mode's UNCOMPRESSED
            storage = f.arrow_storage_type()
            if pa.types.is_list(storage) or pa.types.is_large_list(storage):
                key = f.name + '.list.element'
            else:
                key = f.name
            incompressible = isinstance(
                f.codec, (CompressedImageCodec, CompressedNdarrayCodec))
            per_column[key] = 'NONE' if incompressible else 'SNAPPY'
        return per_column

    def _partition_dir(self, row):
        segments = []
        for key in self.partition_by:
            if key not in row:
                raise ValueError('Row is missing partition column %r' % key)
            segments.append('%s=%s' % (key, quote(str(row[key]), safe='')))
        return '/'.join(segments)

    def _sorting_columns(self):
        """Parquet sorted-column metadata for the declared sort key, or
        None. Ascending nulls-last: the ordering :func:`dict_to_encoded_row`
        output naturally satisfies when the caller feeds sorted rows."""
        if self.sort_by is None:
            return None
        index = self._arrow_schema.get_field_index(self.sort_by)
        if index < 0:  # sort key is a partition column — not in-file
            return None
        return [pq.SortingColumn(index)]

    def _writer_for(self, part_dir):
        if part_dir not in self._writers:
            directory = posixpath.join(self.root_path, part_dir) if part_dir else self.root_path
            self.fs.makedirs(directory, exist_ok=True)
            path = posixpath.join(directory, '%s-%05d.parquet' % (self._file_prefix, self._file_seq))
            self._file_seq += 1
            sink = self.fs.open(path, 'wb')
            # Footer statistics are ALWAYS on: a dataset written without
            # them reads full-scan-priced — every pushdown plan declines
            # with 'no-statistics' (docs/troubleshoot.md).
            self._writers[part_dir] = (
                pq.ParquetWriter(sink, self._arrow_schema,
                                 compression=self._resolve_compression(),
                                 write_statistics=True,
                                 sorting_columns=self._sorting_columns()),
                sink, path)
            self._buffers[part_dir] = []
        return self._writers[part_dir][0]

    @staticmethod
    def _row_nbytes(encoded):
        total = 0
        for v in encoded.values():
            if isinstance(v, (bytes, bytearray)):
                total += len(v)
            elif isinstance(v, list):
                total += 8 * len(v)
            else:
                total += 8
        return total

    def write_row_dict(self, row_dict):
        with span('encode'):
            encoded = dict_to_encoded_row(self.schema, row_dict)
        self._append_encoded(encoded)

    def _append_encoded(self, encoded):
        part_dir = self._partition_dir(encoded)
        self._writer_for(part_dir)
        buf = self._buffers[part_dir]
        buf.append(encoded)
        if len(buf) >= self.rowgroup_size_rows:
            self._flush(part_dir)
        elif self.rowgroup_size_bytes is not None:
            self._buffer_bytes[part_dir] = (self._buffer_bytes.get(part_dir, 0)
                                            + self._row_nbytes(encoded))
            if self._buffer_bytes[part_dir] >= self.rowgroup_size_bytes:
                self._flush(part_dir)

    def write_row_dicts(self, row_dicts):
        if self._workers_count > 1:
            for encoded in self._encode_parallel(row_dicts):
                self._append_encoded(encoded)
        else:
            for row in row_dicts:
                self.write_row_dict(row)

    def _encode_parallel(self, row_dicts):
        """Encoded rows in input order, encoded ``workers_count``-wide.

        Streaming: ``row_dicts`` may be a generator — at most
        ``workers_count + 2`` chunks of raw rows are in flight, so memory
        stays O(chunks), matching the serial path's streaming contract.
        Chunked so scalar-heavy schemas don't drown in per-task dispatch;
        an encode error (bad shape/dtype) surfaces here exactly as it
        would serially, just possibly a chunk early."""
        if self._encode_pool is None:
            self._encode_pool = ThreadPoolExecutor(
                max_workers=self._workers_count,
                thread_name_prefix='pt-encode')

        def encode_chunk(part):
            with span('encode'):
                return [dict_to_encoded_row(self.schema, r) for r in part]

        rows_iter = iter(row_dicts)
        pending = collections.deque()
        while True:
            while len(pending) < self._workers_count + 2:
                part = list(itertools.islice(rows_iter, 64))
                if not part:
                    break
                pending.append(self._encode_pool.submit(encode_chunk, part))
            if not pending:
                break
            # FIFO completion keeps input order; .result() re-raises an
            # encode error just as the serial path would
            for encoded in pending.popleft().result():
                yield encoded

    def new_file(self):
        """Close current files; subsequent rows open fresh parquet files."""
        self._close_writers()

    def _flush(self, part_dir):
        rows = self._buffers[part_dir]
        self._buffer_bytes[part_dir] = 0
        if not rows:
            return
        with span('write_flush'):
            columns = {}
            for field in self._arrow_schema:
                values = [r[field.name] for r in rows]
                columns[field.name] = pa.array(values, type=field.type)
            table = pa.table(columns, schema=self._arrow_schema)
            self._writers[part_dir][0].write_table(table)
        self._rows_written += len(rows)
        self._buffers[part_dir] = []

    def _close_writers(self):
        for part_dir in list(self._writers):
            self._flush(part_dir)
            writer, sink, path = self._writers.pop(part_dir)
            writer.close()
            sink.close()
            self._buffers.pop(part_dir, None)
            self._files_written += 1
            self.paths_written.append(path)

    def close(self):
        if self._encode_pool is not None:
            self._encode_pool.shutdown(wait=True)
            self._encode_pool = None
        if self._files_written == 0 and not self._writers and not self.partition_by:
            # Zero-row dataset: still produce one (empty) parquet file so the
            # dataset is a valid, readable store rather than a footer error.
            self._writer_for('')
        self._close_writers()

    def abort(self):
        """Tear down without publishing buffered rows: drop unflushed
        buffers, close the underlying sinks, and delete every file this
        writer opened (including already-closed ones). The exception-path
        counterpart of :meth:`close` — after an abort the directory holds
        no half-written output from this writer."""
        if self._encode_pool is not None:
            self._encode_pool.shutdown(wait=True, cancel_futures=True)
            self._encode_pool = None
        opened = []
        for part_dir in list(self._writers):
            writer, sink, path = self._writers.pop(part_dir)
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                logger.debug('abort: parquet writer close failed for %s', path)
            try:
                sink.close()
            except OSError:
                pass
            opened.append(path)
            self._buffers.pop(part_dir, None)
        for path in opened + self.paths_written:
            try:
                if self.fs.exists(path):
                    self.fs.rm(path)
            except (OSError, ValueError):
                pass
        self.paths_written = []
        self._buffers = {}
        self._buffer_bytes = {}

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        # Success path publishes; an exception path must NOT flush the
        # partial buffers as if the write finished — it aborts, removing
        # this writer's files, so a crashed ETL job can simply rerun.
        if exc_type is None:
            self.close()
        else:
            self.abort()


def write_dataset(dataset_url, schema, rows, rowgroup_size_rows=1000,
                  num_files=1, partition_by=(), storage_options=None,
                  rowgroup_size_mb=None, workers_count=None):
    """One-call materialization: write ``rows`` and the metadata footer."""
    rows = list(rows)
    with materialize_dataset(dataset_url, schema, storage_options=storage_options):
        with DatasetWriter(dataset_url, schema, rowgroup_size_rows,
                           partition_by, storage_options=storage_options,
                           rowgroup_size_mb=rowgroup_size_mb,
                           workers_count=workers_count) as writer:
            if num_files <= 1:
                writer.write_row_dicts(rows)
            else:
                per_file = max(1, (len(rows) + num_files - 1) // num_files)
                for start in range(0, len(rows), per_file):
                    writer.write_row_dicts(rows[start:start + per_file])
                    writer.new_file()
