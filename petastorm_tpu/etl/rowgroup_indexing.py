"""Build and load row-group indexes stored in the dataset footer.

Re-design of ``petastorm/etl/rowgroup_indexing.py:37-158``: instead of a Spark
map-reduce producing a pickled index, the index is built with a local thread
pool over row-groups (each worker decodes only the indexed columns) and stored
as versioned JSON.
"""

import json
import logging
from concurrent.futures import ThreadPoolExecutor

import pyarrow.parquet as pq

from petastorm_tpu.codecs import decode_batch_with_nulls
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import (
    ParquetDatasetInfo, add_to_dataset_metadata, get_schema, load_row_groups,
)
from petastorm_tpu.etl.rowgroup_indexers import indexer_from_json

logger = logging.getLogger(__name__)

ROWGROUPS_INDEX_KEY = b'petastorm_tpu.rowgroups_index.v1'


def build_rowgroup_index(dataset_url, indexers, storage_options=None, workers=8):
    """Scan the dataset once and store the indexes in ``_common_metadata``.

    :param indexers: list of :class:`RowGroupIndexerBase` instances.
    """
    info = ParquetDatasetInfo(dataset_url, storage_options)
    schema = get_schema(info)
    pieces = load_row_groups(info)

    needed_columns = sorted({c for ix in indexers for c in ix.column_names})
    missing = [c for c in needed_columns if c not in schema.fields]
    if missing:
        raise ValueError('Indexed fields not in schema: %s' % missing)

    def decode_piece(piece_and_index):
        piece_index, piece = piece_and_index
        file_columns = [c for c in needed_columns if c not in piece.partition_values]
        with info.open(piece.path) as f:
            table = pq.ParquetFile(f).read_row_group(piece.row_group,
                                                     columns=file_columns)
        columns = {}
        for name in file_columns:
            field = schema.fields[name]
            values = table.column(name).to_pylist()
            if field.codec is not None:
                # Null cells bypass the codec (nullable columns are exactly
                # what FieldNotNullIndexer exists for).
                columns[name] = decode_batch_with_nulls(field, values)
            else:
                columns[name] = values
        n = table.num_rows
        for name in needed_columns:
            if name in piece.partition_values:
                columns[name] = [piece.partition_values[name]] * n
        rows = [{c: columns[c][i] for c in needed_columns} for i in range(n)]
        return piece_index, rows

    with ThreadPoolExecutor(max_workers=workers) as pool:
        for piece_index, rows in pool.map(decode_piece, enumerate(pieces)):
            for indexer in indexers:
                indexer.build_index(rows, piece_index)

    payload = json.dumps({ix.index_name: ix.to_json_dict() for ix in indexers})
    add_to_dataset_metadata(info, ROWGROUPS_INDEX_KEY, payload.encode('utf-8'))
    logger.info('Built %d row-group index(es) over %d row-groups',
                len(indexers), len(pieces))


def get_row_group_indexes(dataset_info):
    """Load ``{index_name: indexer}`` from the footer."""
    cm = dataset_info.common_metadata
    if cm is None or cm.metadata is None or ROWGROUPS_INDEX_KEY not in cm.metadata:
        raise MetadataError('Dataset %r carries no row-group index; run '
                            'build_rowgroup_index first' % dataset_info.url)
    raw = json.loads(cm.metadata[ROWGROUPS_INDEX_KEY].decode('utf-8'))
    return {name: indexer_from_json(d) for name, d in raw.items()}
