"""Built-in row-group indexers (reference: ``petastorm/etl/rowgroup_indexers.py``)."""

from collections import defaultdict

from petastorm_tpu.etl import RowGroupIndexerBase


class SingleFieldIndexer(RowGroupIndexerBase):
    """Maps every observed value of one field to the set of row-group ordinals
    containing it (values are stringified for the JSON footer format)."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._field = index_field
        self._index = defaultdict(set)

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._field]

    @property
    def indexed_values(self):
        return list(self._index.keys())

    def get_row_group_indexes(self, value_key):
        return self._index.get(str(value_key), set())

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            value = row[self._field]
            if value is None:
                continue
            self._index[str(value)].add(piece_index)

    def __add__(self, other):
        if self._field != other._field:
            raise ValueError('Cannot merge indexers of different fields')
        merged = SingleFieldIndexer(self._index_name, self._field)
        for value, groups in self._index.items():
            merged._index[value] |= groups
        for value, groups in other._index.items():
            merged._index[value] |= groups
        return merged

    # -- JSON footer form ---------------------------------------------------

    def to_json_dict(self):
        return {'type': 'SingleFieldIndexer', 'index_name': self._index_name,
                'field': self._field,
                'index': {k: sorted(v) for k, v in self._index.items()}}

    @classmethod
    def from_json_dict(cls, d):
        idx = cls(d['index_name'], d['field'])
        for value, groups in d['index'].items():
            idx._index[value] = set(groups)
        return idx


class FieldNotNullIndexer(RowGroupIndexerBase):
    """Indexes row-groups that contain at least one non-null value of a field
    (reference: ``rowgroup_indexers.py:78``)."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._field = index_field
        self._not_null_groups = set()

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._field]

    @property
    def indexed_values(self):
        return ['false_values_not_indexed']

    def get_row_group_indexes(self, value_key=None):
        return self._not_null_groups

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            if row[self._field] is not None:
                self._not_null_groups.add(piece_index)
                return

    def to_json_dict(self):
        return {'type': 'FieldNotNullIndexer', 'index_name': self._index_name,
                'field': self._field, 'groups': sorted(self._not_null_groups)}

    @classmethod
    def from_json_dict(cls, d):
        idx = cls(d['index_name'], d['field'])
        idx._not_null_groups = set(d['groups'])
        return idx


_INDEXER_TYPES = {
    'SingleFieldIndexer': SingleFieldIndexer,
    'FieldNotNullIndexer': FieldNotNullIndexer,
}


def indexer_from_json(d):
    if d['type'] not in _INDEXER_TYPES:
        raise ValueError('Unknown indexer type %r' % d['type'])
    return _INDEXER_TYPES[d['type']].from_json_dict(d)
