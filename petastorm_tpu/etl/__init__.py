"""ETL: dataset materialization, footer metadata, and row-group indexing.

Reference layer: ``petastorm/etl/`` (SURVEY.md §2.3). The write path here is
Spark-free — pyarrow writes parquet; a Spark adapter can wrap it — and the
footer schema format is versioned JSON instead of a Python pickle.
"""

from abc import ABCMeta, abstractmethod


class RowGroupIndexerBase(metaclass=ABCMeta):
    """Base class for row-group indexers (reference: ``petastorm/etl/__init__.py:21``)."""

    @property
    @abstractmethod
    def index_name(self):
        """Unique name of this index."""

    @property
    @abstractmethod
    def column_names(self):
        """Column names needed to build the index."""

    @property
    @abstractmethod
    def indexed_values(self):
        """All values the index can look up."""

    @abstractmethod
    def get_row_group_indexes(self, value_key):
        """Row-group ids containing ``value_key``."""

    @abstractmethod
    def build_index(self, decoded_rows, piece_index):
        """Consume rows of one row-group and update the index."""
