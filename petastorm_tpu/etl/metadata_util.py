"""Metadata inspector CLI (reference: ``petastorm/etl/metadata_util.py``).

Prints the stored Unischema, per-file row-group counts, and any row-group
indexes of a dataset.

Usage: ``python -m petastorm_tpu.etl.metadata_util file:///path --print-all``
"""

import argparse
import sys


def print_metadata(dataset_url, print_schema=True, print_row_groups=True,
                   print_index=True, storage_options=None, out=None):
    from petastorm_tpu.errors import MetadataError
    from petastorm_tpu.etl.dataset_metadata import (
        ParquetDatasetInfo, infer_or_load_unischema, load_row_groups,
    )
    from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes

    out = out or sys.stdout
    info = ParquetDatasetInfo(dataset_url, storage_options)
    if print_schema:
        schema = infer_or_load_unischema(info)
        print('Unischema: %s' % schema._name, file=out)
        for field in schema:
            print('  %s: %s %s codec=%s nullable=%s'
                  % (field.name, getattr(field.numpy_dtype, '__name__',
                                         field.numpy_dtype),
                     field.shape, type(field.codec).__name__
                     if field.codec else None, field.nullable), file=out)
    if print_row_groups:
        pieces = load_row_groups(info)
        by_file = {}
        for piece in pieces:
            by_file[piece.path] = by_file.get(piece.path, 0) + 1
        print('Row-groups: %d over %d file(s)' % (len(pieces), len(by_file)),
              file=out)
        for path in sorted(by_file):
            print('  %s: %d' % (info.relpath(path), by_file[path]), file=out)
    if print_index:
        try:
            indexes = get_row_group_indexes(info)
        except MetadataError:
            print('Row-group indexes: none', file=out)
        else:
            print('Row-group indexes:', file=out)
            for name, indexer in indexes.items():
                print('  %s: fields=%s values=%d'
                      % (name, sorted(indexer.column_names),
                         len(indexer.indexed_values)), file=out)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--skip-schema', action='store_true')
    parser.add_argument('--skip-row-groups', action='store_true')
    parser.add_argument('--skip-index', action='store_true')
    args = parser.parse_args(argv)
    print_metadata(args.dataset_url,
                   print_schema=not args.skip_schema,
                   print_row_groups=not args.skip_row_groups,
                   print_index=not args.skip_index)
    return 0


if __name__ == '__main__':
    sys.exit(main())
