"""Read schemas pickled into footers by the reference implementation.

The reference stores its ``Unischema`` as a Python pickle under
``dataset-toolkit.unischema.v1`` (``petastorm/etl/dataset_metadata.py:194-205``)
— including pre-rename module paths (``av.experimental.deepdrive.dataset_toolkit``,
``petastorm/etl/legacy.py:22-47``). This module depickles those blobs into
:class:`petastorm_tpu.unischema.Unischema` **without importing petastorm or
pyspark**, using shim classes and a restricted unpickler.

Security: footers are untrusted input. ``find_class`` only resolves an
explicit allowlist (numpy scalars/dtypes, OrderedDict, Decimal) and maps every
petastorm/pyspark class onto inert local shims; anything else raises.
"""

import io
import pickle
from collections import OrderedDict, namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu import codecs as tpu_codecs
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.unischema import Unischema, UnischemaField

_LEGACY_PACKAGES = ('petastorm', 'av.experimental.deepdrive.dataset_toolkit')

# numpy names a pickled schema may legitimately reference: the dtype machinery
# and scalar type classes. Nothing that does I/O or code execution.
_SAFE_NUMPY_NAMES = frozenset([
    'dtype', 'ndarray', '_reconstruct', 'scalar',
    'bool_', 'int8', 'uint8', 'int16', 'uint16', 'int32', 'uint32',
    'int64', 'uint64', 'float16', 'float32', 'float64', 'longdouble',
    'complex64', 'complex128', 'str_', 'bytes_', 'unicode_', 'string_',
    'object_', 'datetime64', 'timedelta64', 'void', 'generic', 'number',
    'integer', 'signedinteger', 'unsignedinteger', 'floating', 'inexact',
    'flexible', 'character', 'intc', 'intp', 'int_', 'uint', 'single', 'double',
])

# The reference's UnischemaField is a NamedTuple with this exact field order
# (``petastorm/unischema.py:50-66``); pickles reconstruct it positionally.
_ShimField = namedtuple('_ShimField', ['name', 'numpy_dtype', 'shape', 'codec', 'nullable'])
_ShimField.__new__.__defaults__ = (None, False)


class _ShimObject:
    """Generic stand-in for a pickled reference/pyspark object: records its
    origin and accepts any instance state."""

    _shim_module = None
    _shim_name = None

    def __init__(self, *args, **kwargs):
        self._shim_args = args
        self._shim_kwargs = kwargs

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self.__dict__['_shim_state'] = state


def _make_shim(module, name):
    return type('_Shim_%s' % name, (_ShimObject,),
                {'_shim_module': module, '_shim_name': name})


class _RestrictedUnpickler(pickle.Unpickler):
    _ALLOWED = {
        ('collections', 'OrderedDict'): OrderedDict,
        ('decimal', 'Decimal'): Decimal,
        ('builtins', 'frozenset'): frozenset,
        ('builtins', 'set'): set,
        ('builtins', 'object'): object,
        ('copyreg', '_reconstructor'): __import__('copyreg')._reconstructor,
        # Python-2 module spellings: the reference's oldest datasets
        # (0.4.x-0.7.x, committed in its tree) were pickled under py2
        ('copy_reg', '_reconstructor'): __import__('copyreg')._reconstructor,
        ('__builtin__', 'frozenset'): frozenset,
        ('__builtin__', 'set'): set,
        ('__builtin__', 'object'): object,
        ('__builtin__', 'tuple'): tuple,
        ('__builtin__', 'list'): list,
        ('__builtin__', 'dict'): dict,
    }

    # legacy numpy scalar-type names removed in numpy 2.0; py2-era pickles
    # reference them
    _NUMPY_RENAMES = {'unicode_': 'str_', 'string_': 'bytes_'}

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED:
            return self._ALLOWED[(module, name)]
        if module == 'numpy' or module.startswith('numpy.'):
            # numpy dtype/scalar reconstruction only — a fixed allowlist of
            # reconstruction helpers and scalar-type classes, never arbitrary
            # numpy callables (numpy.load etc. must stay unreachable).
            if name in _SAFE_NUMPY_NAMES:
                if module in ('numpy.core.multiarray', 'numpy._core.multiarray'):
                    try:  # numpy >= 2.0
                        from numpy._core import multiarray
                    except ImportError:  # numpy 1.x
                        from numpy.core import multiarray
                    return getattr(multiarray, name)
                return getattr(np, self._NUMPY_RENAMES.get(name, name))
            raise pickle.UnpicklingError(
                'Refusing to depickle numpy attribute %s.%s from a dataset footer'
                % (module, name))
        for pkg in _LEGACY_PACKAGES:
            if module == pkg + '.unischema' and name == 'UnischemaField':
                return _ShimField
            if module.startswith(pkg + '.') or module == pkg:
                return _make_shim(module, name)
        if module.startswith('pyspark.'):
            return _make_shim(module, name)
        raise pickle.UnpicklingError(
            'Refusing to depickle %s.%s from a dataset footer' % (module, name))


def _loads(blob):
    # latin1: the standard decoding for Python-2 pickles (maps each byte
    # 1:1, so py2 str payloads like numpy scalar bytes survive); a no-op
    # for py3-written pickles, whose strings are SHORT_BINUNICODE
    return _RestrictedUnpickler(io.BytesIO(blob), encoding='latin1').load()


# ---------------------------------------------------------------------------
# shim → petastorm_tpu conversion
# ---------------------------------------------------------------------------

_SPARK_TYPE_NAME_TO_ARROW = {
    'BooleanType': pa.bool_(), 'ByteType': pa.int8(), 'ShortType': pa.int16(),
    'IntegerType': pa.int32(), 'LongType': pa.int64(), 'FloatType': pa.float32(),
    'DoubleType': pa.float64(), 'StringType': pa.string(),
    'BinaryType': pa.binary(), 'TimestampType': pa.timestamp('us'),
    'DateType': pa.date32(),
}


def _convert_codec(shim):
    if shim is None:
        return None
    name = getattr(type(shim), '_shim_name', None)
    state = getattr(shim, '__dict__', {})
    if name == 'NdarrayCodec':
        return tpu_codecs.NdarrayCodec()
    if name == 'CompressedNdarrayCodec':
        return tpu_codecs.CompressedNdarrayCodec()
    if name == 'CompressedImageCodec':
        image_codec = state.get('_image_codec', '.png').lstrip('.')
        return tpu_codecs.CompressedImageCodec(image_codec, state.get('_quality', 80))
    if name == 'ScalarCodec':
        spark_type = state.get('_spark_type')
        return tpu_codecs.ScalarCodec(_convert_spark_type(spark_type))
    raise MetadataError('Unknown legacy codec class %r in pickled schema' % name)


def _convert_spark_type(shim):
    name = getattr(type(shim), '_shim_name', None)
    if name in _SPARK_TYPE_NAME_TO_ARROW:
        return _SPARK_TYPE_NAME_TO_ARROW[name]
    if name == 'DecimalType':
        state = getattr(shim, '__dict__', {})
        return pa.decimal128(state.get('precision', 38), state.get('scale', 18))
    raise MetadataError('Unknown legacy spark type %r in pickled schema' % name)


def _convert_field(shim_field):
    if isinstance(shim_field, _ShimField):
        name, numpy_dtype, shape, codec, nullable = shim_field
    else:
        # pre-0.7.6 pickles reconstruct fields through a namedtuple-restore
        # helper — the shim captures it as
        # _shim_args = (typename, field_names, values)
        d = dict(getattr(shim_field, '__dict__', {}))
        args = d.get('_shim_args')
        if (args and len(args) == 3 and isinstance(args[1], (tuple, list))
                and isinstance(args[2], (tuple, list))):
            if len(args[1]) != len(args[2]):
                # zip would silently truncate, turning a malformed pickle
                # into silently-undecoded (raw bytes) columns
                raise MetadataError(
                    'Pickled field restore has %d names but %d values'
                    % (len(args[1]), len(args[2])))
            d.update(zip(args[1], args[2]))
        missing = {'name', 'numpy_dtype', 'shape'} - set(d)
        if missing:
            raise MetadataError('Pickled field has unexpected structure: '
                                'missing %s in %r' % (sorted(missing),
                                                      sorted(d)))
        name, numpy_dtype, shape = d['name'], d['numpy_dtype'], d['shape']
        codec, nullable = d.get('codec'), d.get('nullable', False)
    return UnischemaField(name, numpy_dtype, tuple(shape),
                          _convert_codec(codec), bool(nullable))


def depickle_legacy_unischema(blob):
    """Decode a reference-pickled Unischema blob into our Unischema."""
    obj = _loads(blob)
    d = getattr(obj, '__dict__', None)
    if d is None:
        raise MetadataError('Pickled schema has unexpected structure: %r' % type(obj))
    name = d.get('_name', 'legacy')
    fields = d.get('_fields')
    if fields is None:
        raise MetadataError('Pickled schema carries no _fields')
    if isinstance(fields, dict):
        shim_fields = list(fields.values())
    else:
        shim_fields = list(fields)
    return Unischema(name, [_convert_field(f) for f in shim_fields])


# ---------------------------------------------------------------------------
# petastorm_tpu → reference-compatible pickle (write-side interop)
# ---------------------------------------------------------------------------
#
# The reference loads schemas exclusively by unpickling the
# ``dataset-toolkit.unischema.v1`` footer blob
# (``petastorm/etl/dataset_metadata.py:356-386``), so for a dataset written by
# this framework to be readable by a real petastorm install, the footer must
# carry a pickle whose class references resolve to ``petastorm.unischema.*``,
# ``petastorm.codecs.*`` and ``pyspark.sql.types.*``. None of those packages
# are importable here; instead, lookalike classes with the right
# ``__module__``/``__qualname__`` are registered in ``sys.modules`` for the
# duration of one ``pickle.dumps`` call. The unpickling side (a genuine
# petastorm + pyspark install) reconstructs its own real classes from the
# module paths — instance state is what carries the schema.

import threading

from petastorm_tpu.codecs import ARROW_TO_SPARK_TYPE_NAME

_EXPORT_LOCK = threading.Lock()


def _real_modules_if_importable():
    """Use a genuinely-installed petastorm/pyspark for the export when
    available: real classes pickle with perfect fidelity AND no sys.modules
    shadowing is needed (so concurrent pyspark users are never exposed to
    stub modules)."""
    import importlib.util
    try:
        if (importlib.util.find_spec('petastorm') is None
                or importlib.util.find_spec('pyspark') is None):
            return None
        import petastorm.codecs as pc
        import petastorm.unischema as pu
        import pyspark.sql.types as pt
    except Exception:  # noqa: BLE001 - any breakage falls back to stubs
        return None
    ns = {name: getattr(pu, name) for name in ('Unischema', 'UnischemaField')}
    for name in ('ScalarCodec', 'NdarrayCodec', 'CompressedNdarrayCodec',
                 'CompressedImageCodec'):
        ns[name] = getattr(pc, name)
    for name in set(ARROW_TO_SPARK_TYPE_NAME.values()) | {
            'DecimalType', 'TimestampType'}:
        ns[name] = getattr(pt, name)
    return ns


def _install_export_modules():
    """Create sys.modules entries whose classes pickle under reference names.

    Returns (namespace dict, saved sys.modules entries) — caller must restore.
    """
    import sys
    import types

    mods = {}

    def new_module(name):
        m = types.ModuleType(name)
        mods[name] = m
        return m

    new_module('petastorm')
    new_module('pyspark')
    new_module('pyspark.sql')
    m_uni = new_module('petastorm.unischema')
    m_cod = new_module('petastorm.codecs')
    m_spark = new_module('pyspark.sql.types')

    ns = {}

    # The reference's UnischemaField is a NamedTuple of these 5 entries
    # (``petastorm/unischema.py:50-66``); namedtuple instances pickle as
    # class(*values), which the real class reconstructs positionally.
    field_cls = namedtuple('UnischemaField',
                           ['name', 'numpy_dtype', 'shape', 'codec', 'nullable'])
    field_cls.__module__ = 'petastorm.unischema'
    field_cls.__qualname__ = 'UnischemaField'
    m_uni.UnischemaField = field_cls
    ns['UnischemaField'] = field_cls

    class Unischema:  # noqa: N801 - must pickle under the reference name
        pass

    Unischema.__module__ = 'petastorm.unischema'
    Unischema.__qualname__ = 'Unischema'
    m_uni.Unischema = Unischema
    ns['Unischema'] = Unischema

    for codec_name in ('ScalarCodec', 'NdarrayCodec', 'CompressedNdarrayCodec',
                       'CompressedImageCodec'):
        cls = type(codec_name, (), {})
        cls.__module__ = 'petastorm.codecs'
        cls.__qualname__ = codec_name
        setattr(m_cod, codec_name, cls)
        ns[codec_name] = cls

    for type_name in set(ARROW_TO_SPARK_TYPE_NAME.values()) | {
            'DecimalType', 'TimestampType'}:
        cls = type(type_name, (), {})
        cls.__module__ = 'pyspark.sql.types'
        cls.__qualname__ = type_name
        setattr(m_spark, type_name, cls)
        ns[type_name] = cls

    saved = {k: sys.modules.get(k) for k in mods}
    sys.modules.update(mods)
    return ns, saved


def _restore_modules(saved):
    import sys
    for k, v in saved.items():
        if v is None:
            sys.modules.pop(k, None)
        else:
            sys.modules[k] = v


def _export_spark_type(ns, arrow_type, real_ctors):
    if pa.types.is_decimal(arrow_type):
        if real_ctors:
            return ns['DecimalType'](arrow_type.precision, arrow_type.scale)
        t = ns['DecimalType']()
        t.precision = arrow_type.precision
        t.scale = arrow_type.scale
        t.hasPrecisionInfo = True
        return t
    if pa.types.is_timestamp(arrow_type):
        return ns['TimestampType']()
    name = ARROW_TO_SPARK_TYPE_NAME.get(str(arrow_type))
    if name is None:
        raise MetadataError('No pyspark equivalent for arrow type %s' % arrow_type)
    return ns[name]()


def _export_codec(ns, codec, real_ctors):
    if codec is None:
        return None
    cls_name = type(codec).__name__
    if cls_name == 'NdarrayCodec':
        return ns['NdarrayCodec']()
    if cls_name == 'CompressedNdarrayCodec':
        return ns['CompressedNdarrayCodec']()
    if cls_name == 'CompressedImageCodec':
        if real_ctors:
            return ns['CompressedImageCodec'](codec.image_codec, codec._quality)
        out = ns['CompressedImageCodec']()
        out._image_codec = '.' + codec.image_codec
        out._quality = codec._quality
        return out
    if cls_name == 'ScalarCodec':
        spark_type = _export_spark_type(ns, codec._arrow_type, real_ctors)
        if real_ctors:
            return ns['ScalarCodec'](spark_type)
        out = ns['ScalarCodec']()
        out._spark_type = spark_type
        return out
    raise MetadataError('Codec %s has no reference equivalent' % cls_name)


def _build_export_schema(ns, schema, real_ctors):
    fields = OrderedDict()
    for f in schema.fields.values():
        fields[f.name] = ns['UnischemaField'](
            f.name, f.numpy_dtype, tuple(f.shape),
            _export_codec(ns, f.codec, real_ctors), bool(f.nullable))
    if real_ctors:
        return ns['Unischema'](schema._name, list(fields.values()))
    out = ns['Unischema']()
    out._name = schema._name
    out._fields = fields
    for name, field in fields.items():
        setattr(out, name, field)
    return out


def pickle_unischema_for_reference(schema):
    """Pickle our Unischema so a genuine petastorm+pyspark install loads it.

    The byte stream references only ``petastorm.unischema``,
    ``petastorm.codecs``, ``pyspark.sql.types``, numpy and stdlib names —
    exactly what the reference's own pickles reference — so its
    ``get_schema`` (``etl/dataset_metadata.py:356-386``) reconstructs a real
    ``petastorm.unischema.Unischema``. Protocol 2 for maximum back-compat.

    When a genuine petastorm+pyspark install is present, its real classes do
    the pickling directly. Otherwise lookalike classes are registered in
    ``sys.modules`` for the duration of one (lock-serialized) ``dumps`` call;
    an ``import pyspark`` racing that window from another thread could
    transiently bind a stub module — unavoidable with this technique, and
    only reachable when pyspark is not installed (so such an import would
    fail anyway).
    """
    real = _real_modules_if_importable()
    if real is not None:
        return pickle.dumps(_build_export_schema(real, schema, real_ctors=True),
                            protocol=2)
    with _EXPORT_LOCK:
        ns, saved = _install_export_modules()
        try:
            return pickle.dumps(_build_export_schema(ns, schema, real_ctors=False),
                                protocol=2)
        finally:
            _restore_modules(saved)
