"""Read schemas pickled into footers by the reference implementation.

The reference stores its ``Unischema`` as a Python pickle under
``dataset-toolkit.unischema.v1`` (``petastorm/etl/dataset_metadata.py:194-205``)
— including pre-rename module paths (``av.experimental.deepdrive.dataset_toolkit``,
``petastorm/etl/legacy.py:22-47``). This module depickles those blobs into
:class:`petastorm_tpu.unischema.Unischema` **without importing petastorm or
pyspark**, using shim classes and a restricted unpickler.

Security: footers are untrusted input. ``find_class`` only resolves an
explicit allowlist (numpy scalars/dtypes, OrderedDict, Decimal) and maps every
petastorm/pyspark class onto inert local shims; anything else raises.
"""

import io
import pickle
from collections import OrderedDict, namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu import codecs as tpu_codecs
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.unischema import Unischema, UnischemaField

_LEGACY_PACKAGES = ('petastorm', 'av.experimental.deepdrive.dataset_toolkit')

# numpy names a pickled schema may legitimately reference: the dtype machinery
# and scalar type classes. Nothing that does I/O or code execution.
_SAFE_NUMPY_NAMES = frozenset([
    'dtype', 'ndarray', '_reconstruct', 'scalar',
    'bool_', 'int8', 'uint8', 'int16', 'uint16', 'int32', 'uint32',
    'int64', 'uint64', 'float16', 'float32', 'float64', 'longdouble',
    'complex64', 'complex128', 'str_', 'bytes_', 'unicode_', 'string_',
    'object_', 'datetime64', 'timedelta64', 'void', 'generic', 'number',
    'integer', 'signedinteger', 'unsignedinteger', 'floating', 'inexact',
    'flexible', 'character', 'intc', 'intp', 'int_', 'uint', 'single', 'double',
])

# The reference's UnischemaField is a NamedTuple with this exact field order
# (``petastorm/unischema.py:50-66``); pickles reconstruct it positionally.
_ShimField = namedtuple('_ShimField', ['name', 'numpy_dtype', 'shape', 'codec', 'nullable'])
_ShimField.__new__.__defaults__ = (None, False)


class _ShimObject:
    """Generic stand-in for a pickled reference/pyspark object: records its
    origin and accepts any instance state."""

    _shim_module = None
    _shim_name = None

    def __init__(self, *args, **kwargs):
        self._shim_args = args
        self._shim_kwargs = kwargs

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self.__dict__['_shim_state'] = state


def _make_shim(module, name):
    return type('_Shim_%s' % name, (_ShimObject,),
                {'_shim_module': module, '_shim_name': name})


class _RestrictedUnpickler(pickle.Unpickler):
    _ALLOWED = {
        ('collections', 'OrderedDict'): OrderedDict,
        ('decimal', 'Decimal'): Decimal,
        ('builtins', 'frozenset'): frozenset,
        ('builtins', 'set'): set,
        ('builtins', 'object'): object,
        ('copyreg', '_reconstructor'): __import__('copyreg')._reconstructor,
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED:
            return self._ALLOWED[(module, name)]
        if module == 'numpy' or module.startswith('numpy.'):
            # numpy dtype/scalar reconstruction only — a fixed allowlist of
            # reconstruction helpers and scalar-type classes, never arbitrary
            # numpy callables (numpy.load etc. must stay unreachable).
            if name in _SAFE_NUMPY_NAMES:
                if module in ('numpy.core.multiarray', 'numpy._core.multiarray'):
                    try:  # numpy >= 2.0
                        from numpy._core import multiarray
                    except ImportError:  # numpy 1.x
                        from numpy.core import multiarray
                    return getattr(multiarray, name)
                return getattr(np, name)
            raise pickle.UnpicklingError(
                'Refusing to depickle numpy attribute %s.%s from a dataset footer'
                % (module, name))
        for pkg in _LEGACY_PACKAGES:
            if module == pkg + '.unischema' and name == 'UnischemaField':
                return _ShimField
            if module.startswith(pkg + '.') or module == pkg:
                return _make_shim(module, name)
        if module.startswith('pyspark.'):
            return _make_shim(module, name)
        raise pickle.UnpicklingError(
            'Refusing to depickle %s.%s from a dataset footer' % (module, name))


def _loads(blob):
    return _RestrictedUnpickler(io.BytesIO(blob)).load()


# ---------------------------------------------------------------------------
# shim → petastorm_tpu conversion
# ---------------------------------------------------------------------------

_SPARK_TYPE_NAME_TO_ARROW = {
    'BooleanType': pa.bool_(), 'ByteType': pa.int8(), 'ShortType': pa.int16(),
    'IntegerType': pa.int32(), 'LongType': pa.int64(), 'FloatType': pa.float32(),
    'DoubleType': pa.float64(), 'StringType': pa.string(),
    'BinaryType': pa.binary(), 'TimestampType': pa.timestamp('us'),
    'DateType': pa.date32(),
}


def _convert_codec(shim):
    if shim is None:
        return None
    name = getattr(type(shim), '_shim_name', None)
    state = getattr(shim, '__dict__', {})
    if name == 'NdarrayCodec':
        return tpu_codecs.NdarrayCodec()
    if name == 'CompressedNdarrayCodec':
        return tpu_codecs.CompressedNdarrayCodec()
    if name == 'CompressedImageCodec':
        image_codec = state.get('_image_codec', '.png').lstrip('.')
        return tpu_codecs.CompressedImageCodec(image_codec, state.get('_quality', 80))
    if name == 'ScalarCodec':
        spark_type = state.get('_spark_type')
        return tpu_codecs.ScalarCodec(_convert_spark_type(spark_type))
    raise MetadataError('Unknown legacy codec class %r in pickled schema' % name)


def _convert_spark_type(shim):
    name = getattr(type(shim), '_shim_name', None)
    if name in _SPARK_TYPE_NAME_TO_ARROW:
        return _SPARK_TYPE_NAME_TO_ARROW[name]
    if name == 'DecimalType':
        state = getattr(shim, '__dict__', {})
        return pa.decimal128(state.get('precision', 38), state.get('scale', 18))
    raise MetadataError('Unknown legacy spark type %r in pickled schema' % name)


def _convert_field(shim_field):
    if isinstance(shim_field, _ShimField):
        name, numpy_dtype, shape, codec, nullable = shim_field
    else:  # very old pickles may carry a shim object with attributes
        d = shim_field.__dict__
        name, numpy_dtype, shape = d['name'], d['numpy_dtype'], d['shape']
        codec, nullable = d.get('codec'), d.get('nullable', False)
    return UnischemaField(name, numpy_dtype, tuple(shape),
                          _convert_codec(codec), bool(nullable))


def depickle_legacy_unischema(blob):
    """Decode a reference-pickled Unischema blob into our Unischema."""
    obj = _loads(blob)
    d = getattr(obj, '__dict__', None)
    if d is None:
        raise MetadataError('Pickled schema has unexpected structure: %r' % type(obj))
    name = d.get('_name', 'legacy')
    fields = d.get('_fields')
    if fields is None:
        raise MetadataError('Pickled schema carries no _fields')
    if isinstance(fields, dict):
        shim_fields = list(fields.values())
    else:
        shim_fields = list(fields)
    return Unischema(name, [_convert_field(f) for f in shim_fields])
