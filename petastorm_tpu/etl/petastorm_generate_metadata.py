"""Attach/regenerate petastorm_tpu metadata on an existing Parquet store.

Reference: ``petastorm/etl/petastorm_generate_metadata.py:47-161`` — used
when a dataset was produced without :func:`materialize_dataset` (plain
pyarrow/Spark write), or its ``_common_metadata`` was lost. The schema comes
from (in priority order): an explicit ``--unischema-class`` (full qualified
name, located via pydoc), the existing footer, or arrow-schema inference.

Usage::

    python -m petastorm_tpu.etl.petastorm_generate_metadata \
        file:///path/to/dataset [--unischema-class mypkg.MySchema]
"""

import argparse
import logging
import sys

logger = logging.getLogger(__name__)


def generate_petastorm_metadata(dataset_url, unischema_class=None,
                                storage_options=None):
    """Write schema JSON + row-group counts into ``_common_metadata``."""
    from pydoc import locate

    from petastorm_tpu.errors import MetadataError
    from petastorm_tpu.etl.dataset_metadata import (
        ParquetDatasetInfo, _write_dataset_footer, get_schema,
        infer_or_load_unischema,
    )
    from petastorm_tpu.unischema import Unischema

    info = ParquetDatasetInfo(dataset_url, storage_options)
    if unischema_class:
        schema = locate(unischema_class)
        if not isinstance(schema, Unischema):
            raise ValueError('%r does not resolve to a Unischema instance'
                             % unischema_class)
    else:
        try:
            schema = get_schema(info)
            logger.info('Regenerating metadata from the existing footer schema')
        except MetadataError:
            schema = infer_or_load_unischema(info)
            logger.info('No stored schema found; inferred one from the '
                        'arrow schema (codec-less fields)')
    _write_dataset_footer(dataset_url, schema, storage_options)
    return schema


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--unischema-class', default=None,
                        help='full qualified name of a Unischema instance, '
                             'e.g. examples.mnist.schema.MnistSchema')
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO)
    generate_petastorm_metadata(args.dataset_url,
                                unischema_class=args.unischema_class)
    return 0


if __name__ == '__main__':
    sys.exit(main())
