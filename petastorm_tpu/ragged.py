"""Shared diagnosis of object-dtype columns at the framework-bridge seam.

A decoded column arrives as a 1-d object array in exactly three cases —
ragged numeric cells (variable-shape fields), string/decimal cells, or
all-None (nullable) cells — and every dense consumer (torch collation,
tf.data elements) must reject them with the SAME actionable story. One
classifier + one message keeps the three bridge call sites from drifting
into inconsistent diagnoses of identical data.
"""

import numpy as np

RAGGED_MESSAGE = (
    'Field %r has variable shape (rows of differing sizes) and cannot be '
    'collated into one dense tensor; project it away (schema_fields), '
    'densify it with a TransformSpec, or use '
    'make_jax_loader(pad_ragged=...) / bucket_boundaries for static-shape '
    'padded batches')
STRING_MESSAGE = (
    'Field %r is a string/decimal and has no dense tensor representation; '
    'project it away (schema_fields/TransformSpec) or convert it in a '
    'TransformSpec')
NULL_MESSAGE = (
    'Field %r is entirely None in this batch (nullable field); fill or '
    'filter nulls before dense collation, or project the field away '
    '(schema_fields)')


def classify_object_column(arr):
    """``'ragged' | 'string' | 'null'`` for a 1-d object column."""
    first = next((c for c in arr if c is not None), None)
    if first is None:
        return 'null'
    if isinstance(first, (np.ndarray, list, tuple)):
        return 'ragged'
    return 'string'


def reject_object_column(name, arr):
    """Raise the classified, actionable ``TypeError`` for ``arr``."""
    kind = classify_object_column(arr)
    message = {'ragged': RAGGED_MESSAGE, 'string': STRING_MESSAGE,
               'null': NULL_MESSAGE}[kind]
    raise TypeError(message % name)
