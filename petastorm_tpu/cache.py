"""Row-group caches.

Parity with ``petastorm/cache.py`` + ``local_disk_cache.py``, minus the
``diskcache`` dependency: :class:`LocalDiskCache` is a small self-contained
file cache (pickled values, sharded dirs, size-bounded LRU by access time).
"""

import hashlib
import logging
import os
import pickle
import re
import socket
import threading
import time
from abc import ABCMeta, abstractmethod

logger = logging.getLogger(__name__)

#: every disk tier writes entries as ``<entry><_TMP_MARKER><host>-<pid>``
#: and publishes them with an atomic ``os.replace``
_TMP_MARKER = '.tmp.'

#: pid liveness can only be checked on the writer's own host; a FOREIGN
#: host's tmp file is purged only once it is old enough that its writer
#: has certainly crashed or finished (a write takes seconds, not an hour)
_FOREIGN_TMP_TTL_S = 3600.0

_HOST = re.sub(r'[^A-Za-z0-9]', '', socket.gethostname())[:32] or 'host'


def is_tmp_entry(name):
    """True for an in-flight (or orphaned) writer's tmp file."""
    return _TMP_MARKER in name


def tmp_entry_path(entry):
    """The tmp name a writer publishes ``entry`` through. Carries host AND
    pid: pid liveness is only checkable on the writer's own host, so a
    cache directory on shared storage (the multi-host service-fleet
    shape) must be able to tell a local writer from a remote one."""
    return '%s%s%s-%d' % (entry, _TMP_MARKER, _HOST, os.getpid())


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # e.g. EPERM: the pid exists, just isn't ours
    return True


def _tmp_status(full, name, now):
    """``None`` for a real entry, ``'live'`` for an in-flight writer's
    tmp file, ``'stale'`` for a dead writer's orphan. This host's tmp
    files are judged by pid liveness; a foreign host's (shared-storage
    fleet directory) only by age past :data:`_FOREIGN_TMP_TTL_S` —
    ``os.kill`` on another host's pid would misread a LIVE remote writer
    as dead and delete the file out from under its rename."""
    i = name.rfind(_TMP_MARKER)
    if i < 0:
        return None
    suffix = name[i + len(_TMP_MARKER):]
    host, _, pid_text = suffix.rpartition('-')
    if not pid_text.isdigit():
        return 'live'  # not our naming: excluded from scans, never purged
    if host in ('', _HOST):
        # this host (or a legacy pid-only suffix): liveness check
        return 'live' if _pid_alive(int(pid_text)) else 'stale'
    try:
        age = now - os.stat(full).st_mtime
    except OSError:
        return 'live'
    return 'stale' if age >= _FOREIGN_TMP_TTL_S else 'live'


def purge_stale_tmp_files(path):
    """Delete tmp files whose writer is dead (see :func:`_tmp_status`).

    A writer killed between its tmp write and the ``os.replace`` leaks an
    orphan that would otherwise inflate every size scan forever and — if
    the eviction walk saw it — could be "evicted" out from under a LIVE
    writer's in-flight rename. Returns the number removed."""
    removed = 0
    now = time.time()
    for root, _, files in os.walk(path):
        for name in files:
            full = os.path.join(root, name)
            if _tmp_status(full, name, now) == 'stale':
                try:
                    os.remove(full)
                    removed += 1
                except OSError:
                    pass
    return removed


def attach_scan(path):
    """Cache-init walk: purge stale tmp files AND total the surviving
    entries in ONE pass — a fleet directory can hold tens of thousands of
    entries on network storage, and two back-to-back walks would double
    an already slow startup stat storm. Returns the entry byte total."""
    total = 0
    now = time.time()
    for root, _, files in os.walk(path):
        for name in files:
            full = os.path.join(root, name)
            status = _tmp_status(full, name, now)
            if status is None:  # a real entry: count it
                try:
                    total += os.stat(full).st_size
                except OSError:
                    pass
            elif status == 'stale':
                try:
                    os.remove(full)
                except OSError:
                    pass
    return total


def publish_entry(entry, write_func):
    """Atomic cache-entry publish, shared by both disk tiers: write the
    payload to the entry's tmp name via ``write_func(tmp_path)``, then
    ``os.replace`` it into place — concurrent readers see the old bytes
    or the new, never a partial file. Returns ``(size, replaced)``:
    the new entry's size and the size of any entry it overwrote (the
    caller's running-total accounting needs the difference; forgetting
    the overwrite would inflate the total until the next full rescan)."""
    os.makedirs(os.path.dirname(entry), exist_ok=True)
    tmp = tmp_entry_path(entry)
    write_func(tmp)
    size = os.stat(tmp).st_size
    try:
        replaced = os.stat(entry).st_size
    except OSError:
        replaced = 0
    os.replace(tmp, entry)
    return size, replaced


def scan_dir_entries(path):
    """``([(atime, size, path), ...], total_bytes)`` over a cache
    directory, skipping in-flight tmp files (they aren't entries and must
    never be size-accounted or evicted). Shared by both disk tiers."""
    entries, total = [], 0
    for root, _, files in os.walk(path):
        for name in files:
            if is_tmp_entry(name):
                continue
            p = os.path.join(root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_atime, st.st_size, p))
            total += st.st_size
    return entries, total


def evict_lru(path, size_limit):
    """Walk ``path`` and LRU-delete entries (oldest atime first) until it
    fits ``size_limit``. Returns ``(total_after, evictions,
    bytes_evicted)``. Deliberately lock-free: callers must NOT hold their
    cache lock across this filesystem walk (it would serialize every
    concurrent hit behind I/O); concurrent evictors/re-writers are
    tolerated — sizes are re-measured at eviction time and races surface
    as the OSError passes."""
    entries, total = scan_dir_entries(path)
    evictions = bytes_evicted = 0
    if total > size_limit:
        entries.sort()  # oldest access first
        for _, _, p in entries:
            try:
                # Size measured at EVICTION time, not scan time: another
                # process may have re-written the entry since (atomic
                # rename), and accounting the stale size would drift the
                # running total.
                size = os.stat(p).st_size
                os.remove(p)
                total -= size
                evictions += 1
                bytes_evicted += size
            except OSError:
                pass
            if total <= size_limit:
                break
    return total, evictions, bytes_evicted


# telemetry counter names (read back by telemetry.pipeline_report's cache
# section); a worker process's increments ride the pool delta channel
CACHE_HITS = 'petastorm_tpu_cache_hits_total'
CACHE_MISSES = 'petastorm_tpu_cache_misses_total'
CACHE_EVICTIONS = 'petastorm_tpu_cache_evictions_total'
CACHE_BYTES_WRITTEN = 'petastorm_tpu_cache_bytes_written_total'
CACHE_BYTES_EVICTED = 'petastorm_tpu_cache_bytes_evicted_total'
CACHE_SIZE_BYTES = 'petastorm_tpu_cache_size_bytes'


class CacheBase(metaclass=ABCMeta):
    @abstractmethod
    def get(self, key, fill_cache_func):
        """Value for ``key``; on miss call ``fill_cache_func``, store, return."""

    def cleanup(self):
        """Release resources (no-op by default)."""


class NullCache(CacheBase):
    """Never caches (reference: ``cache.py:30-39``)."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class LocalDiskCache(CacheBase):
    """File-backed cache with a soft size bound and LRU eviction.

    :param path: cache directory (created if needed).
    :param size_limit_bytes: soft cap; least-recently-accessed entries are
        evicted when exceeded.
    :param expected_row_size_bytes: accepted for reference API compatibility.
    """

    _SHARDS = 64

    def __init__(self, path, size_limit_bytes, expected_row_size_bytes=None,
                 cleanup=False, **_unused):
        self._path = path
        self._size_limit = size_limit_bytes
        self._cleanup_on_exit = cleanup
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)
        # One walk purges dead writers' tmp files AND totals the entries.
        # The running byte total avoids re-walking the tree on every
        # store; full walks happen only here and when the cap is crossed.
        self._total = attach_scan(path)

    def _scan_total(self):
        return scan_dir_entries(self._path)[1]

    def __getstate__(self):
        # Locks don't cross the process-pool spawn boundary; each process
        # gets its own (the cache is safe across processes via atomic rename).
        state = self.__dict__.copy()
        del state['_lock']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _entry_path(self, key):
        digest = hashlib.sha1(str(key).encode('utf-8')).hexdigest()
        shard = digest[:2]
        return os.path.join(self._path, shard, digest + '.pkl')

    @staticmethod
    def _registry():
        from petastorm_tpu.telemetry import get_registry
        return get_registry()

    def _size_gauge(self):
        # labeled per process so last-writer-wins gauge merges from
        # different workers don't interleave into flicker. Every process's
        # running total covers the WHOLE shared cache directory, so the
        # consumer aggregates these series with max (freshest estimate of
        # the one directory), never sum — see telemetry.export's cache
        # section.
        return self._registry().gauge(CACHE_SIZE_BYTES, pid=str(os.getpid()))

    def get(self, key, fill_cache_func):
        entry = self._entry_path(key)
        try:
            with open(entry, 'rb') as f:
                value = pickle.load(f)
            os.utime(entry)  # LRU touch
            self._registry().counter(CACHE_HITS).inc()
            return value
        except OSError:
            pass  # plain miss: no entry yet
        except (pickle.UnpicklingError, ValueError, EOFError,
                AttributeError):
            # Corrupt entry (UnpicklingError and its subclasses, numpy's
            # truncated-read ValueError, a short file's EOFError, a
            # missing-attribute unpickle): delete it NOW so every other
            # process stops re-reading the bad bytes until our re-fill
            # below lands — and keep the running total honest.
            logger.warning('LocalDiskCache entry for %r corrupt; deleting',
                           key, exc_info=True)
            try:
                size = os.stat(entry).st_size
                os.remove(entry)
                with self._lock:
                    self._total -= size
            except OSError:
                pass
        self._registry().counter(CACHE_MISSES).inc()
        value = fill_cache_func()
        try:
            def write(tmp):
                with open(tmp, 'wb') as f:
                    pickle.dump(value, f,
                                protocol=pickle.HIGHEST_PROTOCOL)
            size, replaced = publish_entry(entry, write)
            self._registry().counter(CACHE_BYTES_WRITTEN).inc(size)
            with self._lock:
                self._total += size - replaced
                over_limit = self._total > self._size_limit
            self._size_gauge().set(self._total)
            if over_limit:
                self._maybe_evict()
        except OSError:
            logger.warning('LocalDiskCache failed to store %r', key, exc_info=True)
        return value

    def _maybe_evict(self):
        # the walk runs OUTSIDE the lock (an eviction pass over a large
        # tier must not serialize every concurrent get behind disk I/O);
        # only the running-total update is guarded
        with self._lock:
            before = self._total
        total, evictions, bytes_evicted = evict_lru(self._path,
                                                    self._size_limit)
        with self._lock:
            # merge, don't assign: entries published DURING the walk
            # bumped _total concurrently, and a plain overwrite would
            # lose them (cap overrun with no eviction trigger). Keeping
            # their delta can at worst double-count a publish the walk
            # also saw — an overestimate that only triggers an extra
            # self-correcting walk, never a silent overrun.
            self._total = total + (self._total - before)
        if evictions:
            registry = self._registry()
            registry.counter(CACHE_EVICTIONS).inc(evictions)
            registry.counter(CACHE_BYTES_EVICTED).inc(bytes_evicted)
        self._size_gauge().set(self._total)

    def cleanup(self):
        if self._cleanup_on_exit:
            import shutil
            shutil.rmtree(self._path, ignore_errors=True)
