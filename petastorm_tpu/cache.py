"""Row-group caches.

Parity with ``petastorm/cache.py`` + ``local_disk_cache.py``, minus the
``diskcache`` dependency: :class:`LocalDiskCache` is a small self-contained
file cache (pickled values, sharded dirs, size-bounded LRU by access time).
"""

import hashlib
import logging
import os
import pickle
import threading
from abc import ABCMeta, abstractmethod

logger = logging.getLogger(__name__)

# telemetry counter names (read back by telemetry.pipeline_report's cache
# section); a worker process's increments ride the pool delta channel
CACHE_HITS = 'petastorm_tpu_cache_hits_total'
CACHE_MISSES = 'petastorm_tpu_cache_misses_total'
CACHE_EVICTIONS = 'petastorm_tpu_cache_evictions_total'
CACHE_BYTES_WRITTEN = 'petastorm_tpu_cache_bytes_written_total'
CACHE_BYTES_EVICTED = 'petastorm_tpu_cache_bytes_evicted_total'
CACHE_SIZE_BYTES = 'petastorm_tpu_cache_size_bytes'


class CacheBase(metaclass=ABCMeta):
    @abstractmethod
    def get(self, key, fill_cache_func):
        """Value for ``key``; on miss call ``fill_cache_func``, store, return."""

    def cleanup(self):
        """Release resources (no-op by default)."""


class NullCache(CacheBase):
    """Never caches (reference: ``cache.py:30-39``)."""

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class LocalDiskCache(CacheBase):
    """File-backed cache with a soft size bound and LRU eviction.

    :param path: cache directory (created if needed).
    :param size_limit_bytes: soft cap; least-recently-accessed entries are
        evicted when exceeded.
    :param expected_row_size_bytes: accepted for reference API compatibility.
    """

    _SHARDS = 64

    def __init__(self, path, size_limit_bytes, expected_row_size_bytes=None,
                 cleanup=False, **_unused):
        self._path = path
        self._size_limit = size_limit_bytes
        self._cleanup_on_exit = cleanup
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)
        # Running byte total avoids walking the whole tree on every store;
        # the full walk happens only at init and when the cap is crossed.
        self._total = self._scan_total()

    def _scan_total(self):
        total = 0
        for root, _, files in os.walk(self._path):
            for name in files:
                try:
                    total += os.stat(os.path.join(root, name)).st_size
                except OSError:
                    pass
        return total

    def __getstate__(self):
        # Locks don't cross the process-pool spawn boundary; each process
        # gets its own (the cache is safe across processes via atomic rename).
        state = self.__dict__.copy()
        del state['_lock']
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _entry_path(self, key):
        digest = hashlib.sha1(str(key).encode('utf-8')).hexdigest()
        shard = digest[:2]
        return os.path.join(self._path, shard, digest + '.pkl')

    @staticmethod
    def _registry():
        from petastorm_tpu.telemetry import get_registry
        return get_registry()

    def _size_gauge(self):
        # labeled per process so last-writer-wins gauge merges from
        # different workers don't interleave into flicker. Every process's
        # running total covers the WHOLE shared cache directory, so the
        # consumer aggregates these series with max (freshest estimate of
        # the one directory), never sum — see telemetry.export's cache
        # section.
        return self._registry().gauge(CACHE_SIZE_BYTES, pid=str(os.getpid()))

    def get(self, key, fill_cache_func):
        entry = self._entry_path(key)
        try:
            with open(entry, 'rb') as f:
                value = pickle.load(f)
            os.utime(entry)  # LRU touch
            self._registry().counter(CACHE_HITS).inc()
            return value
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        self._registry().counter(CACHE_MISSES).inc()
        value = fill_cache_func()
        try:
            os.makedirs(os.path.dirname(entry), exist_ok=True)
            tmp = entry + '.tmp.%d' % os.getpid()
            with open(tmp, 'wb') as f:
                pickle.dump(value, f, protocol=pickle.HIGHEST_PROTOCOL)
            size = os.stat(tmp).st_size
            # An overwrite (re-fill after a truncated/corrupt entry)
            # replaces the old bytes; forgetting to subtract them would
            # inflate the running total until the next full rescan and
            # trigger premature evictions.
            try:
                replaced = os.stat(entry).st_size
            except OSError:
                replaced = 0
            os.replace(tmp, entry)
            self._registry().counter(CACHE_BYTES_WRITTEN).inc(size)
            with self._lock:
                self._total += size - replaced
                over_limit = self._total > self._size_limit
            self._size_gauge().set(self._total)
            if over_limit:
                self._maybe_evict()
        except OSError:
            logger.warning('LocalDiskCache failed to store %r', key, exc_info=True)
        return value

    def _maybe_evict(self):
        evictions = 0
        bytes_evicted = 0
        with self._lock:
            entries = []
            total = 0
            for root, _, files in os.walk(self._path):
                for name in files:
                    p = os.path.join(root, name)
                    try:
                        st = os.stat(p)
                    except OSError:
                        continue
                    entries.append((st.st_atime, p))
                    total += st.st_size
            if total <= self._size_limit:
                self._total = total
            else:
                entries.sort()  # oldest access first
                for _, p in entries:
                    try:
                        # Size measured at EVICTION time, not insert/scan
                        # time: another process may have re-written the
                        # entry since (atomic rename), and accounting the
                        # stale size would drift the running total.
                        size = os.stat(p).st_size
                        os.remove(p)
                        total -= size
                        evictions += 1
                        bytes_evicted += size
                    except OSError:
                        pass
                    if total <= self._size_limit:
                        break
                self._total = total
        if evictions:
            registry = self._registry()
            registry.counter(CACHE_EVICTIONS).inc(evictions)
            registry.counter(CACHE_BYTES_EVICTED).inc(bytes_evicted)
        self._size_gauge().set(self._total)

    def cleanup(self):
        if self._cleanup_on_exit:
            import shutil
            shutil.rmtree(self._path, ignore_errors=True)
