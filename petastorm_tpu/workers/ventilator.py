"""Ventilator: feeds work items into a pool with bounded in-flight count.

Re-design of ``petastorm/workers_pool/ventilator.py:26-166``. Beyond the
reference semantics (bounded back-pressure, per-epoch reshuffle, infinite
epochs), this ventilator is **checkpointable**: :meth:`state_dict` /
:meth:`load_state_dict` capture (epoch, cursor, RNG seed) so a reader can
resume mid-epoch — a capability the reference lacks (SURVEY.md §5.4).
"""

import inspect
import logging
import threading
from abc import ABCMeta, abstractmethod

import numpy as np

from petastorm_tpu.telemetry import span, tracing

logger = logging.getLogger(__name__)


def _accepts_trace_ctx(fn):
    """True when ``fn(**item)`` tolerates the injected ``_trace_ctx``
    kwarg (a ``**kwargs`` or an explicit parameter). The pools' ``ventilate``
    methods do; a bare user callable may not — tracing is advisory, so
    for those the context is simply not carried rather than crashing the
    ventilation thread with a TypeError."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    for param in sig.parameters.values():
        if param.kind is inspect.Parameter.VAR_KEYWORD:
            return True
        if param.name == tracing.TRACE_CTX_KEY:
            return True
    return False

_VENTILATION_INTERVAL_S = 0.01

# Seed advance per reset() sweep; far larger than any realistic epoch count
# so `seed + epoch` ranges of successive sweeps never collide.
_RESET_SEED_STRIDE = 0x9E3779B1


def epoch_order(n_items, seed, epoch, randomize):
    """The ONE owner of the per-epoch item order: epoch ``e`` permutes
    with ``RandomState((seed + e) mod 2^32)`` (identity when not
    randomized). Shared by :class:`ConcurrentVentilator` and the
    readahead plane's sequence mirror (:mod:`petastorm_tpu.readahead`) —
    two private copies would drift silently, and the mirror's failure
    mode (zero hits, rows still correct) is invisible to parity tests."""
    if not randomize:
        return list(range(n_items))
    rng = np.random.RandomState((seed + epoch) % (2 ** 32))
    return [int(i) for i in rng.permutation(n_items)]


class Ventilator(metaclass=ABCMeta):
    """Base class for ventilators (reference: ``ventilator.py:26-52``)."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    @abstractmethod
    def start(self):
        """Begin ventilation."""

    @abstractmethod
    def processed_item(self):
        """Called by the pool whenever a worker finishes one item."""

    @abstractmethod
    def completed(self):
        """True when no more items will ever be ventilated."""

    @abstractmethod
    def stop(self):
        """Stop ventilation."""


class ConcurrentVentilator(Ventilator):
    """Feeds items from a background thread, keeping at most
    ``max_ventilation_queue_size`` items in flight.

    :param ventilate_fn: callable receiving ``**item`` for each work item.
    :param items_to_ventilate: list of dicts (kwargs for ``ventilate_fn``).
    :param iterations: number of epochs over the item list; None = infinite.
    :param max_ventilation_queue_size: in-flight bound (back-pressure);
        defaults to one full epoch. May be a zero-arg callable, re-read on
        every wait cycle — a pool whose worker fleet grows at runtime (the
        service pool's remote worker servers) raises the bound live.
    :param randomize_item_order: reshuffle item order at each epoch start.
    :param random_seed: seed for the per-epoch permutations. Epoch ``e`` uses
        ``seed + e`` so every shard/host can reproduce the order
        arithmetically without communication.
    :param trace_shard: shard id recorded in minted trace contexts (the
        Reader passes its resolved ``cur_shard``). The ventilator is where
        per-item tracing BEGINS: each sampled item gets a
        :class:`~petastorm_tpu.telemetry.tracing.TraceContext` injected as
        the reserved ``_trace_ctx`` kwarg, which every pool flavor strips
        (and activates) before ``worker.process`` — so worker-side events
        anywhere in the fleet share the trace id minted here.
    """

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 max_ventilation_queue_size=None, randomize_item_order=False,
                 random_seed=0, pass_epoch=False, trace_shard=None,
                 always_exclude=None):
        """``always_exclude``: item indices skipped in EVERY epoch (and
        across resets) — the Reader's statistics-pruned row-groups
        (:mod:`petastorm_tpu.pushdown`): items proven to deliver zero
        rows stay in the list (so item indices, shard assignment and
        checkpoint identities are unchanged) but are never handed to the
        pool. Distinct from :meth:`exclude_from_next_epoch`, which is a
        one-epoch resume exclusion."""
        super().__init__(ventilate_fn)
        if iterations is not None and iterations <= 0:
            raise ValueError('iterations must be positive or None, got %r' % iterations)
        self._pass_epoch = pass_epoch
        self._trace_shard = trace_shard
        self._carries_trace_ctx = _accepts_trace_ctx(ventilate_fn)
        self._items = list(items_to_ventilate)
        self._initial_iterations = iterations
        self._iterations_remaining = iterations
        self._max_queue_size = (max_ventilation_queue_size
                                or max(1, len(self._items)))
        self._randomize = randomize_item_order
        # None = nondeterministic: draw once so the epoch/reset arithmetic
        # (`seed + epoch`, reset stride) always has an int to work with.
        if random_seed is None:
            random_seed = int(np.random.randint(0, 2 ** 32, dtype=np.uint32))
        self._seed = random_seed

        self._epoch = 0
        self._cursor = 0
        self._exclude_once = frozenset()
        self._exclude_always = frozenset(always_exclude or ())
        self._in_flight = 0
        self._cv = threading.Condition()
        self._stop_requested = False
        self._completed = False
        self._error = None
        self._thread = None

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        with self._cv:
            if self._thread is not None:
                raise RuntimeError('Ventilator already started')
            if not self._items or (
                    self._exclude_always
                    and self._exclude_always.issuperset(
                        range(len(self._items)))):
                # nothing will ever be ventilated (empty list, or every
                # item statistics-pruned): complete immediately — even
                # for infinite iterations, where spinning through empty
                # epochs would burn a core delivering nothing forever
                self._completed = True
                return
            if self._stop_requested:
                return
            # Created AND started under the lock so stop() can never observe
            # a thread object that is not yet joinable.
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def processed_item(self):
        with self._cv:
            self._in_flight = max(0, self._in_flight - 1)
            self._cv.notify_all()

    def completed(self):
        return self._completed

    @property
    def error(self):
        """The exception that killed ventilation, or None. A dead
        ventilator reads as completed (no more items will ever arrive) so
        consumers drain and stop instead of waiting forever; callers that
        must distinguish truncation from success check here."""
        return self._error

    def stop(self):
        with self._cv:
            self._stop_requested = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
            with self._cv:
                self._thread = None

    def reset(self):
        """Restart ventilation for the originally requested epoch count.

        Only legal after the previous run completed
        (reference: ``ventilator.py:125-134``).
        """
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError('Cannot reset a ventilator that is still ventilating')
        if not self._completed:
            raise RuntimeError('Cannot reset a ventilator before it completed')
        self._thread = None
        self._completed = False
        self._error = None
        self._stop_requested = False
        self._cursor = 0
        self._epoch = 0
        # Epoch numbering restarts at 0 (the reader's resume math depends on
        # it), so advance the seed instead: without this, every reset sweep
        # would replay the first sweep's "random" row-group orders verbatim.
        # Deterministic, so multi-host readers that reset in lockstep still
        # agree arithmetically, and state_dict()'s captured seed reproduces
        # the order on resume.
        self._seed = (self._seed + _RESET_SEED_STRIDE) % (2 ** 32)
        self._in_flight = 0
        self._iterations_remaining = self._initial_iterations
        self.start()

    # -- checkpointable iteration state -------------------------------------

    def state_dict(self):
        with self._cv:
            return {
                'epoch': self._epoch,
                'cursor': self._cursor,
                'seed': self._seed,
                'iterations_remaining': self._iterations_remaining,
            }

    def load_state_dict(self, state):
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError('Cannot load state while ventilating')
        self._epoch = state['epoch']
        self._cursor = state['cursor']
        self._seed = state['seed']
        self._iterations_remaining = state['iterations_remaining']

    def exclude_from_next_epoch(self, item_indices):
        """Skip the given item indices during the next epoch only — used for
        exact resume: already-consumed items are not re-ventilated."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError('Cannot set exclusions while ventilating')
        self._exclude_once = frozenset(item_indices)
        self._cursor = 0

    # -- internals ----------------------------------------------------------

    def _current_max_queue_size(self):
        size = self._max_queue_size
        return size() if callable(size) else size

    def _epoch_order(self, epoch):
        return epoch_order(len(self._items), self._seed, epoch,
                           self._randomize)

    def _run(self):
        # A ventilation-thread death must never read as "still running":
        # before this guard, an exception here (e.g. a ventilate_fn
        # rejecting the injected _trace_ctx kwarg) died silently with
        # ``completed()`` stuck False, wedging every consumer that polls
        # it — the exact silent-deadlock class pipecheck exists to stop.
        # Found while testing the analyzer; regression:
        # tests/test_workers_pool.py::test_ventilator_error_completes.
        try:
            self._run_inner()
        except Exception as e:  # noqa: BLE001 - surfaced via .error
            logger.exception('Ventilator thread died; marking ventilation '
                             'complete so consumers do not wait forever')
            with self._cv:
                self._error = e
                self._completed = True
                self._cv.notify_all()

    def _run_inner(self):
        while True:
            with self._cv:
                if self._stop_requested:
                    break
                if self._iterations_remaining is not None and self._iterations_remaining <= 0:
                    self._completed = True
                    break
            order = self._epoch_order(self._epoch)
            if self._exclude_always:
                order = [i for i in order if i not in self._exclude_always]
            if self._exclude_once:
                order = [i for i in order if i not in self._exclude_once]
                self._exclude_once = frozenset()
            while self._cursor < len(order):
                with self._cv:
                    while (self._in_flight >= self._current_max_queue_size()
                           and not self._stop_requested):
                        self._cv.wait(_VENTILATION_INTERVAL_S)
                    if self._stop_requested:
                        return
                    # in_flight must rise BEFORE the item reaches the pool:
                    # a worker's processed_item() decrement may otherwise
                    # precede the increment and be lost to the >=0 clamp.
                    self._in_flight += 1
                    item_index = order[self._cursor]
                # 'ventilate' stage = time HANDING items to the pool
                # (serialization, dispatcher submit); the bounded wait
                # above is back-pressure by design, not stage work
                item = self._items[item_index]
                ctx = tracing.mint(item.get('item_index', item_index),
                                   epoch=self._epoch,
                                   shard=self._trace_shard)
                if ctx is not None and self._carries_trace_ctx:
                    item = dict(item)
                    item[tracing.TRACE_CTX_KEY] = ctx
                with tracing.activate(ctx, track='ventilator'):
                    with span('ventilate'):
                        if self._pass_epoch:
                            self._ventilate_fn(epoch=self._epoch, **item)
                        else:
                            self._ventilate_fn(**item)
                # The cursor advances only after the item was handed to the
                # pool, so a state_dict() snapshot can never skip an item that
                # was not ventilated (at-least-once resume semantics).
                with self._cv:
                    self._cursor += 1
            with self._cv:
                self._epoch += 1
                self._cursor = 0
                if self._iterations_remaining is not None:
                    self._iterations_remaining -= 1
        with self._cv:
            self._cv.notify_all()
