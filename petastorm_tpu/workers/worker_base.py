"""Worker contract (reference: ``petastorm/workers_pool/worker_base.py:18-35``)."""

from abc import ABCMeta, abstractmethod


class WorkerBase(metaclass=ABCMeta):
    """A worker processes ventilated items and publishes results.

    Subclasses implement :meth:`process`; the pool calls it once per
    ventilated item with the item's args/kwargs. Results are emitted by
    calling ``self.publish_func(data)`` any number of times per item.
    """

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def initialize(self):
        """Called once on the worker's thread/process before any item."""

    def shutdown(self):
        """Called once when the pool stops."""

    @abstractmethod
    def process(self, *args, **kwargs):
        """Process a single ventilated work item."""
