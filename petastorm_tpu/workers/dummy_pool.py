"""Synchronous in-caller-thread pool for debugging and profiling.

Re-design of ``petastorm/workers_pool/dummy_pool.py:20-91``: all work runs
lazily on the caller's thread inside ``get_results`` so profilers and
debuggers see the full pipeline.
"""

import time
from collections import deque

from petastorm_tpu.telemetry import tracing
from petastorm_tpu.workers import EmptyResultError, VentilatedItemProcessedMessage


class DummyPool:
    def __init__(self, workers_count=1, results_queue_size=None):
        self._worker = None
        self._ventilator = None
        self._work_items = deque()
        self._results = deque()
        self._ventilated_items = 0
        self._processed_items = 0

    @property
    def workers_count(self):
        return 1

    def start(self, worker_class, worker_args=None, ventilator=None,
              start_ventilator=True):
        if self._worker is not None:
            raise RuntimeError('DummyPool already started')
        self._worker = worker_class(0, self._results.append, worker_args)
        self._worker.initialize()
        self._ventilator = ventilator
        if ventilator is not None and start_ventilator:
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        self._ventilated_items += 1
        self._work_items.append((args, kwargs))

    def get_results(self, timeout=None):
        while True:
            if self._results:
                result = self._results.popleft()
                if isinstance(result, VentilatedItemProcessedMessage):
                    continue
                if isinstance(result, Exception):
                    raise result
                return result
            if not self._work_items:
                if self._ventilator is None or self._ventilator.completed():
                    raise EmptyResultError()
                # The ventilator thread may still be pushing items.
                time.sleep(0.001)
                continue
            args, kwargs = self._work_items.popleft()
            ctx = kwargs.pop(tracing.TRACE_CTX_KEY, None)
            try:
                with tracing.attempt(ctx, 'dummy-0'):
                    self._worker.process(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - surfaced to the consumer
                self._processed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                raise e
            self._processed_items += 1
            if self._ventilator is not None:
                self._ventilator.processed_item()

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()

    def join(self):
        if self._worker is not None:
            self._worker.shutdown()
            # workers_alive must read 0 after join, like the other pools
            self._worker = None

    @property
    def diagnostics(self):
        return {'pending_work_items': len(self._work_items),
                'pending_results': len(self._results),
                # SHARED_POOL_GAUGES (work runs lazily on the caller's
                # thread, so "in flight" is exactly the undrained backlog)
                'items_ventilated': self._ventilated_items,
                'items_processed': self._processed_items,
                'items_inflight': len(self._work_items),
                'output_queue_size': len(self._results),
                'workers_alive': 1 if self._worker is not None else 0}

    @property
    def results_qsize(self):
        return len(self._results)
