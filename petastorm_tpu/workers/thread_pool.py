"""Thread worker pool (re-design of ``petastorm/workers_pool/thread_pool.py``).

The default executor on a TPU VM host: pyarrow parquet reads, zlib, and cv2
image decode all release the GIL, so threads scale across the host's cores
without process-spawn or serialization overhead.
"""

import logging
import queue
import threading
import time
from cProfile import Profile
from pstats import Stats

from petastorm_tpu.telemetry import (
    STALL_NOTE_FLOOR_S, note_producer_wait, tracing,
)
from petastorm_tpu.workers import (
    EmptyResultError, TimeoutWaitingForResultError, VentilatedItemProcessedMessage,
)

logger = logging.getLogger(__name__)

_RESULTS_QUEUE_SIZE_DEFAULT = 50
_POLL_INTERVAL_S = 0.05


class _WorkerExit(Exception):
    """Internal signal: the pool is stopping."""


class ThreadPool:
    """N daemon worker threads over stdlib queues.

    Contract (shared with ProcessPool/DummyPool): ``start`` → ``ventilate``\\*
    → ``get_results``\\* → ``stop`` → ``join``. Worker exceptions are
    forwarded through the results queue and re-raised in the consumer
    (reference: ``thread_pool.py:68-75``).
    """

    def __init__(self, workers_count, results_queue_size=_RESULTS_QUEUE_SIZE_DEFAULT,
                 profiling_enabled=False):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._work_queue = queue.Queue()
        self._stop_event = threading.Event()
        self._threads = []
        self._workers = []
        self._ventilator = None
        self._ventilated_items = 0
        self._processed_items = 0
        self._counter_lock = threading.Lock()
        self._profiling_enabled = profiling_enabled
        self._profiles = []
        self._error = None

    @property
    def workers_count(self):
        return self._workers_count

    # -- lifecycle ----------------------------------------------------------

    def start(self, worker_class, worker_args=None, ventilator=None,
              start_ventilator=True):
        if self._threads:
            raise RuntimeError('ThreadPool already started')
        for worker_id in range(self._workers_count):
            worker = worker_class(worker_id, self._publish, worker_args)
            self._workers.append(worker)
            thread = threading.Thread(target=self._worker_loop, args=(worker,),
                                      daemon=True)
            thread.start()
            self._threads.append(thread)
        self._ventilator = ventilator
        if ventilator is not None and start_ventilator:
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        with self._counter_lock:
            self._ventilated_items += 1
        self._work_queue.put((args, kwargs))

    def get_results(self, timeout=None):
        """Next result, blocking; raises :class:`EmptyResultError` at the end.

        End-of-data is: results queue drained ∧ all ventilated items processed
        ∧ ventilator (if any) has completed (reference: ``thread_pool.py:157-160``).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._error is not None:
                # A worker error is terminal: every subsequent read re-raises
                # it instead of hanging on counters that will never reconcile.
                raise self._error
            try:
                result = self._results_queue.get(timeout=_POLL_INTERVAL_S)
            except queue.Empty:
                if self._stop_event.is_set():
                    # After stop() in-flight counters can never reconcile;
                    # a drained queue means no result will ever arrive.
                    raise EmptyResultError()
                with self._counter_lock:
                    all_done = (self._ventilated_items == self._processed_items)
                if all_done and (self._ventilator is None or self._ventilator.completed()):
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            if isinstance(result, VentilatedItemProcessedMessage):
                with self._counter_lock:
                    self._processed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if isinstance(result, Exception):
                self._error = result
                self.stop()
                self.join()
                raise result
            return result

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('Must call stop() before join()')
        for thread in self._threads:
            thread.join()
        self._threads = []
        for worker in self._workers:
            worker.shutdown()
        if self._profiling_enabled and self._profiles:
            # a worker that never got an item has an EMPTY profile, and
            # pstats refuses to construct from one — merge only non-empty
            stats = None
            for p in self._profiles:
                p.create_stats()
                if not p.stats:
                    continue
                if stats is None:
                    stats = Stats(p)
                else:
                    stats.add(p)
            if stats is not None:
                stats.sort_stats('cumulative').print_stats()

    @property
    def diagnostics(self):
        with self._counter_lock:
            ventilated = self._ventilated_items
            processed = self._processed_items
        return {
            'output_queue_size': self._results_queue.qsize(),
            'items_ventilated': ventilated,
            'items_processed': processed,
            # gauge names shared with ProcessPool/ServicePool so dashboards
            # and autotune advice read identically across pool flavors
            'items_inflight': ventilated - processed,
            'workers_alive': sum(1 for t in self._threads if t.is_alive()),
        }

    @property
    def results_qsize(self):
        return self._results_queue.qsize()

    # -- internals ----------------------------------------------------------

    def _publish(self, data):
        """Stop-aware put: never deadlocks a worker against a full results
        queue during shutdown (reference: ``thread_pool.py:200-214``).

        Time blocked against a full queue is back-pressure from a slow
        consumer — it feeds stall attribution as producer wait
        (= consumer-bound evidence)."""
        start = time.monotonic()
        try:
            while not self._stop_event.is_set():
                try:
                    self._results_queue.put(data, timeout=_POLL_INTERVAL_S)
                    return
                except queue.Full:
                    continue
            raise _WorkerExit()
        finally:
            blocked = time.monotonic() - start
            if blocked > STALL_NOTE_FLOOR_S:
                note_producer_wait(blocked)

    def _worker_loop(self, worker):
        profiler = Profile() if self._profiling_enabled else None
        if profiler:
            self._profiles.append(profiler)
        try:
            worker.initialize()
            while not self._stop_event.is_set():
                try:
                    args, kwargs = self._work_queue.get(timeout=_POLL_INTERVAL_S)
                except queue.Empty:
                    continue
                # traced items carry their context as a reserved kwarg;
                # strip it and make it the thread's active trace so the
                # worker's stage spans land on the item's timeline
                ctx = kwargs.pop(tracing.TRACE_CTX_KEY, None)
                try:
                    if profiler:
                        profiler.enable()
                    with tracing.attempt(ctx, 'thread-%d'
                                         % worker.worker_id):
                        worker.process(*args, **kwargs)
                    if profiler:
                        profiler.disable()
                    self._publish(VentilatedItemProcessedMessage())
                except _WorkerExit:
                    return
                except Exception as e:  # noqa: BLE001 - forwarded to consumer
                    if profiler:
                        profiler.disable()
                    logger.debug('Worker %d forwarding exception', worker.worker_id,
                                 exc_info=True)
                    try:
                        self._publish(e)
                        # Keep ventilated/processed counters consistent so the
                        # ventilator's in-flight accounting cannot wedge.
                        self._publish(VentilatedItemProcessedMessage())
                    except _WorkerExit:
                        return
        except _WorkerExit:
            pass
