"""Spawned-process worker pool over ZeroMQ.

Re-design of ``petastorm/workers_pool/process_pool.py`` (protocol diagram at
``:52-74``) for a TPU host. Topology is the same three-socket pattern —

    main:   PUSH work ──► worker PULL
    main:   PUB control ─► worker SUB          (stop broadcast)
    worker: PUSH results ► main PULL

— with these differences:

* **ipc:// endpoints** (unix domain sockets in a private temp dir) instead of
  random localhost TCP ports: no port collisions, lower latency, and no
  loopback TCP stack on the data path.
* Workers are spawned (never forked) via
  :func:`~petastorm_tpu.workers.exec_in_new_process.exec_in_new_process` and
  pinned to ``JAX_PLATFORMS=cpu`` so they can never grab the trainer's TPU.
* Results ride a pluggable :mod:`~petastorm_tpu.serializers` codec via its
  multipart frame API — the default :class:`PickleSerializer` ships every
  ndarray payload as its own pickle-5 out-of-band ZMQ frame, and the
  consumer receives with ``copy=False`` so deserialization is zero-copy
  (arrays view the wire buffers); back-pressure = ZMQ high-water marks
  sized from ``results_queue_size``.
* Same failure model as the reference: worker exceptions are serialized onto
  the results channel and re-raised in the consumer; an orphan-monitor thread
  in each worker exits when the main process dies (``process_pool.py:320-327``);
  all workers must check in within a startup timeout (``process_pool.py:38-39``);
  the stop broadcast repeats until every (possibly slow-joining) worker exits
  (``process_pool.py:289-294``).
"""

import logging
import os
import shutil
import tempfile
import threading
import time

import dill

from petastorm_tpu import sanitizer
from petastorm_tpu.serializers import PickleSerializer
from petastorm_tpu.telemetry import (
    STALL_NOTE_FLOOR_S, dump_delta_frame, load_delta_frame,
    merge_worker_delta, note_producer_wait, tracing,
)
from petastorm_tpu.workers import (
    EmptyResultError, TimeoutWaitingForResultError, WorkerTerminationRequested,
)

logger = logging.getLogger(__name__)

_STARTUP_TIMEOUT_S = 20
_POLL_INTERVAL_MS = 50
_JOIN_TIMEOUT_S = 10

# Wire message types (first frame).
_MSG_READY = b'R'
_MSG_RESULT = b'D'
_MSG_MARKER = b'M'
_MSG_ERROR = b'E'
_MSG_EXIT = b'X'
_CTRL_STOP = b'stop'


class ProcessPool:
    """N spawned decode processes; contract identical to :class:`ThreadPool`."""

    def __init__(self, workers_count, results_queue_size=50, serializer=None):
        self._workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._processes = []
        self._ventilator = None
        self._ventilated_items = 0
        self._processed_items = 0
        self._error = None
        self._stop_event = threading.Event()
        self._ipc_dir = None
        self._context = None
        self._work_socket = None
        self._control_socket = None
        self._results_socket = None

    @property
    def workers_count(self):
        return self._workers_count

    # -- lifecycle ----------------------------------------------------------

    def start(self, worker_class, worker_args=None, ventilator=None,
              start_ventilator=True):
        import zmq
        if self._processes:
            raise RuntimeError('ProcessPool already started')
        self._context = zmq.Context()
        self._ipc_dir = tempfile.mkdtemp(prefix='petastorm_tpu_pool_')
        work_ep = 'ipc://%s/work' % self._ipc_dir
        control_ep = 'ipc://%s/control' % self._ipc_dir
        results_ep = 'ipc://%s/results' % self._ipc_dir

        self._work_socket = self._context.socket(zmq.PUSH)
        self._work_socket.set_hwm(0)  # work items are tiny dicts; never block
        self._work_socket.bind(work_ep)
        self._control_socket = self._context.socket(zmq.PUB)
        self._control_socket.bind(control_ep)
        # ZMQ high-water marks are PER CONNECTION; split the global results
        # bound across the send and receive sides of every worker connection
        # so total buffering stays ≈ results_queue_size items, matching the
        # ThreadPool's single shared bounded queue.
        per_worker_hwm = max(1, self._results_queue_size
                             // (2 * self._workers_count))
        self._per_worker_hwm = per_worker_hwm
        self._results_socket = self._context.socket(zmq.PULL)
        self._results_socket.set_hwm(per_worker_hwm)
        self._results_socket.bind(results_ep)

        from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
        payload = dill.dumps((worker_class, worker_args))
        for worker_id in range(self._workers_count):
            proc = exec_in_new_process(
                _worker_bootstrap, worker_id, os.getpid(), work_ep, control_ep,
                results_ep, payload, dill.dumps(self._serializer),
                per_worker_hwm)
            self._processes.append(proc)

        self._await_checkins()
        self._ventilator = ventilator
        if ventilator is not None and start_ventilator:
            ventilator.start()

    def _await_checkins(self):
        import zmq
        poller = zmq.Poller()
        poller.register(self._results_socket, zmq.POLLIN)
        checked_in = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while checked_in < self._workers_count:
            dead = self._dead_workers()
            if dead:
                self._abort_startup()
                raise RuntimeError(
                    'Pool worker process(es) %s died during startup — see '
                    'their stderr for the traceback' % dead)
            if time.monotonic() > deadline:
                self._abort_startup()
                raise RuntimeError(
                    'Only %d of %d pool workers checked in within %ds'
                    % (checked_in, self._workers_count, _STARTUP_TIMEOUT_S))
            if poller.poll(_POLL_INTERVAL_MS):
                frames = self._results_socket.recv_multipart()
                if frames[0] == _MSG_READY:
                    checked_in += 1
                # anything else this early is impossible; drop it

    def _abort_startup(self):
        """Failure during start(): reap children and release every resource
        (join() is unusable here — stop() was never called)."""
        self._terminate_all()
        for p in self._processes:
            try:
                p.wait(timeout=2)
            except Exception:  # noqa: BLE001
                p.kill()
                p.wait()
        self._processes = []
        self._close_sockets()

    def ventilate(self, *args, **kwargs):
        self._ventilated_items += 1
        self._work_socket.send(dill.dumps((args, kwargs)))

    def get_results(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._error is not None:
                raise self._error
            if not self._results_socket.poll(_POLL_INTERVAL_MS):
                if self._stop_event.is_set():
                    raise EmptyResultError()
                if (self._ventilated_items == self._processed_items
                        and (self._ventilator is None or self._ventilator.completed())):
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                if self._dead_workers() and self._error is None:
                    self._error = RuntimeError(
                        'Pool worker process(es) died unexpectedly: %s'
                        % self._dead_workers())
                continue
            # copy=False: frames stay in ZMQ's receive buffers, exposed as
            # zero-copy memoryviews — what lets the pickle-5 out-of-band
            # result path rebuild ndarrays as views over the wire buffers
            # (no host copy between the socket and the consumer's arrays)
            frames = self._results_socket.recv_multipart(copy=False)
            kind = frames[0].bytes
            if kind == _MSG_MARKER:
                self._processed_items += 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                # markers piggyback the worker's metric delta (io/decode/
                # transform spans, cache counters, producer-wait clock):
                # fold it into THIS process's registry + stall attributor
                if len(frames) > 1:
                    merge_worker_delta(load_delta_frame(frames[1].bytes))
                continue
            if kind == _MSG_ERROR:
                self._error = dill.loads(frames[1].bytes)
                self.stop()
                self.join()
                raise self._error
            if kind == _MSG_RESULT:
                payload = [f.buffer for f in frames[1:]]
                if sanitizer.sanitize_enabled():
                    # read-only memoryviews over the receive buffers:
                    # arrays pickle-5 rebuilds over them come out
                    # writeable=False, so a consumer's in-place write
                    # raises instead of corrupting ZMQ's buffers
                    payload = [b.toreadonly() for b in payload]
                return self._serializer.deserialize_frames(payload)
            if kind in (_MSG_READY, _MSG_EXIT):
                continue
            logger.warning('Unknown pool message type %r', kind)

    def _dead_workers(self):
        return [p.pid for p in self._processes
                if p.poll() is not None and p.returncode != 0]

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        if not self._stop_event.is_set():
            raise RuntimeError('Must call stop() before join()')
        if not self._processes:
            return
        # Slow-joiner-tolerant stop: a worker that connected its SUB socket
        # after our first broadcast would otherwise never hear it.
        deadline = time.monotonic() + _JOIN_TIMEOUT_S
        while any(p.poll() is None for p in self._processes):
            try:
                self._control_socket.send(_CTRL_STOP)
            except Exception:  # noqa: BLE001 - socket may already be closed
                break
            if time.monotonic() > deadline:
                logger.warning('Terminating pool workers that ignored stop')
                self._terminate_all()
                break
            time.sleep(_POLL_INTERVAL_MS / 1000.0)
        for p in self._processes:
            try:
                p.wait(timeout=_JOIN_TIMEOUT_S)
            except Exception:  # noqa: BLE001
                p.kill()
        self._processes = []
        self._close_sockets()

    def _terminate_all(self):
        for p in self._processes:
            if p.poll() is None:
                p.terminate()

    def _close_sockets(self):
        for sock in (self._work_socket, self._control_socket, self._results_socket):
            if sock is not None:
                sock.close(linger=0)
        self._work_socket = self._control_socket = self._results_socket = None
        if self._context is not None:
            self._context.term()
            self._context = None
        if self._ipc_dir:
            shutil.rmtree(self._ipc_dir, ignore_errors=True)
            self._ipc_dir = None

    @property
    def diagnostics(self):
        # Counters are mutated by two different threads (ventilator /
        # consumer); snapshot into locals and clamp so a torn read can
        # never report a negative in-flight gauge.
        ventilated = self._ventilated_items
        processed = self._processed_items
        return {
            'items_ventilated': ventilated,
            'items_processed': processed,
            'items_inflight': max(0, ventilated - processed),
            'workers_alive': sum(1 for p in self._processes if p.poll() is None),
            # SHARED_POOL_GAUGES parity: results buffer in ZMQ (per-socket
            # HWM), not a host-side queue this process can measure — 0 is
            # the honest depth of the (nonexistent) consumer-side queue
            'output_queue_size': 0,
        }


def _worker_bootstrap(worker_id, main_pid, work_ep, control_ep, results_ep,
                      class_payload, serializer_payload, results_hwm):
    """Entry point of a spawned decode process."""
    import zmq
    import psutil

    def _orphan_monitor():
        while True:
            if not psutil.pid_exists(main_pid):
                os._exit(0)
            time.sleep(1.0)

    threading.Thread(target=_orphan_monitor, daemon=True).start()

    worker_class, worker_args = dill.loads(class_payload)
    serializer = dill.loads(serializer_payload)

    context = zmq.Context()
    work = context.socket(zmq.PULL)
    work.connect(work_ep)
    control = context.socket(zmq.SUB)
    control.setsockopt(zmq.SUBSCRIBE, b'')
    control.connect(control_ep)
    results = context.socket(zmq.PUSH)
    results.set_hwm(max(1, results_hwm))
    results.connect(results_ep)

    def send_or_stop(frames):
        """Stop-aware send (mirrors ThreadPool._publish): a worker parked on
        a full results channel must still hear the stop broadcast, or every
        mid-stream shutdown would end in SIGTERM with no clean shutdown().

        Time blocked against the channel's HWM is back-pressure from a
        slow consumer; it lands in this worker's registry (producer-wait
        counter) and reaches the consumer with the next marker's delta."""
        start = time.monotonic()
        try:
            while True:
                if results.poll(_POLL_INTERVAL_MS, zmq.POLLOUT):
                    results.send_multipart(frames)
                    return
                if control.poll(0) and control.recv() == _CTRL_STOP:
                    raise WorkerTerminationRequested()
        finally:
            blocked = time.monotonic() - start
            if blocked > STALL_NOTE_FLOOR_S:
                note_producer_wait(blocked)

    def publish(value):
        # multipart result: frame 0 the pickle-5 stream, every ndarray
        # payload its own out-of-band frame (serializers.py) — ZMQ sends
        # straight from the exported buffers, one memcpy per array
        send_or_stop([_MSG_RESULT] + list(serializer.serialize_frames(value)))

    worker = worker_class(worker_id, publish, worker_args)
    worker.initialize()
    results.send_multipart([_MSG_READY, b''])

    poller = zmq.Poller()
    poller.register(work, zmq.POLLIN)
    poller.register(control, zmq.POLLIN)
    try:
        while True:
            events = dict(poller.poll())
            if control in events:
                if control.recv() == _CTRL_STOP:
                    break
            if work in events:
                args, kwargs = dill.loads(work.recv())
                # traced items carry their context over the work channel;
                # activate it here so this PROCESS's stage spans + attempt
                # event land on the item's timeline (they ship back with
                # the marker's delta frame below)
                ctx = kwargs.pop(tracing.TRACE_CTX_KEY, None)
                try:
                    with tracing.attempt(ctx, 'process-%d' % worker_id):
                        worker.process(*args, **kwargs)
                    # marker piggybacks this worker's metric delta (one
                    # shared framing with the service's DONE piggyback)
                    send_or_stop([_MSG_MARKER, dump_delta_frame()])
                except WorkerTerminationRequested:
                    break
                except Exception as e:  # noqa: BLE001 - forwarded to consumer
                    try:
                        err_payload = dill.dumps(e)
                    except Exception:  # noqa: BLE001 - unpicklable exception
                        err_payload = dill.dumps(
                            RuntimeError('%s: %s' % (type(e).__name__, e)))
                    try:
                        send_or_stop([_MSG_ERROR, err_payload])
                        send_or_stop([_MSG_MARKER, dump_delta_frame()])
                    except WorkerTerminationRequested:
                        break
    finally:
        try:
            worker.shutdown()
        except Exception:  # noqa: BLE001 - best-effort shutdown
            pass
        try:
            results.send_multipart([_MSG_EXIT, b''], flags=zmq.NOBLOCK)
        except Exception:  # noqa: BLE001 - channel may be full/closed
            pass
        for sock in (work, control, results):
            sock.close(linger=1000)
        context.term()
        os._exit(0)
