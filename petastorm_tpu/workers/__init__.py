"""Worker-pool runtime: the framework's intra-host scheduler/executor.

Re-design of ``petastorm/workers_pool/`` (SURVEY.md §2.2). The pool contract is
identical — ``start(worker_class, worker_args, ventilator) / ventilate /
get_results / stop / join`` — but the implementations are written for a TPU VM
host: thread workers by default (pyarrow + cv2 release the GIL on the hot
path), a spawned-process ZMQ pool for GIL-heavy user transforms, and a
synchronous dummy pool for debugging/profiling.
"""


#: gauge names EVERY pool flavor (thread/process/dummy/service) must expose
#: through ``diagnostics``, so dashboards and autotune advice read the same
#: keys wherever decode runs; enforced by
#: ``tests/test_telemetry_pools.py::test_pool_gauge_name_parity``. Pools may
#: add flavor-specific extras on top, never rename these.
SHARED_POOL_GAUGES = frozenset([
    'items_ventilated', 'items_processed', 'items_inflight',
    'workers_alive', 'output_queue_size',
])


class EmptyResultError(Exception):
    """Raised by ``get_results`` when all ventilated work is done
    (reference: ``workers_pool/__init__.py:16``)."""


class TimeoutWaitingForResultError(Exception):
    """Raised when a result did not arrive within the poll timeout."""


class VentilatedItemProcessedMessage:
    """Control message a worker publishes after finishing one work item
    (reference: ``workers_pool/__init__.py:25``)."""


class WorkerTerminationRequested(Exception):
    """Raised inside a worker to abort processing during shutdown."""
