"""Spawn (never fork) a Python function in a fresh interpreter.

Forking a process that holds JVM/libhdfs/XLA runtime state is unsafe; the
reference hit the same problem (``workers_pool/exec_in_new_process.py:26-48``)
and solved it the same way: dill-serialize ``(func, args, kwargs)`` to a temp
file and ``Popen`` a clean ``python -m`` bootstrap that loads and runs it.
On a TPU VM this also guarantees workers never inherit a TPU client handle.
"""

import os
import subprocess
import sys
import tempfile

import dill


def exec_in_new_process(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a brand-new Python process.

    :return: the :class:`subprocess.Popen` handle.
    """
    fd, payload_path = tempfile.mkstemp(prefix='petastorm_tpu_spawn_',
                                        suffix='.dill')
    with os.fdopen(fd, 'wb') as f:
        dill.dump((func, args, kwargs), f)
    env = dict(os.environ)
    # Decode workers must never grab the TPU chip the trainer owns — force
    # CPU even when the parent exported JAX_PLATFORMS=tpu.
    env['JAX_PLATFORMS'] = 'cpu'
    # The fresh interpreter must be able to import this package (and the
    # caller's modules, e.g. user worker classes) even when the parent got
    # them via sys.path manipulation rather than an installed distribution.
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    extra_paths = [p for p in [pkg_root] + sys.path if p]
    existing = env.get('PYTHONPATH')
    if existing:
        extra_paths.append(existing)
    seen = set()
    deduped = [p for p in extra_paths if not (p in seen or seen.add(p))]
    env['PYTHONPATH'] = os.pathsep.join(deduped)
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.workers.exec_in_new_process',
         payload_path],
        env=env)


def _main():
    payload_path = sys.argv[1]
    with open(payload_path, 'rb') as f:
        func, args, kwargs = dill.load(f)
    os.unlink(payload_path)
    func(*args, **kwargs)


if __name__ == '__main__':
    _main()
