"""Fleet-ETL writer: sharded encode+write over any worker pool.

:class:`DistributedDatasetWriter` shards row batches across the uniform
pool contract (``start/ventilate/get_results/stop`` — thread, process,
or the PR 13/16 service fleet, which brings job registration, QoS
weights, retries and chaos faultpoints for free), with the
single-process :class:`~petastorm_tpu.etl.dataset_metadata.DatasetWriter`
as the degenerate local backend: pass ``pool=None`` and shards run
inline through the *same* :class:`WriteShardWorker` code path, so
local and fleet writes are byte-equivalent.

Exactly-once publication (the crash-safety contract):

1. a shard worker writes its part files under invisible ``.tmp.`` names
   (discovery skips dotted segments), then renames each into a
   **deterministic** final name ``part-g<gen>-s<shard>-<seq>.parquet``;
2. a SIGKILLed / faulted attempt leaves only tmp litter — the pool
   re-ventilates the shard and the retry republishes byte-identical
   files onto the same names (rename-over-rename is a safe replace);
3. the coordinator commits by swapping ``_manifest.json``
   (:mod:`petastorm_tpu.write.manifest`) *after* the metadata footer —
   readers either see the previous generation or the complete new one,
   never a torn mix.
"""

import json
import logging
import posixpath

import pyarrow.parquet as pq

from petastorm_tpu import faults
from petastorm_tpu.etl.dataset_metadata import (
    LEGACY_ROW_GROUPS_PER_FILE_KEY, LEGACY_UNISCHEMA_KEY,
    ROW_GROUPS_PER_FILE_KEY, UNISCHEMA_KEY, DatasetWriter,
    ParquetDatasetInfo, update_dataset_metadata,
)
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.telemetry import (
    get_registry, knobs, metrics_disabled, tracing,
)
from petastorm_tpu.unischema import Unischema
from petastorm_tpu.workers.worker_base import WorkerBase
from petastorm_tpu.write import layout, manifest
from petastorm_tpu.write.manifest import TMP_PREFIX

logger = logging.getLogger(__name__)

WRITE_ROWS = 'petastorm_tpu_write_rows_total'
WRITE_BYTES = 'petastorm_tpu_write_bytes_total'
WRITE_FILES = 'petastorm_tpu_write_files_total'

_MB = 1024 * 1024


def _default_shard_rows():
    return knobs.get_int('PETASTORM_TPU_WRITE_SHARD_ROWS', 4096, floor=1)


def _default_encode_workers():
    return knobs.get_int('PETASTORM_TPU_WRITE_WORKERS', 0, floor=0)


class WriteShardWorker(WorkerBase):
    """Writes ONE ventilated shard of rows as tmp part files and renames
    them into their deterministic final names.

    ``worker_args``: ``{'dataset_url', 'schema_json', 'generation',
    'rowgroup_size_rows', 'rowgroup_size_mb', 'compression', 'sort_by',
    'encode_workers', 'storage_options'}`` — everything picklable, so
    the same spec ships to thread, process and service-fleet workers.
    Publishes ``{'shard': id, 'entries': [manifest file entries]}``.
    """

    def initialize(self):
        self._schema = Unischema.from_json_dict(self.args['schema_json'])
        self.fs, self.root_path = get_filesystem_and_path_or_paths(
            self.args['dataset_url'], self.args.get('storage_options'))

    def process(self, shard_id, rows):
        a = self.args
        final_prefix = 'part-g%04d-s%05d' % (a['generation'], shard_id)
        if faults.ARMED:
            faults.fault_hit('io.write', key='%s/%s#part'
                             % (self.root_path, final_prefix))
        writer = DatasetWriter(
            a['dataset_url'], self._schema,
            rowgroup_size_rows=a['rowgroup_size_rows'],
            rowgroup_size_mb=a['rowgroup_size_mb'],
            compression=a['compression'],
            file_prefix=TMP_PREFIX + final_prefix,
            sort_by=a['sort_by'],
            workers_count=a['encode_workers'],
            storage_options=a.get('storage_options'))
        try:
            writer.write_row_dicts(rows)
            writer.close()
        except BaseException:
            writer.abort()
            raise
        entries = []
        total_rows = 0
        total_bytes = 0
        for tmp_path in writer.paths_written:
            directory, tmp_name = posixpath.split(tmp_path)
            assert tmp_name.startswith(TMP_PREFIX), tmp_name
            final_path = posixpath.join(directory, tmp_name[len(TMP_PREFIX):])
            if faults.ARMED:
                faults.fault_hit('io.write', key='%s#rename' % final_path)
            self._publish_part(tmp_path, final_path)
            with self.fs.open(final_path, 'rb') as f:
                meta = pq.read_metadata(f)
            nbytes = int(self.fs.info(final_path)['size'])
            rel = posixpath.relpath(final_path, self.root_path.rstrip('/'))
            entries.append(manifest.file_entry(
                rel, meta.num_rows, meta.num_row_groups, nbytes,
                source='write'))
            total_rows += int(meta.num_rows)
            total_bytes += nbytes
        if not metrics_disabled():
            registry = get_registry()
            registry.counter(WRITE_ROWS).inc(total_rows)
            registry.counter(WRITE_BYTES).inc(total_bytes)
            registry.counter(WRITE_FILES).inc(len(entries))
        self.publish_func({'shard': shard_id, 'entries': entries})

    def _publish_part(self, tmp_path, final_path):
        """Rename one tmp part onto its deterministic final name. An
        occupied name is byte-compared: a retry of this shard
        republishes identical bytes (keep the committed copy),
        different bytes mean a CONCURRENT writer took the same
        generation — fail loudly instead of silently replacing another
        commit's data."""
        try:
            occupied = self.fs.exists(final_path)
        except (OSError, ValueError):
            occupied = False
        if not occupied:
            self.fs.mv(tmp_path, final_path)
            return
        with self.fs.open(final_path, 'rb') as f:
            committed_bytes = f.read()
        with self.fs.open(tmp_path, 'rb') as f:
            our_bytes = f.read()
        if committed_bytes != our_bytes:
            raise RuntimeError(
                'write: part name collision at %r — a concurrent writer '
                'committed different bytes under this generation\'s '
                'deterministic name; re-open the writer (append=True) to '
                'take a fresh generation' % final_path)
        # byte-identical retry leftover: the committed copy stands
        self.fs.rm(tmp_path)


class DistributedDatasetWriter:
    """Distributed (or degenerate-local) dataset writer with manifest
    commit. Usage::

        with DistributedDatasetWriter(url, schema, pool=ServicePool(...),
                                      sort_by='id') as w:
            w.write_row_dicts(rows)
        # exit publishes: part files, metadata footer, manifest commit

    ``pool=None`` runs every shard inline through the same
    :class:`WriteShardWorker` (the local backend); any object honoring
    the pool contract distributes them. The pool must be constructed but
    NOT started — this writer owns its start/stop lifecycle.

    ``append=True`` stacks a new manifest generation on top of the
    committed one (rows become visible to bounded-staleness readers at
    the commit); ``append=False`` requires a manifest-free target.
    Hive partitioning stays a :class:`DatasetWriter`-only feature — the
    deterministic shard naming the exactly-once contract rests on does
    not compose with row-value-dependent directories.
    """

    def __init__(self, dataset_url, schema, pool=None, shard_rows=None,
                 rowgroup_size_rows=100000, rowgroup_size_mb=None,
                 compression='auto', sort_by=None, append=False,
                 storage_options=None):
        self.schema = schema
        self.sort_by = sort_by
        self._url = normalize_dir_url(dataset_url)
        self._storage_options = storage_options
        self.fs, self.root_path = get_filesystem_and_path_or_paths(
            self._url, storage_options)
        self.fs.makedirs(self.root_path, exist_ok=True)
        committed = manifest.load(self.fs, self.root_path)
        if committed is not None and not append:
            raise ValueError(
                'Dataset %r already carries a committed manifest '
                '(generation %d); pass append=True to stack a new '
                'generation' % (dataset_url, committed['generation']))
        self._append = bool(append)
        self._base_entries = list(committed['files']) if committed else []
        self.generation = (committed['generation'] if committed else 0) + 1
        if committed and sort_by is None:
            self.sort_by = committed.get('sort_key')
        self._pool = pool
        self._pool_started = False
        self._shard_rows = shard_rows or _default_shard_rows()
        if rowgroup_size_mb is None:
            rowgroup_size_mb = max(1, layout.target_rowgroup_bytes() // _MB)
        self._worker_args = {
            'dataset_url': self._url,
            'schema_json': schema.to_json_dict(),
            'generation': self.generation,
            'rowgroup_size_rows': rowgroup_size_rows,
            'rowgroup_size_mb': rowgroup_size_mb,
            'compression': compression,
            'sort_by': self.sort_by,
            'encode_workers': _default_encode_workers(),
            'storage_options': storage_options,
        }
        self._buffer = []
        self._shards_dispatched = 0
        self._inline_results = []
        self._inline_worker = None
        self.manifest = None  #: the committed manifest, set by close()
        self.last_self_check = None

    # -- dispatch ----------------------------------------------------------

    def write_row_dict(self, row_dict):
        self._buffer.append(row_dict)
        if len(self._buffer) >= self._shard_rows:
            self._dispatch_shard()

    def write_row_dicts(self, row_dicts):
        for row in row_dicts:
            self.write_row_dict(row)

    def _dispatch_shard(self):
        rows, self._buffer = self._buffer, []
        if not rows:
            return
        shard_id = self._shards_dispatched
        self._shards_dispatched += 1
        # each shard is one traced item: the context rides the pools'
        # reserved _trace_ctx kwarg exactly like read-plane row-groups,
        # so encode/write_flush spans on remote workers join the same
        # timeline the read plane records (PR 19: the write plane no
        # longer drops trace contexts on the floor)
        ctx = tracing.mint(shard_id, epoch=self.generation, shard=shard_id)
        if self._pool is None:
            if self._inline_worker is None:
                self._inline_worker = WriteShardWorker(
                    0, self._inline_results.append, self._worker_args)
                self._inline_worker.initialize()
            with tracing.attempt(ctx, 'write-inline-0'):
                self._inline_worker.process(shard_id, rows)
            return
        if not self._pool_started:
            self._pool.start(WriteShardWorker, self._worker_args)
            self._pool_started = True
        if ctx is not None:
            self._pool.ventilate(shard_id=shard_id, rows=rows,
                                 **{tracing.TRACE_CTX_KEY: ctx})
        else:
            self._pool.ventilate(shard_id=shard_id, rows=rows)

    def _drain_pool(self):
        if self._pool is None:
            return list(self._inline_results)
        results = []
        while len(results) < self._shards_dispatched:
            results.append(self._pool.get_results())
        return results

    # -- commit ------------------------------------------------------------

    def close(self):
        """Flush, drain every shard, write the metadata footer, commit
        the manifest, then (unless ``PETASTORM_TPU_WRITE_SELF_CHECK`` is
        disabled) run the layout self-check on the committed dataset.

        The commit section — rebase onto the latest committed manifest,
        footer, swap — holds the commit lease: an append commit that
        raced this writer (another appender on a different generation, a
        compaction fold) keeps its files, and this commit stacks on top
        instead of silently dropping it."""
        self._dispatch_shard()
        try:
            results = self._drain_pool()
        finally:
            self._stop_pool()
        new_entries = [e for r in results for e in r['entries']]
        if not (self._base_entries or new_entries):
            # zero-row dataset: one empty part keeps the store readable
            with DatasetWriter(self._url, self.schema,
                               file_prefix='part-g%04d-s00000' % self.generation,
                               sort_by=self.sort_by,
                               storage_options=self._storage_options) as w:
                pass
            path = w.paths_written[0]
            rel = posixpath.relpath(path, self.root_path.rstrip('/'))
            with self.fs.open(path, 'rb') as f:
                meta = pq.read_metadata(f)
            new_entries = [manifest.file_entry(
                rel, meta.num_rows, meta.num_row_groups,
                int(self.fs.info(path)['size']), source='write')]
        with manifest.commit_lock(self.fs, self.root_path):
            latest = manifest.load(self.fs, self.root_path)
            if latest is not None:
                if not self._append:
                    raise manifest.ManifestError(
                        'Dataset %r gained a committed manifest (generation '
                        '%d) while this non-append write ran — refusing to '
                        'clobber it' % (self._url, latest['generation']))
                # rebase: commits that landed since __init__ (another
                # generation's appender, a compaction fold) keep their
                # files; ours stack on top
                self._base_entries = list(latest['files'])
                self.generation = latest['generation'] + 1
                if self.sort_by is None:
                    self.sort_by = latest.get('sort_key')
            entries = self._base_entries + new_entries
            built = manifest.build_manifest(entries,
                                            generation=self.generation,
                                            sort_key=self.sort_by)
            self._write_footer(built)
            self.manifest = manifest.publish(self.fs, self.root_path, built,
                                             locked=True)
        manifest.purge_stale_tmp(self.fs, self.root_path)
        if not knobs.is_disabled('PETASTORM_TPU_WRITE_SELF_CHECK'):
            info = ParquetDatasetInfo(self._url, self._storage_options)
            self.last_self_check = layout.self_check(info,
                                                     sort_key=self.sort_by)

    def _write_footer(self, built):
        """Stamp ``_common_metadata`` (schema JSON + row-group counts)
        from the manifest's already-known counts — zero footer re-scans,
        and written BEFORE the manifest swap so a committed generation
        always has its footer. Counts merge over the previously stamped
        map so a reader holding an older generation (whose superseded
        files are still on disk) keeps resolving."""
        from petastorm_tpu.etl.dataset_metadata import (
            _row_group_counts_from_common_metadata,
        )
        info = ParquetDatasetInfo(self._url, self._storage_options,
                                  validate=False)
        # the footer must describe the NEW generation even though the
        # committed manifest (append mode) still lists the previous one
        info.file_paths = sorted(manifest.committed_paths(built,
                                                          self.root_path))
        try:
            previous = _row_group_counts_from_common_metadata(info)
        except (OSError, ValueError):
            previous = None
        counts = manifest.merge_footer_counts(
            self.fs, self.root_path, manifest.row_group_counts(built),
            previous)
        counts_json = json.dumps(counts, sort_keys=True).encode('utf-8')
        entries = {
            ROW_GROUPS_PER_FILE_KEY: counts_json,
            UNISCHEMA_KEY: json.dumps(
                self.schema.to_json_dict()).encode('utf-8'),
        }
        try:
            from petastorm_tpu.etl.legacy import pickle_unischema_for_reference
            entries[LEGACY_UNISCHEMA_KEY] = pickle_unischema_for_reference(
                self.schema)
            entries[LEGACY_ROW_GROUPS_PER_FILE_KEY] = counts_json
        except MetadataError as e:
            logger.debug('Not writing reference-compatible schema pickle: %s',
                         e)
        update_dataset_metadata(info, entries)

    def dump_trace(self, path):
        """Export this process's flight recorder (which the pool delta
        channels already merged remote shard events into) as Chrome
        trace-event JSON — the write-plane sibling of
        ``Reader.dump_trace``. Returns the event count."""
        return tracing.dump_trace(path)

    def _stop_pool(self):
        if self._pool is not None and self._pool_started:
            self._pool_started = False
            self._pool.stop()
            self._pool.join()

    def abort(self):
        """Exception-path teardown: stop the pool and sweep THIS
        generation's litter (tmp files and any already-renamed parts of
        the uncommitted generation). The committed manifest is untouched
        — readers never knew this write happened."""
        self._buffer = []
        try:
            self._stop_pool()
        except Exception:  # noqa: BLE001 - teardown must reach the sweep
            logger.exception('write abort: pool stop failed')
        marker = 'part-g%04d-' % self.generation
        try:
            listing = self.fs.ls(self.root_path, detail=False)
        except (OSError, FileNotFoundError, ValueError):
            return
        for path in listing:
            name = posixpath.basename(path)
            if name == marker or name.startswith(marker) \
                    or name.startswith(TMP_PREFIX + marker):
                try:
                    self.fs.rm(path)
                except (OSError, FileNotFoundError, ValueError):
                    pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is None:
            self.close()
        else:
            self.abort()


def write_dataset_distributed(dataset_url, schema, rows, pool=None,
                              sort_by=None, append=False, shard_rows=None,
                              rowgroup_size_rows=100000,
                              rowgroup_size_mb=None,
                              storage_options=None):
    """One-call distributed materialization; returns the committed
    :class:`DistributedDatasetWriter` (manifest + self-check report)."""
    writer = DistributedDatasetWriter(
        dataset_url, schema, pool=pool, shard_rows=shard_rows,
        rowgroup_size_rows=rowgroup_size_rows,
        rowgroup_size_mb=rowgroup_size_mb, sort_by=sort_by, append=append,
        storage_options=storage_options)
    with writer:
        writer.write_row_dicts(rows)
    return writer
