"""Read-optimized layout: row-group sizing aimed at the readahead
window, and the post-write self-check that proves it.

The write plane's core bet is that layout is a READ-side decision
(Efficient Tabular Data Preprocessing of ML Pipelines, PAPERS.md): a
row-group sized so its column chunks coalesce under the PR 15 readahead
window (``PETASTORM_TPU_READAHEAD_MAX_RANGE_MB`` /
``_GAP_KB``) turns every row-group read into a handful of wire-speed
sequential ranges, and statistics-rich footers (always-on
``write_statistics`` + sorted-column metadata) let PR 12 pushdown prune
instead of scan.

:func:`self_check` closes the loop: after a commit it reads the freshly
written dataset back through the SAME planner machinery the read path
uses (``pushdown.StatsIndex`` footers, ``readahead.coalesce_ranges``)
and reports predicted prune/coalesce quality — so a layout regression is
caught at write time, not discovered as a slow epoch a week later
(docs/troubleshoot.md "My freshly written dataset reads
full-scan-priced").
"""

import logging

from petastorm_tpu import readahead
from petastorm_tpu.etl.dataset_metadata import (
    DEFAULT_ROW_GROUP_SIZE_MB, ParquetDatasetInfo, load_row_groups,
)
from petastorm_tpu.telemetry import knobs

logger = logging.getLogger(__name__)

_MB = 1024 * 1024

#: coalesce quality floor the self-check warns under: at least this
#: share of a row-group's coalesced reads should fit one readahead window
_FITS_WINDOW_FLOOR = 0.8
#: prune quality floor for sorted datasets: a mid-range point predicate
#: on the sort key should prune at least this share of row-groups
_PRUNE_SHARE_FLOOR = 0.5


def target_rowgroup_bytes():
    """The write plane's row-group byte target.

    ``PETASTORM_TPU_WRITE_ROWGROUP_MB`` when set; otherwise the smaller
    of the classic 32 MB parquet block and the readahead max-range
    window — a row-group bigger than the window can never be fetched as
    one coalesced read, so exceeding it buys nothing and costs request
    fan-out."""
    configured = knobs.get_int('PETASTORM_TPU_WRITE_ROWGROUP_MB', 0, floor=0)
    if configured:
        return configured * _MB
    return min(DEFAULT_ROW_GROUP_SIZE_MB * _MB, readahead.max_range_bytes())


def _overlaps(lo, hi, value):
    try:
        return lo <= value <= hi
    except TypeError:  # cross-type stats (bytes vs int): keep, like pushdown
        return True


def self_check(dataset_url_or_info, sort_key=None, storage_options=None):
    """Layout quality report for a dataset, via the read path's own
    planners. Pure analysis — reads footers only, never data pages.

    Returns a dict::

        {'files': N, 'row_groups': N,
         'stats_coverage': share of row-groups with min/max stats,
         'predicted_prune_share': share prunable by a mid-range point
                                  predicate on sort_key (None without one),
         'sort_key': the checked key or None,
         'coalesce': {'raw_ranges': N, 'coalesced_ranges': N,
                      'ratio': raw/coalesced, 'mean_range_bytes': B,
                      'fits_window_share': share of coalesced reads that
                                           fit one readahead window},
         'warnings': [human-readable strings]}
    """
    from petastorm_tpu.pushdown import StatsIndex

    info = (dataset_url_or_info
            if isinstance(dataset_url_or_info, ParquetDatasetInfo)
            else ParquetDatasetInfo(dataset_url_or_info, storage_options))
    pieces = load_row_groups(info)
    index = StatsIndex(info)
    index.prefetch({p.path for p in pieces})

    gap = readahead.gap_bytes()
    window = readahead.max_range_bytes()

    with_stats = 0
    key_ranges = []
    raw_ranges = 0
    coalesced = []
    for piece in pieces:
        got = index.get(piece.path, piece.row_group)
        if got is not None and got[0]:
            with_stats += 1
            if sort_key is not None and sort_key in got[0]:
                lo, hi, _ = got[0][sort_key]
                key_ranges.append((lo, hi))
        ranges = index.get_ranges(piece.path, piece.row_group)
        if ranges:
            chunks = sorted(r for per_col in ranges.values()
                            for r in per_col)
            raw_ranges += len(chunks)
            coalesced.extend(readahead.coalesce_ranges(chunks, gap, window))

    total = len(pieces)
    report = {
        'files': len(info.file_paths),
        'row_groups': total,
        'stats_coverage': (with_stats / total) if total else 0.0,
        'sort_key': sort_key,
        'predicted_prune_share': None,
        'coalesce': None,
        'warnings': [],
    }

    if coalesced:
        lengths = [length for _, length in coalesced]
        report['coalesce'] = {
            'raw_ranges': raw_ranges,
            'coalesced_ranges': len(coalesced),
            'ratio': raw_ranges / len(coalesced),
            'mean_range_bytes': int(sum(lengths) / len(lengths)),
            'fits_window_share': (sum(1 for n in lengths if n <= window)
                                  / len(lengths)),
        }

    if sort_key is not None and key_ranges and total:
        # Probe predicate: a point lookup at the median of the key span.
        # On a well-sorted layout each value lands in ~one row-group, so
        # the prunable share approaches (total-1)/total; heavy overlap
        # between row-group [min,max] spans is exactly what kills
        # pushdown on real predicates.
        lows = sorted(lo for lo, _ in key_ranges)
        probe = lows[len(lows) // 2]
        kept = sum(1 for lo, hi in key_ranges if _overlaps(lo, hi, probe))
        kept += total - len(key_ranges)  # stat-less row-groups: never pruned
        report['predicted_prune_share'] = 1.0 - kept / total

    _warn(report, total)
    return report


def _warn(report, total):
    """Attach runbook-keyed warnings (docs/troubleshoot.md) in place."""
    warnings = report['warnings']
    if total and report['stats_coverage'] < 1.0:
        warnings.append(
            'footer statistics missing on %.0f%% of row-groups — pushdown '
            'will decline with no-statistics; rewrite with '
            'write_statistics=True (DatasetWriter default)'
            % (100 * (1 - report['stats_coverage'])))
    prune = report['predicted_prune_share']
    if prune is not None and total > 2 and prune < _PRUNE_SHARE_FLOOR:
        warnings.append(
            'sort key %r prunes only %.0f%% of row-groups on a point '
            'probe — row-group key spans overlap; feed rows in sorted '
            'order or re-shard with compact_dataset(sort_key=...)'
            % (report['sort_key'], 100 * prune))
    co = report['coalesce']
    if co is not None and co['fits_window_share'] < _FITS_WINDOW_FLOOR:
        warnings.append(
            'only %.0f%% of coalesced reads fit one readahead window — '
            'row-groups are larger than PETASTORM_TPU_READAHEAD_MAX_RANGE_MB; '
            'lower PETASTORM_TPU_WRITE_ROWGROUP_MB toward the window'
            % (100 * co['fits_window_share']))
    for message in warnings:
        logger.warning('write layout self-check: %s', message)
