"""Bounded-staleness append reads: follow a growing dataset.

The append write mode (``DistributedDatasetWriter(append=True)``) stacks
monotonic manifest generations; this module is the read side of that
contract — the surface the online-training family (event streams, RL
replay buffers) consumes:

* :class:`AppendFollower` polls the committed manifest at
  ``max_staleness_s / 2`` and yields batches from every part file it has
  not delivered yet. The staleness bound is end-to-end: a row committed
  at time T is yielded no later than T + ``max_staleness_s`` (plus the
  read itself).
* Compaction-aware: a ``source='compact'`` entry whose ``replaces`` were
  all already delivered is *skipped* — its rows already flowed through
  the old files, and redelivering them would break exactly-once. A
  folded entry covering never-seen sources is delivered whole. A fold
  that MIXES delivered and undelivered sources (compaction groups small
  files across generations) is not delivered either way: the follower
  reads the still-on-disk undelivered source files directly (superseded
  files survive until ``gc_superseded``'s grace window passes), so
  delivery stays file-granular and multiset-exact.
"""

import logging
import posixpath
import threading
import time

from petastorm_tpu.fs import get_filesystem_and_path_or_paths, normalize_dir_url
from petastorm_tpu.telemetry import get_registry, metrics_disabled
from petastorm_tpu.write import manifest

logger = logging.getLogger(__name__)

#: observed commit-to-delivery lag at each follower poll: the committed
#: manifest's age while undelivered rows exist, 0 once caught up — the
#: measurable form of the bounded-staleness contract (and the
#: ``append_staleness`` SLO target's input, telemetry/slo.py)
APPEND_STALENESS = 'petastorm_tpu_append_staleness_s'


class AppendFollower:
    """Iterator of row-batches over a manifest dataset that keeps
    picking up newly committed generations.

    ``for batch in AppendFollower(url, max_staleness_s=5): ...`` yields
    the namedtuple batches of :func:`~petastorm_tpu.reader
    .make_batch_reader`, file-set by file-set. ``stop()`` (or exhausting
    ``max_generations``) ends the iteration; between generations the
    follower sleeps in poll steps, never holding a reader open.
    """

    def __init__(self, dataset_url, max_staleness_s=5.0, reader_factory=None,
                 storage_options=None, stop_after_idle_s=None):
        """``reader_factory(file_urls)`` -> context-manager reader
        (defaults to :func:`make_batch_reader` with one epoch and stable
        order); ``stop_after_idle_s`` ends iteration after that long
        with no new commits (None = follow forever until ``stop()``)."""
        self._url = normalize_dir_url(dataset_url)
        self._storage_options = storage_options
        self.max_staleness_s = float(max_staleness_s)
        self._poll_s = max(0.05, self.max_staleness_s / 2.0)
        self._stop_after_idle_s = stop_after_idle_s
        self._reader_factory = reader_factory or self._default_reader
        self.fs, self.root_path = get_filesystem_and_path_or_paths(
            self._url, storage_options)
        self._delivered = set()
        self._stop = threading.Event()
        self.generation = 0  #: latest generation this follower consumed

    def _default_reader(self, file_urls):
        from petastorm_tpu.reader import make_batch_reader
        return make_batch_reader(file_urls, shuffle_row_groups=False,
                                 num_epochs=1,
                                 storage_options=self._storage_options)

    def stop(self):
        self._stop.set()

    def _fresh_entries(self):
        """Undelivered manifest entries of the latest committed
        generation, compact-fold redelivery filtered out. A fold that
        mixes delivered and undelivered sources comes back as pseudo
        entries for the undelivered SOURCE files (read directly off
        disk), never the fold itself — delivering the fold would
        redeliver the consumed part and break exactly-once."""
        committed = manifest.load(self.fs, self.root_path)
        if committed is None or committed['generation'] <= self.generation:
            return None
        fresh = []
        for entry in committed['files']:
            if entry['path'] in self._delivered:
                continue
            replaces = entry.get('replaces') or []
            undelivered = [p for p in replaces if p not in self._delivered]
            if replaces and not undelivered:
                # fold of fully-delivered sources: rows already flowed
                self._delivered.add(entry['path'])
                continue
            if replaces and len(undelivered) < len(replaces):
                on_disk = [p for p in undelivered if self._on_disk(p)]
                if len(on_disk) == len(undelivered):
                    # ``settles`` marks the fold delivered once its last
                    # undelivered source has been read
                    fresh.extend({'path': p, 'settles': entry['path']}
                                 for p in undelivered)
                    continue
                logger.warning(
                    'append follower: fold %r mixes delivered and '
                    'undelivered sources but %d source file(s) are already '
                    'garbage-collected; delivering the whole fold (bounded '
                    'redelivery — keep the gc grace window above the '
                    'follower poll interval to avoid this)',
                    entry['path'], len(undelivered) - len(on_disk))
            fresh.append(entry)
        self.generation = committed['generation']
        return fresh

    def _note_staleness(self, pending):
        """Publish the observed lag: the committed manifest's age while
        undelivered rows are pending, zero once this follower caught up.
        Advisory — a filesystem hiccup degrades to no update."""
        if metrics_disabled():
            return
        lag = 0.0
        if pending:
            lag = manifest.staleness_s(self.fs, self.root_path) or 0.0
        get_registry().gauge(APPEND_STALENESS).set(round(lag, 3))

    def _on_disk(self, rel_path):
        try:
            return self.fs.exists(posixpath.join(self.root_path, rel_path))
        except (OSError, ValueError):
            return False

    def _mark_delivered(self, fresh):
        """Record a read batch of entries as delivered — the entries
        themselves, every source file they folded (those rows flowed
        through the fold), and any fold a direct source read settles."""
        for entry in fresh:
            self._delivered.add(entry['path'])
            for p in entry.get('replaces') or []:
                self._delivered.add(p)
            settles = entry.get('settles')
            if settles is not None:
                self._delivered.add(settles)

    def __iter__(self):
        idle_since = time.monotonic()
        while not self._stop.is_set():
            fresh = self._fresh_entries()
            self._note_staleness(bool(fresh))
            if fresh:
                idle_since = time.monotonic()
                urls = [self._url.rstrip('/') + '/' + e['path']
                        for e in fresh]
                with self._reader_factory(urls) as reader:
                    for batch in reader:
                        yield batch
                # delivery marked AFTER the read: a crash mid-read means
                # redelivery next iteration (at-least-once within one
                # follower restart; exactly-once within a live follower)
                self._mark_delivered(fresh)
                continue
            if (self._stop_after_idle_s is not None
                    and time.monotonic() - idle_since
                    >= self._stop_after_idle_s):
                return
            self._stop.wait(self._poll_s)


def follow_dataset(dataset_url, max_staleness_s=5.0, **kwargs):
    """Convenience: iterate a growing dataset with a staleness bound."""
    return iter(AppendFollower(dataset_url, max_staleness_s=max_staleness_s,
                               **kwargs))
